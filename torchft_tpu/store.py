"""Key-value store — TCPStore analogue for rendezvous and bootstrap.

The reference leans on torch's TCPStore for (a) publishing the manager
address to the replica group (torchft/manager.py:176-212) and (b) epoch-
scoped process-group rendezvous with a ``host:port/prefix`` convention
(torchft/process_group.py:85-103). This module provides the same two roles
on top of the C++ KvStore server (native/coord.cc).

Address convention: ``host:port[/prefix]`` — prefixes nest, and quorum
epochs use ``{store}/torchft/{quorum_id}/{rank}`` exactly like the
reference (torchft/manager.py:472).
"""

from __future__ import annotations

from datetime import timedelta
from typing import List, Optional

from torchft_tpu import _native

__all__ = ["StoreServer", "StoreClient", "create_store_client"]


class StoreServer:
    """In-process KV store server (C++, native/coord.cc KvStore)."""

    def __init__(self, bind: str = "[::]:0") -> None:
        self._handle, self._address = _native.store_create(bind)

    def address(self) -> str:
        """``host:port`` of this store."""
        return self._address

    @property
    def port(self) -> int:
        return int(self._address.rsplit(":", 1)[1])

    def shutdown(self) -> None:
        if self._handle:
            _native.store_shutdown(self._handle)
            self._handle = 0

    def __del__(self) -> None:
        try:
            self.shutdown()
        except Exception:
            pass


class StoreClient:
    """Client for a StoreServer with key-prefix scoping."""

    def __init__(
        self,
        addr: str,
        prefix: str = "",
        connect_timeout: timedelta = timedelta(seconds=60),
        default_timeout: timedelta = timedelta(seconds=60),
    ) -> None:
        self._client = _native.NativeClient(
            addr if "://" in addr else f"tft://{addr}",
            int(connect_timeout.total_seconds() * 1000),
        )
        self._prefix = prefix
        self._default_timeout = default_timeout

    def _k(self, key: str) -> str:
        return f"{self._prefix}{key}"

    def _ms(self, timeout: Optional[timedelta]) -> int:
        t = timeout or self._default_timeout
        return max(1, int(t.total_seconds() * 1000))

    def set(self, key: str, value: bytes | str) -> None:
        if isinstance(value, str):
            value = value.encode()
        self._client.call("store.set", {"k": self._k(key), "v": value}, self._ms(None))

    def get(self, key: str, timeout: Optional[timedelta] = None, wait: bool = True) -> bytes:
        resp = self._client.call(
            "store.get", {"k": self._k(key), "wait": wait}, self._ms(timeout)
        )
        return resp["v"]

    def add(self, key: str, delta: int = 1) -> int:
        resp = self._client.call(
            "store.add", {"k": self._k(key), "delta": delta}, self._ms(None)
        )
        return resp["v"]

    def delete(self, key: str) -> None:
        self._client.call("store.del", {"k": self._k(key)}, self._ms(None))

    def keys(self, prefix: str = "") -> List[str]:
        resp = self._client.call(
            "store.keys", {"prefix": self._k(prefix)}, self._ms(None)
        )
        return resp["keys"]

    def with_prefix(self, prefix: str) -> "StoreClient":
        """A view of the same store under an extended prefix (PrefixStore
        analogue). Shares the underlying connection."""
        out = StoreClient.__new__(StoreClient)
        out._client = self._client
        out._prefix = f"{self._prefix}{prefix}"
        out._default_timeout = self._default_timeout
        return out

    def close(self) -> None:
        self._client.close()


def create_store_client(
    store_addr: str, connect_timeout: timedelta = timedelta(seconds=60)
) -> StoreClient:
    """Parse ``host:port[/prefix]`` into a prefixed client
    (torchft/process_group.py:85-103 analogue; trailing '/' appended so key
    joins are unambiguous)."""
    if "/" in store_addr:
        hostport, prefix = store_addr.split("/", 1)
        prefix = prefix.rstrip("/") + "/"
    else:
        hostport, prefix = store_addr, ""
    return StoreClient(hostport, prefix=prefix, connect_timeout=connect_timeout)
