"""Readers-writer lock with timeouts.

Gates checkpoint serving: the writer is held while checkpoints are
disallowed, so a healing replica's GET blocks until ``send_checkpoint``
stages fresh state (reference: torchft/checkpointing/_rwlock.py:42-132,
used at http_transport.py:181-202). Writer-preference is not needed —
there is exactly one writer (the manager thread) and it must win promptly,
which the ``_want_write`` gate provides.
"""

from __future__ import annotations

import threading
from typing import Iterator, Optional


class RWLock:
    """Many readers / one writer, every acquire bounded by ``timeout``."""

    def __init__(self, timeout: Optional[float] = None) -> None:
        self._timeout = timeout
        self._cond = threading.Condition()
        self._readers = 0  # guarded-by: _cond
        self._writer = False  # guarded-by: _cond
        self._want_write = 0  # pending writers block new readers; guarded-by: _cond

    def _wait(self, predicate, timeout: Optional[float] = None) -> None:
        timeout = self._timeout if timeout is None else timeout
        ok = self._cond.wait_for(predicate, timeout=timeout)
        if not ok:
            raise TimeoutError(f"rwlock acquire timed out after {timeout}s")

    def r_acquire(self, timeout: Optional[float] = None) -> None:
        """``timeout`` overrides the lock-wide default for this acquire —
        the heal metadata endpoints use a short bound so a healer probing
        a source that will NEVER stage this round (e.g. one whose quorum
        ran allow_heal=False) fails fast instead of burning the full
        transfer timeout (docs/heal_plane.md)."""
        with self._cond:
            self._wait(
                lambda: not self._writer and self._want_write == 0,
                timeout=timeout,
            )
            self._readers += 1

    def r_release(self) -> None:
        with self._cond:
            assert self._readers > 0
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def w_acquire(self) -> None:
        with self._cond:
            self._want_write += 1
            try:
                self._wait(lambda: not self._writer and self._readers == 0)
            except BaseException:
                self._want_write -= 1
                if self._want_write == 0:
                    # readers block on _want_write == 0; wake them or they
                    # stall until their own timeout after a writer gives up
                    self._cond.notify_all()
                raise
            self._want_write -= 1
            self._writer = True

    def w_release(self) -> None:
        with self._cond:
            assert self._writer
            self._writer = False
            self._cond.notify_all()

    def w_locked(self) -> bool:
        with self._cond:
            return self._writer

    class _Guard:
        def __init__(self, acquire, release) -> None:
            self._acquire, self._release = acquire, release

        def __enter__(self) -> None:
            self._acquire()

        def __exit__(self, *exc) -> None:
            self._release()

    def read_lock(self) -> "_Guard":
        return RWLock._Guard(self.r_acquire, self.r_release)

    def write_lock(self) -> "_Guard":
        return RWLock._Guard(self.w_acquire, self.w_release)
