"""Deterministic heal-stripe planning (docs/heal_plane.md).

The striped multi-source heal treats the flattened state tree as ONE
logical byte stream (header excluded — it rides the control plane) and
partitions it into byte-balanced ranges served by different live peers.
Because the unit is a *byte range* of the concatenation, not a whole
leaf, the plan is balanced to the alignment quantum by construction —
the old chunk assignment (:func:`assign_chunk_groups`, greedy LPT over
whole buffers) can still leave one chunk carrying most of the bytes when
a single large leaf (an embedding table, a fused optimizer moment)
dominates the tree, and the heal tail is gated by the slowest stripe.

Both sides derive the same plan from the same inputs (total size, source
count, knobs), so no stripe coordination rides the wire: the healer puts
the concrete ``(offset, len)`` in each range request and any source can
serve any range.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Sequence, Tuple

import numpy as np

__all__ = [
    "stripe_ranges",
    "slice_buffers",
    "assign_chunk_groups",
    "heal_sources_limit",
    "heal_stripes_per_source",
]

# align range boundaries down to this quantum so fetches land on cache-
# friendly offsets; the tail range absorbs the remainder
_ALIGN = 64


def heal_sources_limit() -> int:
    """Max peers a healer stripes over (``TORCHFT_HEAL_SOURCES``, default
    4; 1 disables multi-source)."""
    try:
        return max(1, int(os.environ.get("TORCHFT_HEAL_SOURCES", "4")))
    except ValueError:
        return 4


def heal_stripes_per_source() -> int:
    """Ranges per source (``TORCHFT_HEAL_STRIPES``, default 2): more
    ranges than sources keeps the tail short and makes re-striping after
    a source death cheap (only the dead source's pending ranges move)."""
    try:
        return max(1, int(os.environ.get("TORCHFT_HEAL_STRIPES", "2")))
    except ValueError:
        return 2


def stripe_ranges(total_bytes: int, n: int) -> List[Tuple[int, int]]:
    """Partition ``[0, total_bytes)`` into ``n`` contiguous byte ranges,
    balanced to within the alignment quantum (the tail absorbs the
    remainder). Deterministic; empty ranges are dropped (tiny blobs may
    yield fewer than ``n``)."""
    if total_bytes <= 0:
        return []
    n = max(1, n)
    bounds = [((total_bytes * i // n) // _ALIGN) * _ALIGN for i in range(n)]
    bounds.append(total_bytes)
    out: List[Tuple[int, int]] = []
    for i in range(n):
        length = bounds[i + 1] - bounds[i]
        if length > 0:
            out.append((bounds[i], length))
    return out


def slice_buffers(
    buffers: Sequence[np.ndarray],
    sizes: Sequence[int],
    offset: int,
    length: int,
) -> Iterator[memoryview]:
    """Yield the byte slices of the logical buffer concatenation covering
    ``[offset, offset+length)`` — the HTTP serving side of a range request
    (the native blob server walks the same layout in C++). ``sizes[i]``
    must be ``buffers[i]``'s byte length."""
    from torchft_tpu.checkpointing.serialization import as_bytes

    pos = 0
    remaining = length
    for buf, size in zip(buffers, sizes):
        if remaining <= 0:
            return
        end = pos + size
        if end > offset and size > 0:
            lo = max(0, offset - pos)
            hi = min(size, offset + length - pos)
            if hi > lo:
                yield as_bytes(buf)[lo:hi]
                remaining -= hi - lo
        pos = end


def assign_chunk_groups(sizes: List[int], num_chunks: int) -> List[List[int]]:
    """Greedy LPT size-balanced assignment of whole-buffer indices to
    chunks — the legacy ``num_chunks`` HTTP mode's grouping (kept for the
    chunked endpoint; the striped heal path uses :func:`stripe_ranges`,
    which splits large leaves across stripes and balances exactly)."""
    order = sorted(range(len(sizes)), key=lambda i: -sizes[i])
    totals = [0] * num_chunks
    groups: List[List[int]] = [[] for _ in range(num_chunks)]
    for i in order:
        c = totals.index(min(totals))
        groups[c].append(i)
        totals[c] += sizes[i]
    for g in groups:
        g.sort()  # stream each chunk's buffers in deterministic order
    return groups
