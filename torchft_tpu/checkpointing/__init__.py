"""Live checkpoint recovery — peer-to-peer weight transfer at quorum time.

The reference layer is torchft/checkpointing/ (transport ABC + HTTP and
ProcessGroup transports). Here state dicts are JAX pytrees (arrays +
arbitrary leaves) streamed as raw host buffers:

* :class:`HTTPTransport` — in-process HTTP server; healing replicas GET
  ``/checkpoint/{step}/full`` (or metadata + parallel chunks).
* :class:`CollectivesTransport` — rides the reconfigurable data plane's
  send/recv (the PGTransport analogue).
* :class:`DiskCheckpointer` — the user-owned *periodic* checkpoint the
  reference documents but leaves to the application (manager.py:83-85):
  step-tagged atomic snapshots with retention + restore-latest.
"""

from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.checkpointing.collectives_transport import CollectivesTransport
from torchft_tpu.checkpointing.disk import DiskCheckpointer
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.checkpointing.serialization import (
    ShardedArray,
    flatten_state,
    from_transfer_tree,
    load_state,
    save_state,
    unflatten_state,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport

__all__ = [
    "CheckpointTransport",
    "HTTPTransport",
    "CollectivesTransport",
    "DiskCheckpointer",
    "RWLock",
    "ShardedArray",
    "flatten_state",
    "unflatten_state",
    "from_transfer_tree",
    "save_state",
    "load_state",
]
