"""Differential heal — changed-leaf checkpoint deltas (docs/heal_plane.md).

A replica that was absent for a few steps usually still holds a bit-exact
copy of the committed state at its last committed step (the commit
protocol's cross-group bit-identity invariant — every committed step's
state is identical on every group, proven end-to-end by the fault
matrix). Shipping the whole tree again is waste: the serving side keeps a
bounded **commit trail** of per-leaf digests at recent committed steps,
and a healer that reports ``(last_step, tree_digest)`` receives only the
leaves whose digest changed since — falling back to a full heal when the
trail has no entry for that step (absence past the horizon), when the
digests disagree (the healer's copy is not the committed lineage), or
when the leaf layout changed.

Safety is digest-anchored end to end: a delta is only built when the
healer's whole-tree digest at ``last_step`` matches the trail's, and an
unchanged leaf is kept from the healer's own buffers only because its
digest matches the server's — a mismatch anywhere degrades to the full
path rather than risking a silently mixed state.

Wire shape of a delta response (one body)::

    u64 manifest_len | pickle(manifest) | changed raw buffers...

with ``manifest = {"mode": "delta", "header": bytes, "changed": [idx...],
"sizes": [nbytes...]}`` or ``{"mode": "full"}`` (no payload) when the
server declines.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import struct
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from torchft_tpu.checkpointing.serialization import as_bytes

__all__ = [
    "leaf_digests",
    "tree_digest",
    "CommitTrail",
    "diff_enabled",
    "trail_horizon",
    "build_delta",
    "apply_delta",
    "pack_delta",
    "unpack_delta",
]

_LEN = struct.Struct("<Q")


def diff_enabled() -> bool:
    """``TORCHFT_HEAL_DIFF=1`` opts into differential heal. Off by
    default: the trail costs one state flatten + digest per committed
    step on the serving side (see docs/heal_plane.md for when that is
    worth it)."""
    return os.environ.get("TORCHFT_HEAL_DIFF", "0") == "1"


def trail_horizon() -> int:
    """Trail depth in committed steps (``TORCHFT_HEAL_TRAIL``, default
    8): absences older than this fall back to a full heal."""
    try:
        return max(1, int(os.environ.get("TORCHFT_HEAL_TRAIL", "8")))
    except ValueError:
        return 8


def leaf_digests(buffers: Sequence[np.ndarray]) -> List[str]:
    """Per-buffer content digest (blake2b-64bit — cryptographic-family,
    so a delta never mis-skips a changed leaf the way a short checksum
    eventually would)."""
    out: List[str] = []
    for buf in buffers:
        h = hashlib.blake2b(digest_size=8)
        h.update(as_bytes(buf))
        out.append(h.hexdigest())
    return out


def tree_digest(digests: Sequence[str]) -> str:
    """Whole-tree digest over the ordered per-buffer digests.

    Deliberately does NOT hash the header pickle: pickle is not a
    canonical encoding (its id-based memoization makes a freshly-built
    tree and a heal-round-tripped tree with IDENTICAL structure and
    bytes serialize to different header lengths — found the hard way
    when a once-healed survivor was excluded from every stripe plan),
    and buffer identity is the property both consumers actually need —
    stripes move only buffer bytes, and the delta path always adopts the
    SERVER's header while reusing digest-matched healer buffers."""
    h = hashlib.blake2b(digest_size=8)
    for d in digests:
        h.update(d.encode())
    return h.hexdigest()


class CommitTrail:
    """Bounded per-leaf digest trail over recent committed steps.

    Thread-safe: the main thread records at step boundaries while the
    quorum/HTTP serving threads look entries up mid-heal (the staged
    buffers themselves are guarded by the transport's RWLock; this trail
    only carries digests)."""

    def __init__(self, horizon: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._horizon = horizon if horizon is not None else trail_horizon()
        # step -> {"tree": str, "leaves": [str...], "sizes": [int...]}
        self._entries: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()

    def record(
        self,
        step: int,
        buffers: Sequence[np.ndarray],
        digests: Optional[List[str]] = None,
    ) -> List[str]:
        """Record (or return the existing) digests for ``step``; evicts
        entries past the horizon. Returns the per-leaf digests."""
        with self._lock:
            ent = self._entries.get(step)
            if ent is not None:
                return list(ent["leaves"])
        leaves = digests if digests is not None else leaf_digests(buffers)
        ent = {
            "tree": tree_digest(leaves),
            "leaves": leaves,
            "sizes": [int(b.nbytes) for b in buffers],
        }
        with self._lock:
            self._entries[step] = ent
            self._entries.move_to_end(step)
            while len(self._entries) > self._horizon:
                self._entries.popitem(last=False)
        return list(leaves)

    def get(self, step: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            ent = self._entries.get(step)
            return None if ent is None else dict(ent)

    def steps(self) -> List[int]:
        with self._lock:
            return list(self._entries)


def build_delta(
    header: bytes,
    buffers: Sequence[np.ndarray],
    staged_digests: Sequence[str],
    trail_entry: Optional[Dict[str, Any]],
    healer_tree_digest: str,
) -> Optional[Tuple[Dict[str, Any], List[np.ndarray]]]:
    """Server side: the delta manifest + changed buffers for a healer at
    the trail step described by ``trail_entry``, or ``None`` when only a
    full heal is sound (no trail entry, tree-digest mismatch, or leaf
    count drift)."""
    if trail_entry is None:
        return None
    if trail_entry["tree"] != healer_tree_digest:
        return None
    then: List[str] = trail_entry["leaves"]
    if len(then) != len(staged_digests) or len(then) != len(buffers):
        return None
    changed = [
        i for i, (a, b) in enumerate(zip(then, staged_digests)) if a != b
    ]
    manifest = {
        "mode": "delta",
        "header": header,
        "changed": changed,
        "sizes": [int(buffers[i].nbytes) for i in changed],
    }
    return manifest, [buffers[i] for i in changed]


def pack_delta(
    manifest: Dict[str, Any], changed: Sequence[np.ndarray]
) -> List[bytes]:
    """Serialize a delta (or a bare ``{"mode": "full"}`` refusal) into
    response body parts."""
    blob = pickle.dumps(manifest)
    out: List[bytes] = [_LEN.pack(len(blob)), blob]
    out.extend(bytes(as_bytes(b)) for b in changed)
    return out


def unpack_delta(body: bytes) -> Tuple[Dict[str, Any], bytes]:
    """Split a response body into (manifest, payload bytes)."""
    (n,) = _LEN.unpack_from(body, 0)
    manifest = pickle.loads(body[_LEN.size : _LEN.size + n])
    return manifest, body[_LEN.size + int(n) :]


def apply_delta(
    manifest: Dict[str, Any],
    payload: bytes,
    own_buffers: Sequence[np.ndarray],
) -> Tuple[bytes, List[np.ndarray]]:
    """Healer side: combine the delta's changed buffers with the healer's
    own (digest-matched) buffers into the full ``(header, buffers)`` the
    normal unflatten path consumes. Raises ``ValueError`` on any layout
    inconsistency — the caller falls back to a full heal."""
    header: bytes = manifest["header"]
    changed: List[int] = list(manifest["changed"])
    sizes: List[int] = list(manifest["sizes"])
    if len(changed) != len(sizes):
        raise ValueError("delta manifest: changed/sizes length mismatch")
    total = sum(sizes)
    if len(payload) != total:
        raise ValueError(
            f"delta payload truncated: {len(payload)} != {total}"
        )
    buffers: List[np.ndarray] = [
        np.frombuffer(as_bytes(b), dtype=np.uint8) for b in own_buffers
    ]
    off = 0
    for idx, nbytes in zip(changed, sizes):
        if idx >= len(buffers):
            raise ValueError(f"delta manifest: leaf index {idx} out of range")
        buffers[idx] = np.frombuffer(
            payload, dtype=np.uint8, count=nbytes, offset=off
        )
        off += nbytes
    return header, buffers
