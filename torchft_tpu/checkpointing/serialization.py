"""Streaming pytree (de)serialization for checkpoint transfer.

The reference streams torch state dicts with
``torch.distributed._serialization`` after a pytree flatten
(torchft/checkpointing/http_transport.py:219-241, _serialization.py:8-33).
The JAX equivalent: ``jax.tree_util`` flattens the state into leaves; array
leaves (``jax.Array`` / ``np.ndarray`` / scalars) travel as raw host
buffers described by a small pickled header, everything else is pickled
whole. Device arrays are pulled to host at flatten time — on multi-host
deployments each process serializes its addressable shards, and placement
back onto the mesh is the loader's job (the ``NamedSharding`` analogue of
the reference's DTensor-spec handling, pg_transport.py:104-114).

Wire layout::

    u64 header_len | pickle((treedef, leaf_infos)) | raw buffers...

where ``leaf_infos[i]`` is one of

* ``("arr", dtype_str, shape, nbytes)`` — dense array leaf (one buffer);
* ``("shards", dtype_str, global_shape, mesh_desc, spec_entries,
  [(index_desc, nbytes), ...])`` — a sharded ``jax.Array`` leaf shipped
  **per shard** (one buffer per distinct shard): the NamedSharding
  analogue of the reference's DTensor-spec transfer
  (pg_transport.py:104-114, 217-247). Only this process's addressable
  shards travel, deduplicated by shard index (replicated copies ship
  once), so a sharded group never gathers the full model onto one host
  and multi-host groups each contribute their own shards. The receiver
  gets a :class:`ShardedArray` placeholder and rebuilds the device array
  on its own congruent mesh via :func:`from_transfer_tree`;
* ``("obj", pickled_bytes)`` — non-array leaf (inline, no buffer).
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, BinaryIO, List, Tuple

import numpy as np

_LEN = struct.Struct("<Q")

__all__ = [
    "flatten_state",
    "unflatten_state",
    "save_state",
    "load_state",
    "buffer_sizes",
    "ShardedArray",
    "from_transfer_tree",
    "ArraySpec",
    "spec_tree_from_header",
]


def _tree_util():
    # Imported lazily so the coordination/data-plane layers stay importable
    # on hosts without jax (e.g. a CPU-only lighthouse box).
    import jax

    return jax.tree_util


def _is_array(leaf: Any) -> bool:
    if isinstance(leaf, np.ndarray):
        return True
    try:
        import jax

        return isinstance(leaf, jax.Array)
    except Exception:
        return False


def _to_host(leaf: Any, copy: bool = False) -> np.ndarray:
    arr = np.ascontiguousarray(np.asarray(leaf))
    if copy and (arr is leaf or not arr.flags.owndata):
        # ascontiguousarray returns the SAME object for already-contiguous
        # numpy inputs, and np.asarray of a CPU jax.Array can be a zero-copy
        # view over the XLA buffer — either way the "snapshot" would alias
        # live storage. Callers that need a true backup (LocalSGD/DiLoCo
        # rollback) pass copy=True to force ownership.
        arr = arr.copy()
    return arr


def to_host_tree(tree: Any, copy: bool = False) -> Any:
    """Pull every array leaf of a pytree to a contiguous host buffer (the
    shared device→host step used by gradient averaging, LocalSGD backups and
    checkpoint staging). With ``copy=True`` every leaf is guaranteed to own
    its buffer (no aliasing of the input), which backup/rollback paths
    require."""
    return _tree_util().tree_map(lambda l: _to_host(l, copy=copy), tree)


def as_bytes(arr: np.ndarray) -> memoryview:
    """Byte view that also works for ml_dtypes arrays (bfloat16 etc.), whose
    buffers plain ``memoryview(...)`` rejects."""
    return memoryview(np.ascontiguousarray(arr).reshape(-1).view(np.uint8))


def _dtype_name(dtype: np.dtype) -> str:
    # dtype.name (not .str): ml_dtypes report '<V2'-style .str which does not
    # round-trip through np.dtype(); names like 'bfloat16' do once ml_dtypes
    # is imported (jax always imports it).
    return dtype.name


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # noqa: F401 — registers bfloat16/fp8 names

        return np.dtype(name)


# ---------------------------------------------------------------------------
# sharded leaves (NamedSharding descriptor — the DTensor-spec analogue)
# ---------------------------------------------------------------------------


def _index_desc(index: Tuple, shape: Tuple[int, ...]) -> Tuple:
    """Canonicalize a shard's index (tuple of slices) into nested
    ``(start, stop)`` pairs that pickle cleanly and compare by value."""
    out = []
    for sl, n in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = n if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _sharding_desc(arr) -> Any:
    """``(axis_names, mesh_shape, spec_entries)`` for a NamedSharding-ed
    jax.Array spanning >1 device, else None (dense path)."""
    from jax.sharding import NamedSharding

    s = getattr(arr, "sharding", None)
    if not isinstance(s, NamedSharding):
        return None
    if len(s.mesh.devices.flat) <= 1:
        return None
    return (
        tuple(s.mesh.axis_names),
        tuple(s.mesh.devices.shape),
        tuple(s.spec),
    )


class ShardedArray:
    """Host-side carrier for a sharded ``jax.Array`` in transit: global
    shape/dtype, the sender's mesh/spec descriptor, and its (deduplicated)
    addressable shards. Rebuild on the receiver with :meth:`to_jax` against
    a congruent local mesh, or assemble densely with :meth:`full`."""

    def __init__(
        self,
        dtype: np.dtype,
        shape: Tuple[int, ...],
        mesh_desc: Tuple,
        spec_entries: Tuple,
        shards: List[Tuple[Tuple, np.ndarray]],
    ) -> None:
        self.dtype = dtype
        self.shape = tuple(shape)
        self.mesh_desc = mesh_desc
        self.spec_entries = spec_entries
        self.shards = shards  # [(index_desc, host_array), ...]

    @property
    def nbytes(self) -> int:
        return sum(s.nbytes for _, s in self.shards)

    def to_jax(self, mesh):
        """Place the shards onto ``mesh`` with the sender's PartitionSpec.
        The mesh must be congruent (same axis names/sizes for the sharded
        axes); each local device receives exactly its shard — no dense
        intermediate, no cross-device gather."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        sharding = NamedSharding(mesh, PartitionSpec(*self.spec_entries))
        by_index = {idx: data for idx, data in self.shards}
        arrays = []
        for dev, index in sharding.addressable_devices_indices_map(
            self.shape
        ).items():
            data = by_index.get(_index_desc(index, self.shape))
            if data is None:
                raise ValueError(
                    f"missing shard {index} for leaf {self.shape}; sender "
                    f"mesh {self.mesh_desc} is not congruent with the local mesh"
                )
            arrays.append(jax.device_put(data, dev))
        return jax.make_array_from_single_device_arrays(
            self.shape, sharding, arrays
        )

    def full(self) -> np.ndarray:
        """Assemble a dense host array (fallback when no mesh is at hand —
        requires the sender's shards to cover the global array)."""
        out = np.empty(self.shape, dtype=self.dtype)
        # Boolean coverage mask, not a summed element count: overlapping
        # shards would double-count and mask uninitialized gaps (round-2
        # advisor finding).
        covered = np.zeros(self.shape, dtype=bool)
        for idx, data in self.shards:
            sl = tuple(slice(a, b) for a, b in idx)
            out[sl] = data
            covered[sl] = True
        if not covered.all():
            raise ValueError(
                "shards do not cover the array (multi-host sender); "
                "rebuild with to_jax(mesh) instead"
            )
        return out


def from_transfer_tree(tree: Any, mesh) -> Any:
    """Convert every :class:`ShardedArray` leaf back into a ``jax.Array``
    on ``mesh`` (the receiver-side half of the sharded transfer)."""
    tu = _tree_util()
    return tu.tree_map(
        lambda l: l.to_jax(mesh) if isinstance(l, ShardedArray) else l,
        tree,
        is_leaf=lambda l: isinstance(l, ShardedArray),
    )


def flatten_state(state: Any) -> Tuple[bytes, List[np.ndarray]]:
    """Flatten a pytree into ``(header_bytes, array_buffers)``."""
    leaves, treedef = _tree_util().tree_flatten(state)
    infos: List[Tuple] = []
    buffers: List[np.ndarray] = []
    for leaf in leaves:
        if _is_array(leaf):
            desc = _sharding_desc(leaf)
            if desc is not None:
                axis_names, mesh_shape, spec_entries = desc
                seen = {}
                for s in leaf.addressable_shards:
                    idx = _index_desc(s.index, leaf.shape)
                    if idx not in seen:  # replicas ship once
                        seen[idx] = _to_host(s.data)
                shard_meta = [(idx, a.nbytes) for idx, a in seen.items()]
                infos.append(
                    (
                        "shards",
                        _dtype_name(np.dtype(leaf.dtype)),
                        tuple(leaf.shape),
                        (axis_names, mesh_shape),
                        spec_entries,
                        shard_meta,
                    )
                )
                buffers.extend(seen.values())
            else:
                host = _to_host(leaf)
                infos.append(
                    ("arr", _dtype_name(host.dtype), host.shape, host.nbytes)
                )
                buffers.append(host)
        else:
            infos.append(("obj", pickle.dumps(leaf)))
    header = pickle.dumps((treedef, infos))
    return header, buffers


def buffer_sizes(infos: List[Tuple]) -> List[int]:
    """Byte size of every raw buffer that follows the header, in stream
    order (the transports' manifest for chunked / per-buffer transfer)."""
    sizes: List[int] = []
    for info in infos:
        if info[0] == "arr":
            sizes.append(info[3])
        elif info[0] == "shards":
            sizes.extend(n for _, n in info[5])
    return sizes


def unflatten_state(header: bytes, buffers: List[np.ndarray]) -> Any:
    """Inverse of :func:`flatten_state`. Sharded leaves come back as
    :class:`ShardedArray` placeholders — pass the tree through
    :func:`from_transfer_tree` (or call ``.full()``) to materialize."""
    treedef, infos = pickle.loads(header)
    leaves: List[Any] = []
    it = iter(buffers)
    for info in infos:
        if info[0] == "arr":
            _, dtype, shape, _ = info
            buf = next(it)
            leaves.append(np.frombuffer(buf, dtype=_resolve_dtype(dtype)).reshape(shape))
        elif info[0] == "shards":
            _, dtype, shape, mesh_desc, spec_entries, shard_meta = info
            np_dtype = _resolve_dtype(dtype)
            shards = []
            for idx, _nbytes in shard_meta:
                shard_shape = tuple(b - a for a, b in idx)
                shards.append(
                    (
                        idx,
                        np.frombuffer(next(it), dtype=np_dtype).reshape(shard_shape),
                    )
                )
            leaves.append(
                ShardedArray(np_dtype, shape, mesh_desc, spec_entries, shards)
            )
        else:
            leaves.append(pickle.loads(info[1]))
    return _tree_util().tree_unflatten(treedef, leaves)


class ArraySpec:
    """jax-free shape/dtype spec leaf (the ``jax.ShapeDtypeStruct``
    stand-in :func:`spec_tree_from_header` falls back to on hosts
    without jax)."""

    def __init__(self, shape: Tuple[int, ...], dtype: np.dtype) -> None:
        self.shape = tuple(shape)
        self.dtype = dtype

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return f"ArraySpec(shape={self.shape}, dtype={self.dtype})"


def spec_tree_from_header(header: bytes) -> Any:
    """Rebuild the transferred pytree's SHAPE — ``jax.ShapeDtypeStruct``
    leaves for arrays (global shape for sharded leaves), the actual
    objects for ``obj`` leaves — from a transfer header alone. This is
    what the heal/compile overlap consumes: the header arrives before any
    bulk bytes, so a healer can start jit compilation from these specs
    while the stripes stream (docs/heal_plane.md)."""
    treedef, infos = pickle.loads(header)
    try:
        import jax

        make = jax.ShapeDtypeStruct
    except Exception:  # noqa: BLE001 — jax-free hosts get the plain spec
        make = ArraySpec
    leaves: List[Any] = []
    for info in infos:
        if info[0] == "arr":
            _, dtype, shape, _ = info
            leaves.append(make(tuple(shape), _resolve_dtype(dtype)))
        elif info[0] == "shards":
            _, dtype, shape = info[0], info[1], info[2]
            leaves.append(make(tuple(shape), _resolve_dtype(dtype)))
        else:
            leaves.append(pickle.loads(info[1]))
    return _tree_util().tree_unflatten(treedef, leaves)


def save_state(state: Any, f: BinaryIO) -> None:
    """Stream a pytree to a file object."""
    header, buffers = flatten_state(state)
    f.write(_LEN.pack(len(header)))
    f.write(header)
    for buf in buffers:
        f.write(as_bytes(buf))


def load_state(f: BinaryIO) -> Any:
    """Inverse of :func:`save_state`."""
    (header_len,) = _LEN.unpack(f.read(_LEN.size))
    header = f.read(header_len)
    _, infos = pickle.loads(header)
    buffers: List[np.ndarray] = []
    for nbytes in buffer_sizes(infos):
        raw = f.read(nbytes)
        if len(raw) != nbytes:
            raise EOFError("truncated checkpoint stream")
        buffers.append(np.frombuffer(raw, dtype=np.uint8))
    return unflatten_state(header, buffers)


def dumps_state(state: Any) -> bytes:
    buf = io.BytesIO()
    save_state(state, buf)
    return buf.getvalue()


def loads_state(data: bytes) -> Any:
    return load_state(io.BytesIO(data))
