"""Streaming pytree (de)serialization for checkpoint transfer.

The reference streams torch state dicts with
``torch.distributed._serialization`` after a pytree flatten
(torchft/checkpointing/http_transport.py:219-241, _serialization.py:8-33).
The JAX equivalent: ``jax.tree_util`` flattens the state into leaves; array
leaves (``jax.Array`` / ``np.ndarray`` / scalars) travel as raw host
buffers described by a small pickled header, everything else is pickled
whole. Device arrays are pulled to host at flatten time — on multi-host
deployments each process serializes its addressable shards, and placement
back onto the mesh is the loader's job (the ``NamedSharding`` analogue of
the reference's DTensor-spec handling, pg_transport.py:104-114).

Wire layout::

    u64 header_len | pickle((treedef, leaf_infos)) | raw buffers...

where ``leaf_infos[i]`` is ``("arr", dtype_str, shape, nbytes)`` for array
leaves (buffer follows in order) or ``("obj", pickled_bytes)`` for
non-array leaves (inline, no buffer).
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, BinaryIO, List, Tuple

import numpy as np

_LEN = struct.Struct("<Q")

__all__ = ["flatten_state", "unflatten_state", "save_state", "load_state"]


def _tree_util():
    # Imported lazily so the coordination/data-plane layers stay importable
    # on hosts without jax (e.g. a CPU-only lighthouse box).
    import jax

    return jax.tree_util


def _is_array(leaf: Any) -> bool:
    if isinstance(leaf, np.ndarray):
        return True
    try:
        import jax

        return isinstance(leaf, jax.Array)
    except Exception:
        return False


def _to_host(leaf: Any, copy: bool = False) -> np.ndarray:
    arr = np.ascontiguousarray(np.asarray(leaf))
    if copy and (arr is leaf or not arr.flags.owndata):
        # ascontiguousarray returns the SAME object for already-contiguous
        # numpy inputs, and np.asarray of a CPU jax.Array can be a zero-copy
        # view over the XLA buffer — either way the "snapshot" would alias
        # live storage. Callers that need a true backup (LocalSGD/DiLoCo
        # rollback) pass copy=True to force ownership.
        arr = arr.copy()
    return arr


def to_host_tree(tree: Any, copy: bool = False) -> Any:
    """Pull every array leaf of a pytree to a contiguous host buffer (the
    shared device→host step used by gradient averaging, LocalSGD backups and
    checkpoint staging). With ``copy=True`` every leaf is guaranteed to own
    its buffer (no aliasing of the input), which backup/rollback paths
    require."""
    return _tree_util().tree_map(lambda l: _to_host(l, copy=copy), tree)


def as_bytes(arr: np.ndarray) -> memoryview:
    """Byte view that also works for ml_dtypes arrays (bfloat16 etc.), whose
    buffers plain ``memoryview(...)`` rejects."""
    return memoryview(np.ascontiguousarray(arr).reshape(-1).view(np.uint8))


def _dtype_name(dtype: np.dtype) -> str:
    # dtype.name (not .str): ml_dtypes report '<V2'-style .str which does not
    # round-trip through np.dtype(); names like 'bfloat16' do once ml_dtypes
    # is imported (jax always imports it).
    return dtype.name


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # noqa: F401 — registers bfloat16/fp8 names

        return np.dtype(name)


def flatten_state(state: Any) -> Tuple[bytes, List[np.ndarray]]:
    """Flatten a pytree into ``(header_bytes, array_buffers)``."""
    leaves, treedef = _tree_util().tree_flatten(state)
    infos: List[Tuple] = []
    buffers: List[np.ndarray] = []
    for leaf in leaves:
        if _is_array(leaf):
            host = _to_host(leaf)
            infos.append(("arr", _dtype_name(host.dtype), host.shape, host.nbytes))
            buffers.append(host)
        else:
            infos.append(("obj", pickle.dumps(leaf)))
    header = pickle.dumps((treedef, infos))
    return header, buffers


def unflatten_state(header: bytes, buffers: List[np.ndarray]) -> Any:
    """Inverse of :func:`flatten_state`."""
    treedef, infos = pickle.loads(header)
    leaves: List[Any] = []
    it = iter(buffers)
    for info in infos:
        if info[0] == "arr":
            _, dtype, shape, _ = info
            buf = next(it)
            leaves.append(np.frombuffer(buf, dtype=_resolve_dtype(dtype)).reshape(shape))
        else:
            leaves.append(pickle.loads(info[1]))
    return _tree_util().tree_unflatten(treedef, leaves)


def save_state(state: Any, f: BinaryIO) -> None:
    """Stream a pytree to a file object."""
    header, buffers = flatten_state(state)
    f.write(_LEN.pack(len(header)))
    f.write(header)
    for buf in buffers:
        f.write(as_bytes(buf))


def load_state(f: BinaryIO) -> Any:
    """Inverse of :func:`save_state`."""
    (header_len,) = _LEN.unpack(f.read(_LEN.size))
    header = f.read(header_len)
    _, infos = pickle.loads(header)
    buffers: List[np.ndarray] = []
    for info in infos:
        if info[0] == "arr":
            nbytes = info[3]
            raw = f.read(nbytes)
            if len(raw) != nbytes:
                raise EOFError("truncated checkpoint stream")
            buffers.append(np.frombuffer(raw, dtype=np.uint8))
    return unflatten_state(header, buffers)


def dumps_state(state: Any) -> bytes:
    buf = io.BytesIO()
    save_state(state, buf)
    return buf.getvalue()


def loads_state(data: bytes) -> Any:
    return load_state(io.BytesIO(data))
