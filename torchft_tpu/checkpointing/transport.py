"""Checkpoint transport interface.

Mirrors the reference ABC exactly (torchft/checkpointing/transport.py:14-68):
``metadata`` advertises how peers can reach this transport, ``send`` /
``recv`` move one step's state dict, and ``disallow_checkpoint`` closes the
serving window after the commit barrier so stale state is never served.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from datetime import timedelta
from typing import Generic, List, TypeVar

T = TypeVar("T")

__all__ = ["CheckpointTransport"]


class CheckpointTransport(ABC, Generic[T]):
    @abstractmethod
    def metadata(self) -> str:
        """Metadata (e.g. an URL) peers need to fetch checkpoints from this
        rank. Carried to them through the quorum exchange."""

    @abstractmethod
    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: timedelta
    ) -> None:
        """Make ``state_dict`` for ``step`` available to ``dst_ranks``."""

    def disallow_checkpoint(self) -> None:  # noqa: B027 — optional hook
        """Close the serving window (called after the commit barrier)."""

    @abstractmethod
    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: timedelta
    ) -> T:
        """Fetch ``step``'s state dict from ``src_rank``."""

    def shutdown(self, wait: bool = True) -> None:  # noqa: B027 — optional hook
        """Release resources (server threads, sockets)."""
