"""HTTP checkpoint transport — the default live-recovery path.

Reference: torchft/checkpointing/http_transport.py (in-process
ThreadingHTTPServer serving ``/checkpoint/{step}/...``, RWLock-gated so
GETs block while no checkpoint is staged) and http.py (IPv6 server with a
deep accept backlog). Same design here, serving JAX pytrees via the raw
buffer streaming in :mod:`torchft_tpu.checkpointing.serialization`.

Chunked mode (``num_chunks > 0``): the header plus a chunk manifest is
served at ``/metadata``; array buffers are split round-robin by size into
``num_chunks`` groups fetched in parallel — the analogue of the reference's
parallel chunk GETs (http_transport.py:243-266).
"""

from __future__ import annotations

import logging
import socket
import threading
import time
import urllib.request
from datetime import timedelta
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from concurrent.futures import ThreadPoolExecutor
from typing import Generic, List, Optional, Tuple, TypeVar

import numpy as np

from torchft_tpu import telemetry
from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.checkpointing.serialization import (
    as_bytes,
    flatten_state,
    unflatten_state,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport

logger = logging.getLogger(__name__)

T = TypeVar("T")

__all__ = ["HTTPTransport"]


class _Server(ThreadingHTTPServer):
    address_family = socket.AF_INET6
    request_queue_size = 1024
    daemon_threads = True


TRACE_HEADER = "X-TFT-Trace"


def _traced_urlopen(url: str, timeout: float):
    """urlopen with the caller's trace context attached, so the serving
    side records its span as a child of the requesting replica's span —
    the cross-replica parent/child link on the merged timeline."""
    req = urllib.request.Request(url)
    try:
        req.add_header(
            TRACE_HEADER,
            telemetry.TRACER.format_carrier(telemetry.TRACER.inject()),
        )
    except Exception:  # noqa: BLE001 — tracing must never fail a transfer
        pass
    return urllib.request.urlopen(req, timeout=timeout)


def _assign_chunks(sizes: List[int], num_chunks: int) -> List[List[int]]:
    """Greedy size-balanced assignment of buffer indices to chunks."""
    order = sorted(range(len(sizes)), key=lambda i: -sizes[i])
    totals = [0] * num_chunks
    groups: List[List[int]] = [[] for _ in range(num_chunks)]
    for i in order:
        c = totals.index(min(totals))
        groups[c].append(i)
        totals[c] += sizes[i]
    for g in groups:
        g.sort()  # stream each chunk's buffers in deterministic order
    return groups


class HTTPTransport(CheckpointTransport[T], Generic[T]):
    """Serves the staged checkpoint over HTTP from an in-process server."""

    def __init__(
        self,
        timeout: timedelta = timedelta(seconds=60),
        num_chunks: int = 0,
        hostname: Optional[str] = None,
    ) -> None:
        self._timeout = timeout
        self._num_chunks = num_chunks
        self._hostname = hostname or socket.gethostname()
        # payload size of the last recv_checkpoint — the Manager reads it
        # for the heal_end event's bytes field
        self.last_recv_bytes: int = 0

        self._lock = RWLock(timeout=timeout.total_seconds())
        self._step: Optional[int] = None
        self._header: Optional[bytes] = None
        self._buffers: List[np.ndarray] = []
        self._groups: List[List[int]] = []
        # serving starts disallowed: readers block until first staging.
        # _allowed tracks whether the write lock is currently released (the
        # serving window is open); only the manager's quorum/commit path
        # flips it, and that path is single-threaded by the Manager.
        self._lock.w_acquire()
        self._allowed = False

        transport = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self) -> None:
                # /metrics needs no checkpoint state: serve the process
                # telemetry BEFORE the staging lock, so a scrape succeeds
                # even while no checkpoint is staged (readers would block)
                if self.path.rstrip("/") == "/metrics":
                    body = telemetry.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    try:
                        self.wfile.write(body)
                    except (BrokenPipeError, socket.timeout):
                        pass
                    return
                # bound socket writes so one stalled healing peer can't hold
                # the read lock forever (which would block the next
                # disallow_checkpoint and fail should_commit on this side)
                self.connection.settimeout(transport._timeout.total_seconds())
                try:
                    transport._lock.r_acquire()
                except TimeoutError:
                    self.send_error(503, "no checkpoint staged within timeout")
                    return
                try:
                    parts = self.path.strip("/").split("/")
                    # /checkpoint/{step}/{what}
                    if len(parts) != 3 or parts[0] != "checkpoint":
                        self.send_error(404, f"bad path {self.path}")
                        return
                    step = int(parts[1])
                    if step != transport._step:
                        self.send_error(
                            410, f"step {step} not staged (have {transport._step})"
                        )
                        return
                    what = parts[2]
                    if what == "full":
                        payload = transport._render_full()
                    elif what == "metadata":
                        payload = transport._render_metadata()
                    elif what.startswith("chunk_"):
                        payload = transport._render_chunk(int(what[len("chunk_") :]))
                    else:
                        self.send_error(404, f"bad path {self.path}")
                        return
                    from torchft_tpu.faultinject.core import fault_point

                    inj = fault_point(
                        "ckpt.serve", match=what, wire=True, step=step,
                        nbytes=sum(len(p) for p in payload),
                    )
                    if inj is not None and inj.action in ("drop", "torn"):
                        # checkpoint-serve death mid-heal: promise the
                        # full Content-Length, stream only a prefix, then
                        # cut the connection — the healer must fail the
                        # transfer (short read), never stage the torn
                        # state; it retries on its next quorum
                        self._serve_torn(
                            payload,
                            inj.frac if inj.action == "torn" else 0.0,
                        )
                        return
                    self.send_response(200)
                    nbytes = sum(len(p) for p in payload)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Content-Length", str(nbytes))
                    self.end_headers()
                    t0 = time.perf_counter()
                    # child span of the healing replica's heal_recv span:
                    # the requester ships its trace context in a header
                    carrier = telemetry.TRACER.parse_carrier(
                        self.headers.get(TRACE_HEADER, "") or ""
                    )
                    with telemetry.TRACER.span(
                        "checkpoint_serve",
                        parent=carrier,
                        # our own identity, not the carrier's: the span
                        # joins the HEALER's trace (parent/trace_id) but
                        # must render on the SERVING replica's lane
                        replica_id=(
                            telemetry.TRACER.context()["replica_id"] or None
                        ),
                        path=self.path,
                        bytes=nbytes,
                    ):
                        for part in payload:
                            self.wfile.write(part)
                    telemetry.record_checkpoint(
                        "send", nbytes, time.perf_counter() - t0, "http"
                    )
                except (BrokenPipeError, socket.timeout):
                    pass
                except Exception as e:  # noqa: BLE001 — report to the peer
                    logger.exception("checkpoint GET failed")
                    try:
                        self.send_error(500, str(e))
                    except Exception:
                        pass
                finally:
                    transport._lock.r_release()

            def _serve_torn(self, payload, frac: float) -> None:
                nbytes = sum(len(p) for p in payload)
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(nbytes))
                self.end_headers()
                budget = int(nbytes * frac)
                try:
                    for part in payload:
                        if budget <= 0:
                            break
                        chunk = part[:budget]
                        self.wfile.write(chunk)
                        budget -= len(chunk)
                    self.wfile.flush()
                finally:
                    # hard-cut so the client sees EOF mid-body, exactly
                    # like the serving process dying mid-transfer
                    try:
                        self.connection.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    self.close_connection = True

        self._server = _Server(("::", 0), Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="tft_ckpt_http", daemon=True
        )
        self._thread.start()

    # -- render (read lock held) --

    def _render_full(self) -> List[bytes]:
        import struct

        assert self._header is not None
        out = [struct.pack("<Q", len(self._header)), self._header]
        out.extend(as_bytes(b) for b in self._buffers)
        return out

    def _render_metadata(self) -> List[bytes]:
        import pickle

        return [pickle.dumps((self._header, self._groups))]

    def _render_chunk(self, i: int) -> List[bytes]:
        return [as_bytes(self._buffers[j]) for j in self._groups[i]]

    # -- CheckpointTransport --

    def metadata(self) -> str:
        return f"http://{self._hostname}:{self._port}"

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: timedelta
    ) -> None:
        # reclaim the write lock if a previous window is still open (e.g. a
        # step aborted before should_commit ran disallow_checkpoint), so
        # staging never races active GET streams
        self.disallow_checkpoint()
        t0 = time.perf_counter()
        header, buffers = flatten_state(state_dict)
        nbytes = len(header) + sum(int(b.nbytes) for b in buffers)
        telemetry.record_checkpoint(
            "stage", nbytes, time.perf_counter() - t0, "http"
        )
        telemetry.emit(
            "checkpoint_send",
            transport="http",
            dst_ranks=list(dst_ranks),
            step=step,
            bytes=nbytes,
        )
        self._header = header
        self._buffers = buffers
        nchunks = min(self._num_chunks, len(buffers)) if self._num_chunks else 0
        self._groups = (
            _assign_chunks([b.nbytes for b in buffers], nchunks) if nchunks else []
        )
        self._step = step
        self._lock.w_release()  # open the serving window
        self._allowed = True

    def disallow_checkpoint(self) -> None:
        if self._allowed:
            self._lock.w_acquire()
            self._allowed = False

    def _fetch_full(self, base: str, secs: float, step: int) -> T:
        t0 = time.perf_counter()
        with _traced_urlopen(f"{base}/full", timeout=secs) as resp:
            from torchft_tpu.checkpointing.serialization import load_state

            state = load_state(resp)
            nbytes = int(resp.headers.get("Content-Length") or 0)
        self._record_recv(nbytes, time.perf_counter() - t0, step)
        return state

    def _record_recv(self, nbytes: int, seconds: float, step: int) -> None:
        self.last_recv_bytes = nbytes
        telemetry.record_checkpoint("recv", nbytes, seconds, "http")
        telemetry.emit(
            "checkpoint_recv",
            transport="http",
            step=step,
            bytes=nbytes,
            duration_s=round(seconds, 4),
        )

    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: timedelta
    ) -> T:
        from torchft_tpu.faultinject.core import fault_point

        fault_point("ckpt.recv", match=str(step), step=step)
        base = f"{metadata}/checkpoint/{step}"
        secs = timeout.total_seconds()
        if self._num_chunks == 0:
            return self._fetch_full(base, secs, step)

        import pickle

        t0 = time.perf_counter()
        with _traced_urlopen(f"{base}/metadata", timeout=secs) as resp:
            header, groups = pickle.loads(resp.read())
        if not groups:
            # sender staged unchunked (its num_chunks=0 wins over ours)
            return self._fetch_full(base, secs, step)
        _, infos = pickle.loads(header)
        from torchft_tpu.checkpointing.serialization import buffer_sizes

        sizes = buffer_sizes(infos)
        buffers: List[Optional[np.ndarray]] = [None] * len(sizes)

        def fetch(ci: int) -> None:
            with _traced_urlopen(f"{base}/chunk_{ci}", timeout=secs) as r:
                for j in groups[ci]:
                    nbytes = sizes[j]
                    raw = r.read(nbytes)
                    if len(raw) != nbytes:
                        raise EOFError(f"truncated chunk {ci}")
                    buffers[j] = np.frombuffer(raw, dtype=np.uint8)

        with ThreadPoolExecutor(max_workers=len(groups) or 1) as pool:
            for f in [pool.submit(fetch, ci) for ci in range(len(groups))]:
                f.result()
        self._record_recv(
            len(header) + sum(sizes), time.perf_counter() - t0, step
        )
        return unflatten_state(header, [b for b in buffers if b is not None])

    def shutdown(self, wait: bool = True) -> None:
        self._server.shutdown()
        self._server.server_close()
        if wait:
            self._thread.join(timeout=5)
