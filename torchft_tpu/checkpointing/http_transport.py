"""HTTP checkpoint transport — the default live-recovery path.

Reference: torchft/checkpointing/http_transport.py (in-process
ThreadingHTTPServer serving ``/checkpoint/{step}/...``, RWLock-gated so
GETs block while no checkpoint is staged) and http.py (IPv6 server with a
deep accept backlog). Same design here, serving JAX pytrees via the raw
buffer streaming in :mod:`torchft_tpu.checkpointing.serialization`.

Beyond the reference (docs/heal_plane.md):

* **Striped multi-source heal** — :meth:`HTTPTransport.recv_checkpoint_multi`
  pulls byte-balanced ranges of the flattened state tree from EVERY live
  peer in parallel (work-queue scheduling, so a source dying mid-heal just
  hands its pending ranges to the survivors). The bulk bytes ride the
  native blob plane (``native/blob.cc``, GIL-free, shared stripe layer
  with the gradient data plane) when available, with the HTTP
  ``/range_{offset}_{len}`` endpoint as the fallback; metadata, the
  stripe plan and the differential negotiation stay on HTTP.
* **Differential heal** — a healer that still holds the committed state
  at its last step asks ``/delta_{since}_{digest}`` and receives only the
  leaves that changed since (:mod:`torchft_tpu.checkpointing.delta`).
* **Consistency by digest** — every source's ``/stripemeta`` carries the
  staged tree digest; the healer only stripes across sources whose
  digests agree with the primary's (so e.g. LocalSGD groups with diverged
  inner state automatically degrade to single-source heal instead of
  mixing bytes from two different states).

Chunked mode (``num_chunks > 0``): the header plus a chunk manifest is
served at ``/metadata``; array buffers are grouped by greedy-LPT size
balance into ``num_chunks`` groups fetched in parallel — the analogue of
the reference's parallel chunk GETs (http_transport.py:243-266). The
striped path above supersedes it for heals (byte ranges balance exactly
where whole-buffer LPT cannot), but the endpoint remains for tooling.
"""

from __future__ import annotations

import logging
import os
import pickle
import socket
import struct
import threading
import time
import urllib.parse
import urllib.request
from collections import deque
from datetime import timedelta
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Generic, List, Optional, Tuple, TypeVar

import numpy as np

from torchft_tpu import telemetry
from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.checkpointing import delta as delta_mod
from torchft_tpu.checkpointing.serialization import (
    as_bytes,
    flatten_state,
    unflatten_state,
)
from torchft_tpu.checkpointing.stripes import (
    assign_chunk_groups,
    heal_sources_limit,
    heal_stripes_per_source,
    slice_buffers,
    stripe_ranges,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport

logger = logging.getLogger(__name__)

T = TypeVar("T")

__all__ = ["HTTPTransport"]


class _Server(ThreadingHTTPServer):
    address_family = socket.AF_INET6
    request_queue_size = 1024
    daemon_threads = True


TRACE_HEADER = "X-TFT-Trace"

# staging tokens are process-global so a transport recreated in-place can
# never reissue a token an old healer still holds
_STAGING_TOKEN = iter(range(1, 1 << 62))
_STAGING_TOKEN_LOCK = threading.Lock()


def _next_token() -> int:
    with _STAGING_TOKEN_LOCK:
        return next(_STAGING_TOKEN)


def _heal_digest_enabled() -> bool:
    """``TORCHFT_HEAL_DIGEST=0`` disables staging digests — and with
    them multi-source striping AND differential heal (both are
    digest-anchored); heals then behave like the single-source
    reference path."""
    return os.environ.get("TORCHFT_HEAL_DIGEST", "1") != "0"


def _heal_meta_timeout_s() -> float:
    """Staging-window wait bound for the striped-heal endpoints
    (``TORCHFT_HEAL_META_TIMEOUT_S``, default 5): long enough for a
    source mid-staging (flatten+digest complete in well under this for
    any state the full timeout could move anyway), short enough that a
    source that will not stage this round costs seconds, not the
    transfer timeout."""
    try:
        return float(os.environ.get("TORCHFT_HEAL_META_TIMEOUT_S", "5"))
    except ValueError:
        return 5.0


def _heal_native_enabled() -> bool:
    """``TORCHFT_HEAL_NATIVE=0`` keeps heal bytes on HTTP (the native
    blob plane is the default bulk path when the core is loadable)."""
    return os.environ.get("TORCHFT_HEAL_NATIVE", "1") != "0"


def _traced_urlopen(url: str, timeout: float):
    """urlopen with the caller's trace context attached, so the serving
    side records its span as a child of the requesting replica's span —
    the cross-replica parent/child link on the merged timeline."""
    req = urllib.request.Request(url)
    try:
        req.add_header(
            TRACE_HEADER,
            telemetry.TRACER.format_carrier(telemetry.TRACER.inject()),
        )
    except Exception:  # noqa: BLE001 — tracing must never fail a transfer
        pass
    return urllib.request.urlopen(req, timeout=timeout)


# retained import surface: the chunk grouping moved to stripes.py (shared
# with tests and the heal planner)
_assign_chunks = assign_chunk_groups


class HTTPTransport(CheckpointTransport[T], Generic[T]):
    """Serves the staged checkpoint over HTTP from an in-process server."""

    def __init__(
        self,
        timeout: timedelta = timedelta(seconds=60),
        num_chunks: int = 0,
        hostname: Optional[str] = None,
    ) -> None:
        self._timeout = timeout
        self._num_chunks = num_chunks
        self._hostname = hostname or socket.gethostname()
        # payload size of the last recv_checkpoint — the Manager reads it
        # for the heal_end event's bytes field
        self.last_recv_bytes: int = 0
        # per-source throughput + stage attribution of the last
        # multi-source recv (docs/heal_plane.md; the Manager embeds it in
        # heal_end and the recovery bench exports it)
        self.last_heal_stats: Dict[str, Any] = {}
        # differential-heal digest trail (checkpointing/delta.CommitTrail)
        # — attached by the Manager when TORCHFT_HEAL_DIFF is on
        self.commit_trail: Optional[delta_mod.CommitTrail] = None

        self._lock = RWLock(timeout=timeout.total_seconds())
        self._step: Optional[int] = None
        self._header: Optional[bytes] = None
        self._buffers: List[np.ndarray] = []
        self._sizes: List[int] = []
        self._total = 0
        self._digests: Optional[List[str]] = None
        self._tree_digest: Optional[str] = None
        self._groups: List[List[int]] = []
        self._token = 0
        # native blob server (bulk heal bytes), created lazily at first
        # staging; None when the native core is unavailable or disabled
        self._blob = None
        self._blob_failed = False
        # serving starts disallowed: readers block until first staging.
        # _allowed tracks whether the write lock is currently released (the
        # serving window is open); only the manager's quorum/commit path
        # flips it, and that path is single-threaded by the Manager.
        self._lock.w_acquire()
        self._allowed = False

        transport = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self) -> None:
                # /metrics needs no checkpoint state: serve the process
                # telemetry BEFORE the staging lock, so a scrape succeeds
                # even while no checkpoint is staged (readers would block)
                if self.path.rstrip("/") == "/metrics":
                    body = telemetry.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    try:
                        self.wfile.write(body)
                    except (BrokenPipeError, socket.timeout):
                        pass
                    return
                # per-commit critical-path attribution (ISSUE 11): same
                # lock-free contract as /metrics — serves the process
                # attributor's report (empty shape when no monitor runs)
                if self.path.rstrip("/") == "/critical_path.json":
                    from torchft_tpu.telemetry import critical_path

                    body = critical_path.report_json().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    try:
                        self.wfile.write(body)
                    except (BrokenPipeError, socket.timeout):
                        pass
                    return
                # bound socket writes so one stalled healing peer can't hold
                # the read lock forever (which would block the next
                # disallow_checkpoint and fail should_commit on this side)
                self.connection.settimeout(transport._timeout.total_seconds())
                parts = self.path.strip("/").split("/")
                # striped-heal endpoints wait only briefly for a staging
                # window: a healer probing a source whose quorum round ran
                # allow_heal=False (death-watch re-quorum racing a rejoin)
                # would otherwise park for the full transfer timeout on a
                # window that never opens this round — it should drop the
                # source fast and retry next quorum (docs/heal_plane.md)
                bounded = len(parts) == 3 and (
                    parts[2] == "stripemeta"
                    or parts[2].startswith(("range_", "delta_"))
                )
                try:
                    transport._lock.r_acquire(
                        timeout=_heal_meta_timeout_s() if bounded else None
                    )
                except TimeoutError:
                    self.send_error(503, "no checkpoint staged within timeout")
                    return
                try:
                    # /checkpoint/{step}/{what}
                    if len(parts) != 3 or parts[0] != "checkpoint":
                        self.send_error(404, f"bad path {self.path}")
                        return
                    step = int(parts[1])
                    if step != transport._step:
                        self.send_error(
                            410, f"step {step} not staged (have {transport._step})"
                        )
                        return
                    what = parts[2]
                    if what == "full":
                        payload = transport._render_full()
                    elif what == "metadata":
                        payload = transport._render_metadata()
                    elif what == "stripemeta":
                        payload = transport._render_stripemeta()
                    elif what.startswith("chunk_"):
                        payload = transport._render_chunk(int(what[len("chunk_") :]))
                    elif what.startswith("range_"):
                        _, off_s, len_s = what.split("_")
                        payload = transport._render_range(
                            int(off_s), int(len_s)
                        )
                    elif what.startswith("delta_"):
                        _, since_s, digest = what.split("_")
                        payload = transport._render_delta(int(since_s), digest)
                    else:
                        self.send_error(404, f"bad path {self.path}")
                        return
                    from torchft_tpu.faultinject.core import fault_point

                    inj = fault_point(
                        "ckpt.serve", match=what, wire=True, step=step,
                        nbytes=sum(len(p) for p in payload),
                    )
                    if inj is not None and inj.action in ("drop", "torn"):
                        # checkpoint-serve death mid-heal: promise the
                        # full Content-Length, stream only a prefix, then
                        # cut the connection — the healer must fail the
                        # transfer (short read), never stage the torn
                        # state; it retries on its next quorum
                        self._serve_torn(
                            payload,
                            inj.frac if inj.action == "torn" else 0.0,
                        )
                        return
                    self.send_response(200)
                    nbytes = sum(len(p) for p in payload)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Content-Length", str(nbytes))
                    self.end_headers()
                    t0 = time.perf_counter()
                    # child span of the healing replica's heal_recv span:
                    # the requester ships its trace context in a header
                    carrier = telemetry.TRACER.parse_carrier(
                        self.headers.get(TRACE_HEADER, "") or ""
                    )
                    with telemetry.TRACER.span(
                        "checkpoint_serve",
                        parent=carrier,
                        # our own identity, not the carrier's: the span
                        # joins the HEALER's trace (parent/trace_id) but
                        # must render on the SERVING replica's lane
                        replica_id=(
                            telemetry.TRACER.context()["replica_id"] or None
                        ),
                        path=self.path,
                        bytes=nbytes,
                    ):
                        for part in payload:
                            self.wfile.write(part)
                    telemetry.record_checkpoint(
                        "send", nbytes, time.perf_counter() - t0, "http"
                    )
                except (BrokenPipeError, socket.timeout):
                    pass
                except Exception as e:  # noqa: BLE001 — report to the peer
                    logger.exception("checkpoint GET failed")
                    try:
                        self.send_error(500, str(e))
                    except Exception:
                        pass
                finally:
                    transport._lock.r_release()

            def _serve_torn(self, payload, frac: float) -> None:
                nbytes = sum(len(p) for p in payload)
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(nbytes))
                self.end_headers()
                budget = int(nbytes * frac)
                try:
                    for part in payload:
                        if budget <= 0:
                            break
                        chunk = part[:budget]
                        self.wfile.write(chunk)
                        budget -= len(chunk)
                    self.wfile.flush()
                finally:
                    # hard-cut so the client sees EOF mid-body, exactly
                    # like the serving process dying mid-transfer
                    try:
                        self.connection.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    self.close_connection = True

        self._server = _Server(("::", 0), Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="tft_ckpt_http", daemon=True
        )
        self._thread.start()

    # -- render (read lock held) --

    def _render_full(self) -> List[bytes]:
        assert self._header is not None
        out = [struct.pack("<Q", len(self._header)), self._header]
        out.extend(as_bytes(b) for b in self._buffers)
        return out

    def _render_metadata(self) -> List[bytes]:
        return [pickle.dumps((self._header, self._groups))]

    def _render_chunk(self, i: int) -> List[bytes]:
        return [as_bytes(self._buffers[j]) for j in self._groups[i]]

    def _render_stripemeta(self) -> List[bytes]:
        """Everything a healer needs to plan + verify a striped fetch
        from THIS source: the header (treedef + leaf infos), the buffer
        byte layout, the staging token, the staged tree digest (None when
        digests are disabled) and the native blob port (None when the
        bulk path is HTTP-only)."""
        blob = self._blob
        meta = {
            "step": self._step,
            "header": self._header,
            "sizes": list(self._sizes),
            "total": self._total,
            "tree_digest": self._tree_digest,
            "token": self._token,
            "blob_port": getattr(blob, "port", None),
        }
        return [pickle.dumps(meta)]

    def _render_range(self, offset: int, length: int) -> List[bytes]:
        if offset < 0 or length <= 0 or offset + length > self._total:
            raise ValueError(
                f"bad range [{offset}, {offset + length}) of {self._total}"
            )
        return list(
            slice_buffers(self._buffers, self._sizes, offset, length)
        )

    def _render_delta(self, since_step: int, healer_digest: str) -> List[bytes]:
        """Differential response: only the buffers that changed since the
        healer's last committed step — or a loud ``{"mode": "full"}``
        refusal whenever a delta is not provably sound (no trail entry
        for that step, digest mismatch, digests disabled)."""
        assert self._header is not None
        trail = self.commit_trail
        built = None
        if trail is not None and self._digests is not None:
            built = delta_mod.build_delta(
                self._header,
                self._buffers,
                self._digests,
                trail.get(since_step),
                healer_digest,
            )
        if built is None:
            return delta_mod.pack_delta({"mode": "full"}, [])
        manifest, changed = built
        return delta_mod.pack_delta(manifest, changed)

    # -- CheckpointTransport --

    def metadata(self) -> str:
        return f"http://{self._hostname}:{self._port}"

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: timedelta
    ) -> None:
        # reclaim the write lock if a previous window is still open (e.g. a
        # step aborted before should_commit ran disallow_checkpoint), so
        # staging never races active GET streams
        self.disallow_checkpoint()
        t0 = time.perf_counter()
        header, buffers = flatten_state(state_dict)
        # pin contiguity: the blob plane serves raw base pointers, and
        # _to_host already returns contiguous arrays — this is a no-op
        # guard against exotic leaf types
        buffers = [np.ascontiguousarray(b) for b in buffers]
        nbytes = len(header) + sum(int(b.nbytes) for b in buffers)
        telemetry.record_checkpoint(
            "stage", nbytes, time.perf_counter() - t0, "http"
        )
        telemetry.emit(
            "checkpoint_send",
            transport="http",
            dst_ranks=list(dst_ranks),
            step=step,
            bytes=nbytes,
        )
        self._header = header
        self._buffers = buffers
        self._sizes = [int(b.nbytes) for b in buffers]
        self._total = sum(self._sizes)
        if _heal_digest_enabled():
            trail = self.commit_trail
            digests = None
            if trail is not None:
                # the Manager records the trail from the SAME state at the
                # step boundary; reuse its digests instead of re-hashing
                ent = trail.get(step)
                if ent is not None and ent["sizes"] == self._sizes:
                    digests = list(ent["leaves"])
            if digests is None:
                digests = delta_mod.leaf_digests(buffers)
                if trail is not None:
                    trail.record(step, buffers, digests=digests)
            self._digests = digests
            self._tree_digest = delta_mod.tree_digest(digests)
        else:
            self._digests = None
            self._tree_digest = None
        nchunks = min(self._num_chunks, len(buffers)) if self._num_chunks else 0
        self._groups = (
            assign_chunk_groups(self._sizes, nchunks) if nchunks else []
        )
        self._step = step
        self._token = _next_token()
        self._stage_blob()
        self._lock.w_release()  # open the serving window
        self._allowed = True

    def _stage_blob(self) -> None:
        """Stage the flattened buffers on the native blob plane (bulk
        heal bytes, GIL-free). Best-effort: any failure falls back to the
        HTTP range endpoint — the stripemeta simply advertises no port."""
        if not _heal_native_enabled() or self._blob_failed:
            return
        try:
            if self._blob is None:
                from torchft_tpu import _native

                self._blob = _native.BlobServer()
            self._blob.stage(
                [b.ctypes.data for b in self._buffers],
                self._sizes,
                self._token,
            )
        except Exception as e:  # noqa: BLE001 — HTTP fallback stays correct
            logger.warning("native blob staging unavailable: %s", e)
            self._blob = None
            self._blob_failed = True

    def disallow_checkpoint(self) -> None:
        if self._allowed:
            self._lock.w_acquire()
            self._allowed = False
        if self._blob is not None:
            # returns once no in-flight native serve still reads the
            # staged buffers, so the next staging may drop them
            self._blob.unstage()

    # -- single-source receive (reference path) --

    def _fetch_full(self, base: str, secs: float, step: int) -> T:
        t0 = time.perf_counter()
        with _traced_urlopen(f"{base}/full", timeout=secs) as resp:
            from torchft_tpu.checkpointing.serialization import load_state

            state = load_state(resp)
            nbytes = int(resp.headers.get("Content-Length") or 0)
        self._record_recv(nbytes, time.perf_counter() - t0, step)
        return state

    def _record_recv(self, nbytes: int, seconds: float, step: int) -> None:
        self.last_recv_bytes = nbytes
        telemetry.record_checkpoint("recv", nbytes, seconds, "http")
        telemetry.emit(
            "checkpoint_recv",
            transport="http",
            step=step,
            bytes=nbytes,
            duration_s=round(seconds, 4),
        )

    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: timedelta
    ) -> T:
        from torchft_tpu.faultinject.core import fault_point

        fault_point("ckpt.recv", match=str(step), step=step)
        base = f"{metadata}/checkpoint/{step}"
        secs = timeout.total_seconds()
        if self._num_chunks == 0:
            return self._fetch_full(base, secs, step)

        t0 = time.perf_counter()
        with _traced_urlopen(f"{base}/metadata", timeout=secs) as resp:
            header, groups = pickle.loads(resp.read())
        if not groups:
            # sender staged unchunked (its num_chunks=0 wins over ours)
            return self._fetch_full(base, secs, step)
        _, infos = pickle.loads(header)
        from torchft_tpu.checkpointing.serialization import buffer_sizes

        sizes = buffer_sizes(infos)
        buffers: List[Optional[np.ndarray]] = [None] * len(sizes)

        def fetch(ci: int) -> None:
            with _traced_urlopen(f"{base}/chunk_{ci}", timeout=secs) as r:
                for j in groups[ci]:
                    nbytes = sizes[j]
                    raw = r.read(nbytes)
                    if len(raw) != nbytes:
                        raise EOFError(f"truncated chunk {ci}")
                    buffers[j] = np.frombuffer(raw, dtype=np.uint8)

        with ThreadPoolExecutor(max_workers=len(groups) or 1) as pool:
            for f in [pool.submit(fetch, ci) for ci in range(len(groups))]:
                f.result()
        self._record_recv(
            len(header) + sum(sizes), time.perf_counter() - t0, step
        )
        return unflatten_state(header, [b for b in buffers if b is not None])

    # -- striped multi-source receive (docs/heal_plane.md) --

    def recv_checkpoint_multi(
        self,
        sources: List[str],
        step: int,
        timeout: timedelta,
        since_step: Optional[int] = None,
        own: Optional[Tuple[List[np.ndarray], str]] = None,
        header_cb: Optional[Callable[[bytes], None]] = None,
    ) -> T:
        """Fetch ``step``'s state dict striped across ``sources`` (each a
        transport metadata URL; ``sources[0]`` is the lighthouse-named
        primary). With ``since_step``/``own`` the differential fast path
        is tried first (``own`` = this replica's flattened buffers + tree
        digest at ``since_step``). ``header_cb`` fires as soon as the
        header is known — before any bulk bytes land — so the caller can
        overlap jit compile/warmup with the transfer."""
        from torchft_tpu.faultinject.core import fault_point

        fault_point("ckpt.recv", match=str(step), step=step)
        assert sources, "need at least one heal source"
        secs = timeout.total_seconds()
        deadline = time.monotonic() + secs
        t_start = time.perf_counter()
        stats: Dict[str, Any] = {
            "mode": "striped",
            "sources": {},
            "stages": {},
        }
        self.last_heal_stats = stats

        # ---- differential fast path -----------------------------------
        if since_step is not None and own is not None:
            state = self._try_delta(
                sources[0], step, since_step, own, secs, stats,
                header_cb=header_cb,
            )
            if state is not None:
                self._record_recv(
                    int(stats["bytes"]), time.perf_counter() - t_start, step
                )
                return state

        # ---- stripe planning ------------------------------------------
        t0 = time.perf_counter()
        sources = sources[: heal_sources_limit()]
        metas: Dict[str, Dict[str, Any]] = {}
        meta_errors: Dict[str, str] = {}

        # bounded per-source planning probe: the server answers within
        # _heal_meta_timeout_s (or 503s), so a blackholed host must not
        # consume the whole transfer deadline before a single range moves
        meta_secs = min(secs, _heal_meta_timeout_s() + 5.0)

        def fetch_meta(src: str) -> None:
            try:
                with _traced_urlopen(
                    f"{src}/checkpoint/{step}/stripemeta", timeout=meta_secs
                ) as r:
                    metas[src] = pickle.loads(r.read())
            except Exception as e:  # noqa: BLE001 — a dead source is dropped
                meta_errors[src] = str(e)

        if len(sources) == 1:
            fetch_meta(sources[0])
        else:
            with ThreadPoolExecutor(
                max_workers=len(sources), thread_name_prefix="tft_heal_meta"
            ) as pool:
                list(pool.map(fetch_meta, sources))
        alive = [s for s in sources if s in metas]
        if not alive:
            raise ConnectionError(
                f"no heal source reachable for step {step}: {meta_errors}"
            )
        primary = alive[0]
        pmeta = metas[primary]
        if pmeta.get("tree_digest"):
            # stripe only across sources provably staging the SAME bytes;
            # anything else (diverged LocalSGD inner state, a source that
            # re-staged a different step mid-plan) degrades to fewer
            # sources rather than ever mixing two states
            active = []
            for s in alive:
                if metas[s].get("tree_digest") == pmeta["tree_digest"]:
                    active.append(s)
                else:
                    logger.warning(
                        "heal source %s staged a different tree than the "
                        "primary (digest %s vs %s, %d vs %d bytes, header "
                        "%d vs %d) — excluded from striping",
                        s,
                        metas[s].get("tree_digest"),
                        pmeta["tree_digest"],
                        metas[s].get("total"),
                        pmeta.get("total"),
                        len(metas[s].get("header") or b""),
                        len(pmeta.get("header") or b""),
                    )
        else:
            active = [primary]
        header: bytes = pmeta["header"]
        sizes: List[int] = list(pmeta["sizes"])
        total: int = int(pmeta["total"])
        telemetry.LEDGER.record_heal_stage(
            "meta", time.perf_counter() - t0
        )
        stats["stages"]["meta_s"] = round(time.perf_counter() - t0, 4)

        if header_cb is not None:
            try:
                header_cb(header)
            except Exception:  # noqa: BLE001 — warmup is best-effort
                logger.exception("heal header callback failed")

        # ---- striped fetch (work queue: a dead source's pending ranges
        # re-stripe onto the survivors) ---------------------------------
        t0 = time.perf_counter()
        dest = bytearray(total)
        mv = memoryview(dest)
        ranges = stripe_ranges(total, len(active) * heal_stripes_per_source())
        queue: deque = deque(ranges)
        qlock = threading.Lock()
        failures: Dict[str, str] = {}
        done_bytes = [0]

        def fetch_range(src: str, off: int, length: int) -> None:
            left = max(0.1, deadline - time.monotonic())
            meta = metas[src]
            view = mv[off : off + length]
            if (
                meta.get("blob_port")
                and _heal_native_enabled()
                and not self._blob_failed
            ):
                from torchft_tpu import _native

                host = urllib.parse.urlsplit(src).hostname or "localhost"
                _native.blob_fetch(
                    host,
                    int(meta["blob_port"]),
                    int(meta["token"]),
                    off,
                    length,
                    view,
                    timeout_ms=int(left * 1000),
                )
            else:
                url = f"{src}/checkpoint/{step}/range_{off}_{length}"
                with _traced_urlopen(url, timeout=left) as r:
                    got = 0
                    while got < length:
                        k = r.readinto(view[got:])
                        if not k:
                            raise EOFError(
                                f"short range read {got}/{length} from {src}"
                            )
                        got += k

        def worker(src: str) -> None:
            srcstat = stats["sources"].setdefault(
                src, {"bytes": 0, "seconds": 0.0, "ranges": 0}
            )
            while True:
                with qlock:
                    if not queue:
                        return
                    off, length = queue.popleft()
                ts = time.perf_counter()
                try:
                    fetch_range(src, off, length)
                except Exception as e:  # noqa: BLE001 — re-stripe and retire
                    with qlock:
                        queue.append((off, length))
                        failures[src] = str(e)
                    logger.warning(
                        "heal source %s failed mid-stripe (%s); "
                        "re-striping its ranges over survivors",
                        src,
                        e,
                    )
                    return
                dur = time.perf_counter() - ts
                srcstat["bytes"] += length
                srcstat["seconds"] += dur
                srcstat["ranges"] += 1
                with qlock:
                    done_bytes[0] += length

        # re-striping loop: a worker that observed an empty queue exits,
        # but a FAILING worker may re-queue its in-flight range after
        # that — so keep relaunching workers for the surviving sources
        # until the queue drains or every source has failed (each pass
        # either finishes the queue or retires at least one source, so
        # the loop is bounded by len(active))
        while queue and len(failures) < len(active):
            survivors = [s for s in active if s not in failures]
            threads = [
                threading.Thread(
                    target=worker, args=(s,), name=f"tft_heal_stripe{i}"
                )
                for i, s in enumerate(survivors)
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
        if done_bytes[0] != total:
            raise ConnectionError(
                f"striped heal incomplete: {done_bytes[0]}/{total} bytes "
                f"(source failures: {failures or meta_errors})"
            )
        recv_s = time.perf_counter() - t0
        telemetry.LEDGER.record_heal_stage("recv", recv_s)
        for src, st in stats["sources"].items():
            st["gb_per_sec"] = (
                round(st["bytes"] / st["seconds"] / 1e9, 3)
                if st["seconds"] > 0
                else 0.0
            )
        stats["stages"]["recv_s"] = round(recv_s, 4)
        stats["nsources"] = len(active) - len(failures)
        stats["failures"] = failures

        # ---- decode ----------------------------------------------------
        t0 = time.perf_counter()
        buffers: List[np.ndarray] = []
        off = 0
        for s in sizes:
            buffers.append(
                np.frombuffer(dest, dtype=np.uint8, count=s, offset=off)
            )
            off += s
        state = unflatten_state(header, buffers)
        decode_s = time.perf_counter() - t0
        telemetry.LEDGER.record_heal_stage("decode", decode_s)
        stats["stages"]["decode_s"] = round(decode_s, 4)
        stats["bytes"] = len(header) + total
        self._record_recv(
            len(header) + total, time.perf_counter() - t_start, step
        )
        return state

    def _try_delta(
        self,
        primary: str,
        step: int,
        since_step: int,
        own: Tuple[List[np.ndarray], str],
        secs: float,
        stats: Dict[str, Any],
        header_cb: Optional[Callable[[bytes], None]] = None,
    ) -> Optional[T]:
        """Differential attempt against the primary source; None on any
        refusal/failure (the caller proceeds with the striped full path)."""
        own_buffers, own_digest = own
        t0 = time.perf_counter()
        try:
            url = (
                f"{primary}/checkpoint/{step}/delta_{since_step}_{own_digest}"
            )
            with _traced_urlopen(url, timeout=secs) as r:
                body = r.read()
            manifest, payload = delta_mod.unpack_delta(body)
            if manifest.get("mode") != "delta":
                return None
            if header_cb is not None:
                # the heal/compile overlap applies to delta heals too —
                # fire the warmup before the (decode) apply
                try:
                    header_cb(manifest["header"])
                except Exception:  # noqa: BLE001 — warmup is best-effort
                    logger.exception("heal header callback failed")
            header, buffers = delta_mod.apply_delta(
                manifest, payload, own_buffers
            )
            state = unflatten_state(header, buffers)
        except Exception as e:  # noqa: BLE001 — degrade to the full path
            logger.warning(
                "differential heal unavailable (%s); falling back to full",
                e,
            )
            return None
        dur = time.perf_counter() - t0
        telemetry.LEDGER.record_heal_stage("recv", dur)
        stats["mode"] = "delta"
        stats["bytes"] = len(body)
        stats["delta"] = {
            "since_step": since_step,
            "changed": len(manifest["changed"]),
            "leaves": len(own_buffers),
            "bytes": len(body),
            "seconds": round(dur, 4),
        }
        stats["sources"][primary] = {
            "bytes": len(body),
            "seconds": round(dur, 4),
            "ranges": 1,
            "gb_per_sec": round(len(body) / max(dur, 1e-9) / 1e9, 3),
        }
        return state

    def shutdown(self, wait: bool = True) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._blob is not None:
            self._blob.close()
        if wait:
            self._thread.join(timeout=5)
