"""Periodic disk checkpointing — the user-owned half of recovery.

The reference documents but does not implement this composition: "users
should checkpoint [manager + model + optimizer + dataloader] frequently"
(/root/reference/torchft/manager.py:83-85, train_ddp.py:141-148 shows the
workflow). ``DiskCheckpointer`` packages it: step-tagged atomic snapshots
of ``{manager state, user state}``, retention of the newest K, and
restore-latest — sharded ``jax.Array`` leaves ride the per-shard
serialization (serialization.py "shards" infos), so a 7B HSDP group
writes its shards without ever gathering the model.

Division of labor with live healing: the quorum heal covers *partial*
failures (a surviving peer serves current state); the disk checkpoint
covers *total* failures (every group lost) and planned restarts. Load
happens BEFORE the first quorum so a resumed group reports its true step
and heals forward, never backward.

Multi-rank groups: for fully-addressable state, exactly one writer per
group (rank 0 by convention — pass ``is_writer=False`` elsewhere) and
every rank restores from the shared file, so the group's rank planes can
never resume at different steps. When the state holds
**non-fully-addressable** ``jax.Array`` leaves (a cross-process-sharded
multi-host group), a single writer can only serialize its own
addressable shards — so in that case EVERY process writes its own
``..procIofN.ckpt`` shard file (the ``is_writer`` convention then applies
per process, not per group) and :meth:`restore` merges all N files'
shards before handing the tree back (round-2 advisor finding).
"""

from __future__ import annotations

import logging
import os
import re
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Tuple

from torchft_tpu.checkpointing.serialization import (
    ShardedArray,
    load_state,
    save_state,
)

logger = logging.getLogger(__name__)

__all__ = ["DiskCheckpointer"]

_NAME = re.compile(
    r"^(?P<tag>.+)_step(?P<step>\d+)(?:\.g(?P<gen>\d+))?"
    r"(?:\.proc(?P<pidx>\d+)of(?P<pcount>\d+))?\.ckpt$"
)


def _needs_per_process(state: Any) -> bool:
    """True when any leaf is a jax.Array whose shards span processes: one
    writer's ``addressable_shards`` would then be an incomplete checkpoint
    (round-2 advisor finding on the single-writer convention)."""
    try:
        import jax
    except Exception:
        return False
    for leaf in jax.tree_util.tree_leaves(state):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return True
    return False


def _merge_shard_trees(trees: List[Any]) -> Any:
    """Merge per-process checkpoint trees: :class:`ShardedArray` leaves
    pool their shards (deduplicated by index), every other leaf is taken
    from the first tree (they are replicated across writers)."""
    from torchft_tpu.checkpointing.serialization import _tree_util

    tu = _tree_util()
    is_sharded = lambda l: isinstance(l, ShardedArray)  # noqa: E731
    flat = [tu.tree_flatten(t, is_leaf=is_sharded) for t in trees]
    leaves0, treedef = flat[0]
    for _, other_def in flat[1:]:
        if other_def != treedef:
            # never silently pool shards across mismatched structures (a
            # partial code rollout renaming a key would pair shards with
            # the wrong parameter)
            raise ValueError(
                "per-process checkpoints disagree on tree structure: "
                f"{other_def} != {treedef}"
            )
    merged: List[Any] = []
    for i, leaf in enumerate(leaves0):
        if not isinstance(leaf, ShardedArray):
            merged.append(leaf)
            continue
        seen = {}
        for leaves, _ in flat:
            other = leaves[i]
            if (
                not isinstance(other, ShardedArray)
                or other.shape != leaf.shape
                or other.dtype != leaf.dtype
            ):
                raise ValueError(
                    "per-process checkpoints disagree on leaf "
                    f"{leaf.shape}/{leaf.dtype}"
                )
            for idx, data in other.shards:
                seen.setdefault(idx, data)
        merged.append(
            ShardedArray(
                leaf.dtype,
                leaf.shape,
                leaf.mesh_desc,
                leaf.spec_entries,
                list(seen.items()),
            )
        )
    return tu.tree_unflatten(treedef, merged)


class DiskCheckpointer:
    def __init__(
        self,
        directory: str,
        manager,
        state_dict: Callable[[], Any],
        load_state_dict: Callable[[Any], None],
        every: int = 5,
        keep: int = 3,
        tag: str = "group0",
        is_writer: bool = True,
        async_save: bool = False,
    ) -> None:
        """
        Args:
            directory: checkpoint directory (created if missing)
            manager: the Manager whose progress counters ride along
            state_dict / load_state_dict: user snapshot/restore callbacks
                (params, optimizer, sampler position, ...); restored
                sharded leaves arrive as ShardedArray placeholders — pass
                them through ``from_transfer_tree`` (FTTrainer does)
            every: save cadence in committed steps
            keep: newest checkpoints retained (older ones pruned)
            tag: filename prefix — one distinct tag per replica group
            is_writer: for fully-addressable state, exactly one rank per
                group writes and all ranks read. For cross-process-sharded
                state the convention is per *process*: set True on one
                rank of every process (each writes its own
                ``..procIofN.ckpt`` shard file; restore merges the set)
            async_save: serialize + write on a background thread so the
                train loop never blocks on disk. The state is captured
                synchronously — ``jax.Array`` leaves are immutable (free
                to share with the writer), mutable numpy leaves are
                copied — so later training steps can't tear the snapshot.
                At most one save is in flight; a cadence hit while one is
                running is skipped (the next hit retries).
        """
        self._dir = directory
        self._manager = manager
        self._state_dict = state_dict
        self._load_state_dict = load_state_dict
        self._every = max(1, every)
        self._keep = max(1, keep)
        self._tag = tag
        self._is_writer = is_writer
        self._async = async_save
        self._executor: Optional[ThreadPoolExecutor] = None
        self._inflight: Optional[Future] = None
        self._io_lock = threading.Lock()  # serializes writes with prune
        os.makedirs(directory, exist_ok=True)
        # progress gate: never snapshot the step we started at (a pristine
        # step-0 checkpoint on a fresh start is pure noise)
        self._last_saved = manager.current_step()
        # Write generation: arbitration between a dense file and a stale
        # procIofN set at the SAME step must not hinge on filesystem mtime
        # (1 s granularity can tie or invert — round-3 advisor finding).
        # Each incarnation claims max(existing gen)+1 once at construction;
        # every process of a group constructs before any writes (quorum
        # gates the first save), so the whole group shares one generation.
        # Generation 0 keeps the legacy suffix-free filename.
        self._gen = self._scan_max_gen()
        self._cleanup_stale()

    def _scan_max_gen(self) -> int:
        try:
            names = os.listdir(self._dir)
        except FileNotFoundError:
            return 0
        gens = [
            int(m.group("gen") or 0)
            for m in (_NAME.match(n) for n in names)
            if m and m.group("tag") == self._tag
        ]
        return max(gens) + 1 if gens else 0

    def _cleanup_stale(self) -> None:
        for name in os.listdir(self._dir):
            if name.endswith(".ckpt.tmp"):
                # a writer died mid-save; the partial file is garbage.
                # Exact-tag match only ("group1" must not touch
                # "group10_step5.ckpt.tmp" in a shared directory).
                m = _NAME.match(name[: -len(".tmp")])
                if m and m.group("tag") == self._tag:
                    try:
                        os.remove(os.path.join(self._dir, name))
                    except OSError:
                        pass
            elif name == f"{self._tag}.ckpt":
                # pre-DiskCheckpointer layout (unstepped single file)
                logger.warning(
                    "ignoring old-layout checkpoint %s (expected "
                    "'%s_step<N>.ckpt'); it will NOT be restored",
                    name,
                    self._tag,
                )

    # -- paths --

    def _gen_suffix(self) -> str:
        return f".g{self._gen}" if self._gen else ""

    def _path(self, step: int) -> str:
        return os.path.join(
            self._dir, f"{self._tag}_step{step}{self._gen_suffix()}.ckpt"
        )

    def _proc_path(self, step: int, pidx: int, pcount: int) -> str:
        return os.path.join(
            self._dir,
            f"{self._tag}_step{step}{self._gen_suffix()}"
            f".proc{pidx}of{pcount}.ckpt",
        )

    def _existing(self) -> List[Tuple[int, List[str]]]:
        """``[(step, [paths])]`` sorted by step, only *complete* steps: a
        dense checkpoint is one file; a per-process checkpoint counts only
        when all N ``procIofN`` files are present (a host that died
        mid-save must not offer a half checkpoint as restorable)."""
        dense: dict = {}  # step -> (gen, path), highest gen wins
        procs: dict = {}  # (step, gen) -> {pidx: (path, pcount)}
        try:
            names = os.listdir(self._dir)
        except FileNotFoundError:
            return []
        for name in names:
            m = _NAME.match(name)
            if not m or m.group("tag") != self._tag:
                continue
            step = int(m.group("step"))
            gen = int(m.group("gen") or 0)
            path = os.path.join(self._dir, name)
            if m.group("pidx") is None:
                if step not in dense or gen > dense[step][0]:
                    dense[step] = (gen, path)
            else:
                procs.setdefault((step, gen), {})[int(m.group("pidx"))] = (
                    path,
                    int(m.group("pcount")),
                )
        # a procset is complete only when all N files of ONE generation are
        # present; the best complete set per step is the highest generation
        complete_procs: dict = {}  # step -> (gen, [paths])
        for (step, gen), by_idx in procs.items():
            counts = {pcount for _, pcount in by_idx.values()}
            if len(counts) == 1 and len(by_idx) == next(iter(counts)):
                if step not in complete_procs or gen > complete_procs[step][0]:
                    complete_procs[step] = (
                        gen,
                        [by_idx[i][0] for i in sorted(by_idx)],
                    )

        def _mtime_ns(paths: List[str]) -> int:
            # best-effort legacy tiebreak only: ignore unstatable members
            # rather than zeroing the whole set (round-3 advisor finding)
            times = []
            for p in paths:
                try:
                    times.append(os.stat(p).st_mtime_ns)
                except OSError:
                    pass
            return max(times, default=0)

        out: List[Tuple[int, List[str]]] = []
        for step in dense.keys() | complete_procs.keys():
            # one entry per step: an elastic resize can leave BOTH a dense
            # file and a stale complete procIofN set (or vice versa) at the
            # same step — offer only the newer write, never a stale merge.
            # Order of preference: higher write generation (deterministic),
            # then ns mtime (legacy gen-0 files), then dense (stable).
            if step in dense and step in complete_procs:
                dg, dpath = dense[step]
                pg, ppaths = complete_procs[step]
                if dg != pg:
                    pick = [dpath] if dg > pg else ppaths
                else:
                    pick = (
                        [dpath]
                        if _mtime_ns([dpath]) >= _mtime_ns(ppaths)
                        else ppaths
                    )
                out.append((step, pick))
            elif step in dense:
                out.append((step, [dense[step][1]]))
            else:
                out.append((step, complete_procs[step][1]))
        return sorted(out)

    def latest(self) -> Optional[str]:
        """Path of the newest complete checkpoint (first file of a
        per-process set)."""
        existing = self._existing()
        return existing[-1][1][0] if existing else None

    # -- save --

    def _snapshot(self) -> Any:
        """Capture the state tear-free: jax.Arrays are immutable (shared
        with the writer thread for free); mutable numpy leaves are copied
        so in-place training updates can't corrupt an in-flight save."""
        import numpy as np

        state = {"torchft": self._manager.state_dict(), "user": self._state_dict()}
        if not self._async:
            return state
        import jax

        return jax.tree_util.tree_map(
            lambda l: l.copy() if isinstance(l, np.ndarray) else l, state
        )

    def _target_path(self, step: int, state: Any) -> str:
        """Dense single-writer file, or this process's shard file when the
        state is sharded across processes (one writer cannot serialize
        remote shards — round-2 advisor finding)."""
        if _needs_per_process(state):
            import jax

            return self._proc_path(step, jax.process_index(), jax.process_count())
        return self._path(step)

    def _write(self, step: int, state: Any, path: str) -> str:
        tmp = path + ".tmp"
        with self._io_lock:
            with open(tmp, "wb") as f:
                save_state(state, f)
            os.replace(tmp, path)
            self._prune()
        logger.info("checkpointed step %d to %s", step, path)
        return path

    def save(self) -> str:
        """Write a snapshot for the current committed step (atomic: a
        crash mid-write leaves the previous checkpoints intact). Blocks
        until the bytes are on disk regardless of ``async_save``."""
        step = self._manager.current_step()
        self._last_saved = step
        state = self._snapshot()
        return self._write(step, state, self._target_path(step, state))

    def maybe_save(self) -> Optional[str]:
        """Call once per loop iteration after ``should_commit``; saves at
        the configured cadence, only on progress, only on the writer.
        With ``async_save`` the write happens in the background and the
        eventual path is returned immediately."""
        step = self._manager.current_step()
        if not (
            self._is_writer
            and step % self._every == 0
            and step > self._last_saved
        ):
            return None
        if not self._async:
            return self.save()
        if self._inflight is not None and not self._inflight.done():
            logger.warning(
                "skipping checkpoint at step %d: previous save still "
                "writing (cadence faster than disk)",
                step,
            )
            return None
        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tft_ckpt_disk"
            )
        self._last_saved = step
        state = self._snapshot()  # captured NOW, written later
        path = self._target_path(step, state)
        fut = self._executor.submit(self._write, step, state, path)

        def on_done(f: Future) -> None:
            exc = f.exception()
            if exc is not None:
                # surface the failure even if nobody calls flush(), and
                # let the next cadence hit retry this step
                logger.error("async checkpoint of step %d failed: %s", step, exc)
                if self._last_saved == step:
                    self._last_saved = step - 1

        fut.add_done_callback(on_done)
        self._inflight = fut
        return path

    def flush(self) -> None:
        """Block until any in-flight async save has landed (call before
        shutdown; a pending write surfaces its error here)."""
        if self._inflight is not None:
            self._inflight.result()
            self._inflight = None

    def _prune(self) -> None:
        existing = self._existing()
        for _, paths in existing[: -self._keep]:
            for path in paths:
                try:
                    os.remove(path)
                except OSError:
                    pass
        # Orphan sweep: incomplete per-process sets (a host died mid-save,
        # or an elastic resize changed process_count) are invisible to
        # _existing() and would otherwise leak forever. Anything older than
        # the oldest *retained complete* step is dead; newer incomplete
        # sets are left alone (a peer may still be mid-write).
        kept = existing[-self._keep :]
        if not kept:
            return
        floor = kept[0][0]
        # winning generation per retained step: files AT a retained step
        # from a strictly older generation lost arbitration and would
        # otherwise accumulate one full checkpoint per crash-restart
        # incarnation (each incarnation writes distinct .gK names)
        win_gen = {}
        for step, paths in kept:
            m = _NAME.match(os.path.basename(paths[0]))
            if m:
                win_gen[step] = int(m.group("gen") or 0)
        try:
            names = os.listdir(self._dir)
        except FileNotFoundError:
            return
        for name in names:
            m = _NAME.match(name)
            if not m or m.group("tag") != self._tag:
                continue
            path = os.path.join(self._dir, name)
            step = int(m.group("step"))
            gen = int(m.group("gen") or 0)
            # every kept entry has step >= floor, so step < floor alone
            # proves the file is not retained; at a retained step, only a
            # strictly LOWER generation is provably dead (a higher one may
            # be a newer incarnation mid-write)
            if step < floor or gen < win_gen.get(step, 0):
                try:
                    os.remove(path)
                except OSError:
                    pass

    # -- restore --

    def restore(self) -> bool:
        """Load the newest complete snapshot if one exists; returns True on
        resume. Restores manager progress first so the first quorum reports
        the resumed step. A per-process checkpoint set is merged — sharded
        leaves pool every writer's shards — before the user callback runs,
        so ``from_transfer_tree`` can place any device's shard regardless
        of which host wrote it."""
        existing = self._existing()
        if not existing:
            return False
        _, paths = existing[-1]
        trees = []
        for path in paths:
            with open(path, "rb") as f:
                trees.append(load_state(f))
        state = trees[0] if len(trees) == 1 else _merge_shard_trees(trees)
        self._manager.load_state_dict(state["torchft"])
        self._load_state_dict(state["user"])
        self._last_saved = self._manager.current_step()
        logger.info(
            "resumed from %s (%d file%s) at step %d",
            paths[0],
            len(paths),
            "" if len(paths) == 1 else "s",
            self._manager.current_step(),
        )
        return True
