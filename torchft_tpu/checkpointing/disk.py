"""Periodic disk checkpointing — the user-owned half of recovery.

The reference documents but does not implement this composition: "users
should checkpoint [manager + model + optimizer + dataloader] frequently"
(/root/reference/torchft/manager.py:83-85, train_ddp.py:141-148 shows the
workflow). ``DiskCheckpointer`` packages it: step-tagged atomic snapshots
of ``{manager state, user state}``, retention of the newest K, and
restore-latest — sharded ``jax.Array`` leaves ride the per-shard
serialization (serialization.py "shards" infos), so a 7B HSDP group
writes its shards without ever gathering the model.

Division of labor with live healing: the quorum heal covers *partial*
failures (a surviving peer serves current state); the disk checkpoint
covers *total* failures (every group lost) and planned restarts. Load
happens BEFORE the first quorum so a resumed group reports its true step
and heals forward, never backward.

Multi-rank groups: exactly one writer per group (rank 0 by convention —
pass ``is_writer=False`` elsewhere); every rank restores from the shared
file so the group's rank planes can never resume at different steps.
"""

from __future__ import annotations

import logging
import os
import re
from typing import Any, Callable, List, Optional, Tuple

from torchft_tpu.checkpointing.serialization import load_state, save_state

logger = logging.getLogger(__name__)

__all__ = ["DiskCheckpointer"]

_NAME = re.compile(r"^(?P<tag>.+)_step(?P<step>\d+)\.ckpt$")


class DiskCheckpointer:
    def __init__(
        self,
        directory: str,
        manager,
        state_dict: Callable[[], Any],
        load_state_dict: Callable[[Any], None],
        every: int = 5,
        keep: int = 3,
        tag: str = "group0",
        is_writer: bool = True,
    ) -> None:
        """
        Args:
            directory: checkpoint directory (created if missing)
            manager: the Manager whose progress counters ride along
            state_dict / load_state_dict: user snapshot/restore callbacks
                (params, optimizer, sampler position, ...); restored
                sharded leaves arrive as ShardedArray placeholders — pass
                them through ``from_transfer_tree`` (FTTrainer does)
            every: save cadence in committed steps
            keep: newest checkpoints retained (older ones pruned)
            tag: filename prefix — one distinct tag per replica group
            is_writer: exactly one rank per group writes; all ranks read
        """
        self._dir = directory
        self._manager = manager
        self._state_dict = state_dict
        self._load_state_dict = load_state_dict
        self._every = max(1, every)
        self._keep = max(1, keep)
        self._tag = tag
        self._is_writer = is_writer
        os.makedirs(directory, exist_ok=True)
        # progress gate: never snapshot the step we started at (a pristine
        # step-0 checkpoint on a fresh start is pure noise)
        self._last_saved = manager.current_step()
        self._cleanup_stale()

    def _cleanup_stale(self) -> None:
        for name in os.listdir(self._dir):
            if not name.startswith(self._tag):
                continue
            if name.endswith(".ckpt.tmp"):
                # a writer died mid-save; the partial file is garbage
                try:
                    os.remove(os.path.join(self._dir, name))
                except OSError:
                    pass
            elif name.endswith(".ckpt") and not _NAME.match(name):
                logger.warning(
                    "ignoring unrecognized checkpoint %s (expected "
                    "'%s_step<N>.ckpt' — older layout? it will NOT be "
                    "restored)",
                    name,
                    self._tag,
                )

    # -- paths --

    def _path(self, step: int) -> str:
        return os.path.join(self._dir, f"{self._tag}_step{step}.ckpt")

    def _existing(self) -> List[Tuple[int, str]]:
        out = []
        try:
            names = os.listdir(self._dir)
        except FileNotFoundError:
            return out
        for name in names:
            m = _NAME.match(name)
            if m and m.group("tag") == self._tag:
                out.append((int(m.group("step")), os.path.join(self._dir, name)))
        return sorted(out)

    def latest(self) -> Optional[str]:
        existing = self._existing()
        return existing[-1][1] if existing else None

    # -- save --

    def save(self) -> str:
        """Write a snapshot for the current committed step (atomic: a
        crash mid-write leaves the previous checkpoints intact)."""
        step = self._manager.current_step()
        path = self._path(step)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            save_state(
                {"torchft": self._manager.state_dict(), "user": self._state_dict()},
                f,
            )
        os.replace(tmp, path)
        self._last_saved = step
        logger.info("checkpointed step %d to %s", step, path)
        self._prune()
        return path

    def maybe_save(self) -> Optional[str]:
        """Call once per loop iteration after ``should_commit``; saves at
        the configured cadence, only on progress, only on the writer."""
        step = self._manager.current_step()
        if (
            self._is_writer
            and step % self._every == 0
            and step > self._last_saved
        ):
            return self.save()
        return None

    def _prune(self) -> None:
        for _, path in self._existing()[: -self._keep]:
            try:
                os.remove(path)
            except OSError:
                pass

    # -- restore --

    def restore(self) -> bool:
        """Load the newest snapshot if one exists; returns True on resume.
        Restores manager progress first so the first quorum reports the
        resumed step."""
        path = self.latest()
        if path is None:
            return False
        with open(path, "rb") as f:
            state = load_state(f)
        self._manager.load_state_dict(state["torchft"])
        self._load_state_dict(state["user"])
        self._last_saved = self._manager.current_step()
        logger.info(
            "resumed from %s at step %d", path, self._manager.current_step()
        )
        return True
