"""Checkpoint transport over the reconfigurable data plane.

The PGTransport analogue (torchft/checkpointing/pg_transport.py): sends a
pickled meta message (treedef + per-leaf dtype/shape/nbytes) followed by the
raw array buffers over the Collectives send/recv pairs created for the
current quorum. Useful when the control network is slow but the data plane
is fast; on TPU pods this is the DCN path.
"""

from __future__ import annotations

import logging
from datetime import timedelta
from typing import Generic, List, TypeVar

import numpy as np

from torchft_tpu.checkpointing.serialization import (
    as_bytes,
    buffer_sizes,
    flatten_state,
    unflatten_state,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.collectives import Collectives

logger = logging.getLogger(__name__)

T = TypeVar("T")

__all__ = ["CollectivesTransport"]

# Distinct tag space from training-loop traffic; see collectives.py tag map.
_META_TAG = 0x00CC01
_DATA_TAG = 0x00CC02


class CollectivesTransport(CheckpointTransport[T], Generic[T]):
    def __init__(self, collectives: Collectives, timeout: timedelta) -> None:
        self._collectives = collectives
        self._timeout = timeout

    def metadata(self) -> str:
        return "<collectives>"

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: timedelta
    ) -> None:
        header, buffers = flatten_state(state_dict)
        hdr_arr = np.frombuffer(header, dtype=np.uint8)
        len_arr = np.array([len(header)], dtype=np.int64)
        for dst in dst_ranks:
            self._collectives.send(len_arr, dst, tag=_META_TAG).wait(timeout)
            self._collectives.send(hdr_arr, dst, tag=_META_TAG).wait(timeout)
            for buf in buffers:
                self._collectives.send(
                    np.frombuffer(as_bytes(buf), dtype=np.uint8), dst, tag=_DATA_TAG
                ).wait(timeout)

    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: timedelta
    ) -> T:
        len_arr = np.zeros(1, dtype=np.int64)
        self._collectives.recv(len_arr, src_rank, tag=_META_TAG).wait(timeout)
        hdr_arr = np.zeros(int(len_arr[0]), dtype=np.uint8)
        self._collectives.recv(hdr_arr, src_rank, tag=_META_TAG).wait(timeout)
        header = hdr_arr.tobytes()

        import pickle

        _, infos = pickle.loads(header)
        buffers: List[np.ndarray] = []
        for nbytes in buffer_sizes(infos):
            buf = np.zeros(nbytes, dtype=np.uint8)
            self._collectives.recv(buf, src_rank, tag=_DATA_TAG).wait(timeout)
            buffers.append(buf)
        return unflatten_state(header, buffers)
