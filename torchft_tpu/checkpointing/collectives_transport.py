"""Checkpoint transport over the reconfigurable data plane.

The PGTransport analogue (torchft/checkpointing/pg_transport.py): sends a
pickled meta message (treedef + per-leaf dtype/shape/nbytes) followed by the
raw array buffers over the Collectives send/recv pairs created for the
current quorum. Useful when the control network is slow but the data plane
is fast; on TPU pods this is the DCN path.

Transfers are pipelined the way the reference bounds them
(pg_transport.py:171-198): at most ``_WINDOW`` buffer sends are in flight
per destination — enough to overlap serialization with socket I/O without
holding the whole state dict's worth of wire buffers — and fan-out to
several healing replicas runs destinations in parallel. Each buffer gets
its own wire tag so the overlapped frames can complete out of order; the
receiver windows its recvs the same way and lands each buffer directly in
its preallocated array (zero-copy ``into=`` receive).
"""

from __future__ import annotations

import itertools
import logging
import time
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from typing import Deque, Generic, List, TypeVar

import numpy as np

from torchft_tpu import telemetry
from torchft_tpu.checkpointing.serialization import (
    as_bytes,
    buffer_sizes,
    flatten_state,
    unflatten_state,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.collectives import Collectives

logger = logging.getLogger(__name__)

T = TypeVar("T")

__all__ = ["CollectivesTransport"]

# Distinct tag space from training-loop traffic; see collectives.py tag map.
_META_TAG = 0x00CC01
# Per-buffer data tags cycle within a 4096 window: in-flight reordering is
# bounded by _WINDOW (≪ 4096), so a cycled tag can never collide with a
# frame still in flight.  Tags are additionally SALTED per transfer with a
# sender-chosen nonce carried in the length frame: if a recv_checkpoint
# attempt dies mid-window, its abandoned in-flight recvs keep their old
# tags and can never claim a frame belonging to the retry (round-3 advisor
# finding).  16 salts cycle; a stale recv from 16 transfers ago is long
# dead (or the epoch was reconfigured, which poisons it anyway).
_DATA_TAG0 = 0x0D0000
_TAG_CYCLE = 4096
_WINDOW = 3
_MAX_DST_PARALLEL = 4

_TRANSFER_SALT = itertools.count(1)  # process-global: survives re-instantiation

# Only the LENGTH frame uses the fixed _META_TAG (the receiver can't know
# the salt before reading it); the header frame is already salted so an
# attempt that died between the length and header recvs can't have its
# abandoned header recv claim the retry's frames.
_HDR_TAG0 = 0x00CD00


def _hdr_tag(salt: int) -> int:
    return _HDR_TAG0 | (salt & 0xF)


def _data_tag(salt: int, i: int) -> int:
    return _DATA_TAG0 | ((salt & 0xF) << 12) | (i % _TAG_CYCLE)


class CollectivesTransport(CheckpointTransport[T], Generic[T]):
    def __init__(
        self,
        collectives: Collectives,
        timeout: timedelta,
        window: int = _WINDOW,
    ) -> None:
        self._collectives = collectives
        self._timeout = timeout
        self._window = max(1, window)
        # payload size of the last recv_checkpoint — the Manager reads it
        # for the heal_end event's bytes field
        self.last_recv_bytes: int = 0

    def metadata(self) -> str:
        return "<collectives>"

    def _send_one(
        self,
        dst: int,
        len_arr: np.ndarray,
        hdr_arr: np.ndarray,
        buffers: List[np.ndarray],
        timeout: timedelta,
        salt: int,
    ) -> None:
        from collections import deque

        self._collectives.send(len_arr, dst, tag=_META_TAG).wait(timeout)
        self._collectives.send(hdr_arr, dst, tag=_hdr_tag(salt)).wait(timeout)
        window: Deque = deque()
        for i, buf in enumerate(buffers):
            while len(window) >= self._window:
                window.popleft().wait(timeout)
            window.append(
                self._collectives.send(
                    np.frombuffer(as_bytes(buf), dtype=np.uint8),
                    dst,
                    tag=_data_tag(salt, i),
                )
            )
        while window:
            window.popleft().wait(timeout)

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: timedelta
    ) -> None:
        t0 = time.perf_counter()
        header, buffers = flatten_state(state_dict)
        nbytes = len(header) + sum(int(b.nbytes) for b in buffers)
        telemetry.record_checkpoint(
            "stage", nbytes, time.perf_counter() - t0, "collectives"
        )
        hdr_arr = np.frombuffer(header, dtype=np.uint8)
        salt = next(_TRANSFER_SALT)
        # the salt rides in the length frame so the receiver tags its
        # windowed recvs identically without an extra round-trip
        len_arr = np.array([len(header), salt], dtype=np.int64)
        t0 = time.perf_counter()
        if len(dst_ranks) == 1:
            self._send_one(dst_ranks[0], len_arr, hdr_arr, buffers, timeout, salt)
        else:
            with ThreadPoolExecutor(
                max_workers=min(_MAX_DST_PARALLEL, len(dst_ranks)),
                thread_name_prefix="tft_ckpt_send",
            ) as pool:
                futs = [
                    pool.submit(
                        self._send_one, dst, len_arr, hdr_arr, buffers,
                        timeout, salt,
                    )
                    for dst in dst_ranks
                ]
                for f in futs:
                    f.result()
        seconds = time.perf_counter() - t0
        telemetry.record_checkpoint(
            "send", nbytes * len(dst_ranks), seconds, "collectives"
        )
        telemetry.emit(
            "checkpoint_send",
            transport="collectives",
            dst_ranks=list(dst_ranks),
            step=step,
            bytes=nbytes,
            duration_s=round(seconds, 4),
        )

    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: timedelta
    ) -> T:
        from collections import deque

        t0 = time.perf_counter()
        len_arr = np.zeros(2, dtype=np.int64)
        self._collectives.recv(len_arr, src_rank, tag=_META_TAG).wait(timeout)
        salt = int(len_arr[1])
        hdr_arr = np.zeros(int(len_arr[0]), dtype=np.uint8)
        self._collectives.recv(hdr_arr, src_rank, tag=_hdr_tag(salt)).wait(timeout)
        header = hdr_arr.tobytes()

        import pickle

        _, infos = pickle.loads(header)
        buffers: List[np.ndarray] = []
        window: Deque = deque()
        for i, nbytes in enumerate(buffer_sizes(infos)):
            while len(window) >= self._window:
                window.popleft().wait(timeout)
            buf = np.zeros(nbytes, dtype=np.uint8)
            buffers.append(buf)
            window.append(
                self._collectives.recv(buf, src_rank, tag=_data_tag(salt, i))
            )
        while window:
            window.popleft().wait(timeout)
        seconds = time.perf_counter() - t0
        nbytes = len(header) + sum(int(b.nbytes) for b in buffers)
        self.last_recv_bytes = nbytes
        telemetry.record_checkpoint("recv", nbytes, seconds, "collectives")
        telemetry.emit(
            "checkpoint_recv",
            transport="collectives",
            step=step,
            bytes=nbytes,
            duration_s=round(seconds, 4),
        )
        return unflatten_state(header, buffers)
