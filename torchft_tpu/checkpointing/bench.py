"""Checkpoint-transfer benchmark tool.

Reference: torchft/checkpointing/http_transport_bench.py:13-55 — a manual
script moving a default 12 GB state dict, chunked or not. Same tool for the
JAX transports::

    python -m torchft_tpu.checkpointing.bench --total-gb 12 --num-chunks 8
"""

from __future__ import annotations

import argparse
import logging
import time
from datetime import timedelta

import numpy as np


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="checkpoint transfer bench")
    parser.add_argument("--total-gb", type=float, default=12.0)
    parser.add_argument("--tensor-mb", type=float, default=64.0)
    parser.add_argument("--num-chunks", type=int, default=0)
    parser.add_argument(
        "--transport", choices=["http", "collectives"], default="http"
    )
    parser.add_argument(
        "--window",
        type=int,
        default=0,
        help="collectives transport in-flight window override (0 = default "
        "3; 1 reproduces the round-2 serial send/wait schedule — measured "
        "13x slower at 1 GB on loopback)",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    n_tensors = max(1, int(args.total_gb * 1024 / args.tensor_mb))
    elems = int(args.tensor_mb * 1024 * 1024 / 4)
    state = {
        f"t{i}": np.ones(elems, dtype=np.float32) for i in range(n_tensors)
    }
    total_bytes = n_tensors * elems * 4
    timeout = timedelta(seconds=600)

    if args.transport == "http":
        from torchft_tpu.checkpointing.http_transport import HTTPTransport

        send = HTTPTransport(timeout=timeout, num_chunks=args.num_chunks)
        recv = HTTPTransport(timeout=timeout, num_chunks=args.num_chunks)
        try:
            t0 = time.perf_counter()
            send.send_checkpoint([1], step=1, state_dict=state, timeout=timeout)
            staged = time.perf_counter() - t0
            t0 = time.perf_counter()
            out = recv.recv_checkpoint(
                src_rank=0, metadata=send.metadata(), step=1, timeout=timeout
            )
            took = time.perf_counter() - t0
        finally:
            send.shutdown()
            recv.shutdown()
    else:
        from concurrent.futures import ThreadPoolExecutor

        from torchft_tpu.checkpointing.collectives_transport import (
            _WINDOW,
            CollectivesTransport,
        )
        from torchft_tpu.collectives import CollectivesTcp
        from torchft_tpu.store import StoreServer

        window = args.window if args.window > 0 else _WINDOW

        store = StoreServer()
        colls = [CollectivesTcp(timeout=timeout) for _ in range(2)]
        with ThreadPoolExecutor(max_workers=2) as pool:
            list(pool.map(lambda i: colls[i].configure(store.address(), i, 2), range(2)))
        transports = [
            CollectivesTransport(c, timeout=timeout, window=window) for c in colls
        ]
        staged = 0.0
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=2) as pool:
            fs = pool.submit(
                transports[0].send_checkpoint, [1], 1, state, timeout
            )
            fr = pool.submit(
                transports[1].recv_checkpoint, 0, "<collectives>", 1, timeout
            )
            fs.result()
            out = fr.result()
        took = time.perf_counter() - t0
        for c in colls:
            c.shutdown()
        store.shutdown()

    assert len(out) == n_tensors
    gbps = total_bytes / took / 1e9
    print(
        f"transport={args.transport} total={total_bytes/1e9:.2f}GB "
        f"chunks={args.num_chunks} stage={staged:.2f}s transfer={took:.2f}s "
        f"({gbps:.2f} GB/s)"
    )


if __name__ == "__main__":
    main()
