"""LocalSGD and DiLoCo — communication-reduced outer-loop synchronization.

Reference: torchft/local_sgd.py. LocalSGD (arxiv 1805.09767) runs
``sync_every`` purely-local optimizer steps, then averages *parameters*
across replica groups; DiLoCo (arxiv 2311.08105) instead averages
*pseudogradients* (the parameter delta since the last sync) and feeds them
to an outer optimizer.

Functional JAX shape: instead of hooking a torch optimizer, the caller
threads the params pytree through ``step()`` after every inner update::

    lsgd = LocalSGD(manager, sync_every=32)
    lsgd.save(params)                       # backup before the first step
    for batch in data:
        params, opt_state = inner_step(params, opt_state, batch)
        params = lsgd.step(params)          # averages every sync_every calls

A host-side backup of the last synced params makes failed syncs safe: if
the quorum doesn't commit, ``step`` returns the backup and the
``sync_every`` local steps are discarded (same guarantee as the reference).

DiLoCo note: this implementation uses the paper's pseudogradient sign
``backup − local`` (so the outer optimizer *descends* toward the inner
progress). The reference computes ``local − backup`` (local_sgd.py:211-215),
which inverts the outer step direction; we keep the paper semantics.

Pipelined-commit note: LocalSGD works unchanged on a manager with
``commit_pipeline=True`` — ``sync`` resolves any vote a pipelined
per-step driver left in flight before issuing its own quorum and
collectives (the manager refuses collectives while a vote is pending),
then takes the synchronous commit path. Pipelining the *sync* barrier
would buy nothing anyway: it fires once per ``sync_every`` inner steps,
so its RTT is already amortized.
DiLoCo additionally rejects a pipelined manager outright, for the same
reason it requires synchronous quorum: the outer step must start from a
fully-settled state on every replica.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from torchft_tpu.checkpointing.serialization import to_host_tree as _to_host
from torchft_tpu.ddp import allreduce_gradients
from torchft_tpu.manager import Manager
from torchft_tpu.wire_codec import (
    ErrorFeedback,
    ErrorFeedbackBinding,
    LowRankErrorFeedback,
    lowrank_basis,
    lowrank_compress,
    lowrank_decompress,
    lowrank_eligible,
)

__all__ = ["LocalSGD", "DiLoCo"]


class LocalSGD:
    """Parameter averaging every ``sync_every`` local steps."""

    def __init__(
        self,
        manager: Manager,
        sync_every: int,
        error_feedback: "Optional[ErrorFeedback | bool]" = None,
    ) -> None:
        assert sync_every >= 1, "sync_every must be >= 1"
        self._manager = manager
        self._sync_every = sync_every
        self._local_step = 0
        self._backup: Optional[Any] = None
        self._just_healed = False
        # auto/lazy/CMA-gate semantics shared with ManagedOptimizer via
        # the one binding implementation (wire_codec.ErrorFeedbackBinding)
        self._efb = ErrorFeedbackBinding(manager, error_feedback)

    @property
    def error_feedback(self) -> Optional[ErrorFeedback]:
        return self._efb.instance

    def save(self, params: Any) -> None:
        """Snapshot ``params`` to host as the restore point. ``copy=True``
        guarantees the backup owns its buffers — without it a contiguous
        numpy params tree would alias the live params and in-place inner
        updates would silently corrupt the rollback state."""
        self._backup = _to_host(params, copy=True)

    def step(self, params: Any) -> Any:
        """Count one local optimizer step; every ``sync_every`` calls run a
        fault-tolerant sync and return the post-sync params."""
        if self._backup is None:
            raise RuntimeError("call save(params) before the first step")
        self._local_step += 1
        if self._local_step >= self._sync_every:
            params = self.sync(params)
            self._local_step = 0
        return params

    def sync(self, params: Any) -> Any:
        # A pipelined per-step driver may have left a vote in flight: the
        # manager refuses collectives while one is pending, so resolve it
        # BEFORE this sync's quorum/averaging (the driver's own
        # on_resolved callback handles any rollback of its state; getattr
        # keeps duck-typed test stubs working).
        if getattr(self._manager, "pending_commit", lambda: None)() is not None:
            self._manager.resolve_pending_commit()
        self._manager.start_quorum()
        # Functional-JAX heal gap the reference never has: torch heals
        # mutate the model in place, so the caller's reference aliases the
        # healed tensors — here `params` was captured BEFORE start_quorum
        # ran the (sync-mode) heal. A just-healed group's only consistent
        # state is the received backup: syncing from it contributes a zero
        # pseudogradient (DiLoCo) / the healed params (LocalSGD), exactly
        # what a replica with no inner progress since the backup should.
        if self._just_healed:
            params = _to_host(self._backup, copy=True)
        try:
            return self._perform_sync(params)
        finally:
            # also covers async-quorum heals that land inside
            # _perform_sync's commit barrier: the received backup is
            # reconciled there (backup := committed average), so the flag
            # must never leak into the next sync and discard real work
            self._just_healed = False

    # live-recovery snapshot (wire into Manager.set_state_dict_fns along
    # with the caller's params/inner state; the reference leaves this to
    # the integ harness — here it's part of the wrapper)
    def state_dict(self) -> dict:
        out = {"backup": self._backup, "local_step": self._local_step}
        if self._efb.instance is not None:
            out["ef"] = self._efb.instance.state_dict()
        return out

    def load_state_dict(self, state: dict) -> None:
        self._backup = _to_host(state["backup"], copy=True)
        self._local_step = int(state["local_step"])
        ef = self._efb.instance
        if ef is None and "ef" in state:
            # lazy auto mode: adopt the healed accumulators (see
            # ErrorFeedbackBinding.ensure_for_state), don't drop them
            ef = self._efb.ensure_for_state(state["ef"])
        if ef is not None:
            ef.load_state_dict(state.get("ef") or {"acc": {}})
        # the caller's local params are stale relative to this received
        # state; the next sync must start from the backup (see sync())
        self._just_healed = True

    def _perform_sync(self, params: Any) -> Any:
        ef = self._efb.live()
        # allreduce_gradients averages any pytree — here, the params
        averaged = allreduce_gradients(
            self._manager, params, error_feedback=ef
        )
        if self._manager.should_commit():
            if ef is not None:
                ef.commit()
            # the caller continues training on `averaged`; the backup must
            # not alias it or in-place inner steps corrupt the restore point
            self._backup = _to_host(averaged, copy=True)
            return averaged
        if ef is not None:
            ef.rollback()
        # discard the local steps; hand out a copy so in-place training on
        # the restored tree cannot corrupt the snapshot either
        return _to_host(self._backup, copy=True)


class DiLoCo(LocalSGD):
    """Pseudogradient averaging with an outer optimizer.

    ``outer_tx`` is an optax transformation (the paper uses SGD with
    Nesterov momentum). Requires ``use_async_quorum=False``: the outer step
    must start from a fully-healed state or replicas diverge
    (local_sgd.py:195-199).

    ``outer_rank`` (or ``TORCHFT_WIRE_OUTER_RANK``) enables the
    PowerSGD-style low-rank projection on the outer step — the one place
    in the stack where staleness already tolerates approximation
    (docs/wire_plane.md): each eligible 2-D pseudogradient leaf ships as
    its rank-r projection ``P = M·Q`` (the basis ``Q`` is derived from a
    seeded rng keyed on (leaf, outer-sync ordinal), so every replica
    group holds the same basis without communicating it), and a
    projection-error accumulator feeds the truncated component back into
    the next sync."""

    def __init__(
        self,
        manager: Manager,
        outer_tx,
        sync_every: int,
        error_feedback: "Optional[ErrorFeedback | bool]" = None,
        outer_rank: Optional[int] = None,
    ) -> None:
        if manager._use_async_quorum:
            raise ValueError(
                "DiLoCo requires synchronous quorum; construct the Manager "
                "with use_async_quorum=False"
            )
        # getattr: test stubs/duck-typed managers may predate the knob
        if getattr(manager, "commit_pipeline_enabled", lambda: False)():
            raise ValueError(
                "DiLoCo requires the synchronous commit barrier; construct "
                "the Manager with commit_pipeline=False (the outer step "
                "must start from a fully-settled state on every replica)"
            )
        super().__init__(manager, sync_every, error_feedback=error_feedback)
        self._outer_tx = outer_tx
        self._outer_state: Optional[Any] = None
        if outer_rank is None:
            try:
                outer_rank = int(os.environ.get("TORCHFT_WIRE_OUTER_RANK", "0"))
            except ValueError:
                outer_rank = 0
        self._outer_rank = max(0, outer_rank)
        self._lr_ef = LowRankErrorFeedback() if self._outer_rank else None
        # outer-sync ordinal: seeds each sync's projection basis. Synced
        # across groups because it only advances on COMMIT and rides
        # state_dict through heal/checkpoint like local_step does.
        self._outer_syncs = 0

    def save(self, params: Any) -> None:
        super().save(params)
        if self._outer_state is None:
            self._outer_state = self._outer_tx.init(self._backup)

    def _compress_pseudograd(self, leaves: list) -> "tuple[list, dict]":
        """Swap eligible 2-D leaves for their rank-r projections; returns
        (wire leaves, {leaf index: basis})."""
        bases: Dict[int, np.ndarray] = {}
        out = list(leaves)
        for li, leaf in enumerate(leaves):
            m = np.asarray(leaf)
            if m.dtype != np.float32 or not lowrank_eligible(
                m.shape, self._outer_rank
            ):
                continue
            assert self._lr_ef is not None
            m = self._lr_ef.compensate(f"l{li}", m)
            q = lowrank_basis(
                m.shape, self._outer_rank,
                seed=(li * 1_000_003 + self._outer_syncs) & 0x7FFFFFFF,
            )
            p = lowrank_compress(m, q)
            self._lr_ef.stage(f"l{li}", m, lowrank_decompress(p, q))
            bases[li] = q
            out[li] = p
        return out, bases

    def _perform_sync(self, params: Any) -> Any:
        import jax
        import optax

        assert self._backup is not None and self._outer_state is not None
        local = _to_host(params)
        # paper-sign pseudogradient: descend from the backup toward the
        # averaged inner progress
        pseudograd = jax.tree_util.tree_map(np.subtract, self._backup, local)
        ef = self._efb.live()
        bases: Dict[int, np.ndarray] = {}
        if self._outer_rank:
            leaves, treedef = jax.tree_util.tree_flatten(pseudograd)
            leaves, bases = self._compress_pseudograd(leaves)
            pseudograd = jax.tree_util.tree_unflatten(treedef, leaves)
        pseudograd = allreduce_gradients(
            self._manager, pseudograd, error_feedback=ef
        )
        if bases:
            leaves, treedef = jax.tree_util.tree_flatten(pseudograd)
            for li, q in bases.items():
                leaves[li] = lowrank_decompress(np.asarray(leaves[li]), q)
            pseudograd = jax.tree_util.tree_unflatten(treedef, leaves)

        if not self._manager.should_commit():
            if ef is not None:
                ef.rollback()
            if self._lr_ef is not None:
                self._lr_ef.rollback()
            return _to_host(self._backup, copy=True)
        if ef is not None:
            ef.commit()
        if self._lr_ef is not None:
            self._lr_ef.commit()
        self._outer_syncs += 1

        updates, self._outer_state = self._outer_tx.update(
            pseudograd, self._outer_state, self._backup
        )
        new_params = optax.apply_updates(self._backup, updates)
        self._backup = _to_host(new_params, copy=True)
        return new_params

    def outer_state(self) -> Any:
        return self._outer_state

    def state_dict(self) -> dict:
        d = super().state_dict()
        d["outer_state"] = self._outer_state
        d["outer_syncs"] = self._outer_syncs
        if self._lr_ef is not None:
            d["lr_ef"] = self._lr_ef.state_dict()
        return d

    def load_state_dict(self, state: dict) -> None:
        super().load_state_dict(state)
        self._outer_state = state["outer_state"]
        self._outer_syncs = int(state.get("outer_syncs", 0))
        if self._lr_ef is not None:
            self._lr_ef.load_state_dict(state.get("lr_ef") or {"acc": {}})
