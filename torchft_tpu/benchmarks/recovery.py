"""Recovery wall-clock measurement: kill one of two replica groups, time
the survivor's blackout and the rejoiner's time-to-first-commit.

BASELINE.md names "quorum-recovery wall-clock after killing 1 replica
group" as the driver metric and "re-quorum in < 1 step" as the north star;
the reference never measures it (its envelope lives in test assertions,
lighthouse_test.py:44-47, manager_integ_test.py:325-368). This harness
measures it for real, with real process kills:

* two replica groups run as **subprocesses** (numpy data plane over
  ``CollectivesTcp`` — hardware-independent; the TPU stays free for the
  throughput bench in the parent),
* at a chosen step, group 1 takes SIGKILL (no cleanup, no goodbye — its
  manager server and heartbeats die with it),
* group 1 is respawned fresh and heals from the survivor.

Reported numbers (seconds, wall-clock):

* ``survivor_blackout_s`` — last commit before the kill → first commit
  after it, on the surviving group. Covers dead-peer detection (socket
  deadline), the latched-error flush re-quorum, and the split-brain
  guard's wait for the victim's heartbeat lease to lapse.
* ``rejoin_to_commit_s`` — respawn exec → the rejoiner's first committed
  step, covering store bootstrap, quorum join, live checkpoint heal, and
  one training step.
* ``steady_step_s`` — median healthy step time, so the blackout can be
  read in reference units ("< N steps").

The detection cadence is configurable; the defaults here use aggressive
1 s leases (the reference's defaults — 5 s heartbeat timeout, 60 s op
timeout — bound the same path, just slower).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

__all__ = ["measure_recovery", "RecoveryResult"]


# ---------------------------------------------------------------------------
# worker (subprocess entry: python -m torchft_tpu.benchmarks.recovery)
# ---------------------------------------------------------------------------


def _emit(log, **event) -> None:
    event["t"] = time.time()
    log.write(json.dumps(event) + "\n")
    log.flush()


def _worker() -> None:
    """Numpy-only FT training loop; commits are timestamped to the event
    log. Deliberately jax-free so killing it never disturbs the
    accelerator held by the parent bench process."""
    from datetime import timedelta

    import numpy as np

    from torchft_tpu.collectives import CollectivesTcp
    from torchft_tpu.manager import Manager

    if os.environ.get("TORCHFT_BENCH_DEBUG"):
        import logging

        logging.basicConfig(
            level=logging.DEBUG,
            format="%(asctime)s.%(msecs)03d %(name)s: %(message)s",
            datefmt="%H:%M:%S",
        )

    gid = int(os.environ["REPLICA_GROUP_ID"])
    total_steps = int(os.environ["TORCHFT_BENCH_STEPS"])
    step_sleep = float(os.environ.get("TORCHFT_BENCH_STEP_SLEEP", "0.05"))
    op_timeout = float(os.environ.get("TORCHFT_BENCH_OP_TIMEOUT", "1.0"))
    log = open(os.environ["TORCHFT_EVENT_LOG"], "a")

    # Pre-import jax.tree_util on a side thread: the heal decode path
    # (serialization._tree_util) pays this import on first use, and for
    # this numpy-only worker that lands INSIDE rejoin-to-commit — the
    # heal-stage ledger named it as the dominant decode cost. Starting
    # the import now overlaps it with store bootstrap + quorum join
    # (network waits release the GIL), pulling it off the rejoin
    # serial path.
    import threading

    threading.Thread(
        target=lambda: __import__("jax.tree_util"),
        daemon=True,
        name="tft_prewarm_tree",
    ).start()

    params = {"w": np.zeros((256, 256), np.float32), "steps_seen": 0}

    def state_dict() -> Dict[str, object]:
        return {"w": params["w"].copy(), "steps_seen": params["steps_seen"]}

    def load_state_dict(state) -> None:
        params["w"] = np.asarray(state["w"]).copy()
        params["steps_seen"] = int(state["steps_seen"])

    manager = Manager(
        collectives=CollectivesTcp(timeout=timedelta(seconds=op_timeout)),
        load_state_dict=load_state_dict,
        state_dict=state_dict,
        min_replica_size=1,
        replica_id=f"group{gid}_",
        rank=0,
        world_size=1,
        timeout=timedelta(seconds=op_timeout),
        quorum_timeout=timedelta(seconds=10),
        connect_timeout=timedelta(seconds=10),
    )
    _emit(log, event="start", gid=gid, pid=os.getpid())
    rng = np.random.default_rng(gid)
    heal_stats_seen: Dict[str, object] = {}
    try:
        while manager.current_step() < total_steps:
            try:
                manager.start_quorum()
                time.sleep(step_sleep)  # the "forward/backward" of the toy step
                grad = rng.standard_normal(params["w"].shape).astype(np.float32)
                manager.allreduce(grad).wait()
                committed = manager.should_commit()
            except TimeoutError as e:
                # a loaded host can blow the aggressive 1 s deadlines past
                # even the quorum timeout; a real trainer retries the step
                # rather than crashing — so does the bench worker (the
                # orchestrator's own deadline still bounds a true wedge)
                _emit(log, event="timeout_retry", gid=gid, err=str(e)[:120])
                continue
            if committed:
                params["w"] -= 0.01 * grad
                params["steps_seen"] += 1
                # latch this worker's most recent heal attribution (the
                # multi-source transport fills it; empty pre-heal)
                stats = getattr(
                    manager._checkpoint_transport, "last_heal_stats", None
                )
                if isinstance(stats, dict) and stats.get("stages"):
                    heal_stats_seen = stats
                _emit(
                    log,
                    event="commit",
                    gid=gid,
                    step=manager.current_step(),
                    pid=os.getpid(),
                )
    finally:
        # rejoin-SLO + heal-stage attribution for the bench row (ISSUE 9):
        # the orchestrator reads these from the rejoiner's log so the
        # envelope numbers come with their per-stage explanation
        try:
            from torchft_tpu import telemetry

            slo = manager._slo.rejoin
            _emit(
                log,
                event="slo",
                gid=gid,
                rejoin_threshold_s=(slo.threshold_s if slo else 0.0),
                rejoin_breached=bool(slo.breached) if slo else False,
                rejoin_breaches=int(slo.breaches) if slo else 0,
            )
            _emit(
                log,
                event="heal_stats",
                gid=gid,
                stats=heal_stats_seen,
                stages=telemetry.LEDGER.heal_stage_snapshot(),
            )
        except Exception:  # noqa: BLE001 — attribution must not fail the run
            pass
        manager.shutdown(wait=False)
        _emit(log, event="exit", gid=gid, pid=os.getpid())
        log.close()


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------


@dataclass
class RecoveryResult:
    survivor_blackout_s: float
    rejoin_to_commit_s: float
    steady_step_s: float
    survivor_steps_lost: int
    total_steps: int
    # FT event-trail digest (event kind -> count across all groups) plus
    # the raw per-group trail paths, so the envelope numbers above can be
    # cross-checked against the recorded quorum/heal/peer-death sequence
    ft_events: Optional[Dict[str, int]] = None
    trail_paths: Optional[List[str]] = None
    # unix timestamps of the SIGKILL and the respawn exec — anchors for
    # correlating trail records with the induced failure
    t_kill_unix: float = 0.0
    t_respawn_unix: float = 0.0
    # PR 2: the lighthouse's cluster aggregation captured before teardown —
    # the merged Chrome trace (all replicas, one timeline; open in
    # Perfetto) and the /cluster.json per-replica health snapshot
    merged_trace_path: Optional[str] = None
    cluster: Optional[Dict] = None
    # ISSUE 9: rejoin-to-commit SLO verdict (TORCHFT_SLO_REJOIN_S wired
    # into the workers) + the rejoiner's heal attribution (per-source
    # stripe throughput, meta/recv/decode/device_put stage split)
    rejoin_slo: Optional[Dict] = None
    rejoin_heal: Optional[Dict] = None

    def as_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "survivor_blackout_s": round(self.survivor_blackout_s, 3),
            "rejoin_to_commit_s": round(self.rejoin_to_commit_s, 3),
            "steady_step_s": round(self.steady_step_s, 4),
            "blackout_steps": round(
                self.survivor_blackout_s / max(self.steady_step_s, 1e-9), 1
            ),
            "survivor_steps_lost": self.survivor_steps_lost,
        }
        if self.rejoin_slo is not None:
            out["rejoin_slo_s"] = self.rejoin_slo.get("rejoin_threshold_s")
            out["slo_breach"] = bool(self.rejoin_slo.get("rejoin_breached"))
        if self.rejoin_heal is not None:
            out["rejoin_heal"] = self.rejoin_heal
        if self.ft_events is not None:
            out["ft_events"] = self.ft_events
        return out


def _spawn(
    gid: int, env_extra: Dict[str, str], num_groups: int = 2
) -> subprocess.Popen:
    from torchft_tpu.store import StoreServer

    store = StoreServer()
    env = dict(os.environ)
    env.update(env_extra)
    # the package may be run from a checkout (no pip install): make it
    # importable in the child no matter the parent's cwd
    pkg_parent = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_parent, env.get("PYTHONPATH")) if p
    )
    env.update(
        TORCHFT_STORE_ADDR=store.address(),
        REPLICA_GROUP_ID=str(gid),
        NUM_REPLICA_GROUPS=str(num_groups),
        RANK="0",
        WORLD_SIZE="1",
        # keep children off any accelerator the parent owns
        JAX_PLATFORMS="cpu",
    )
    if os.environ.get("TORCHFT_BENCH_DEBUG"):
        stderr_f = open(env["TORCHFT_EVENT_LOG"] + ".stderr", "ab")
    else:
        stderr_f = subprocess.DEVNULL
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "torchft_tpu.benchmarks.recovery"],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=stderr_f,
        )
    finally:
        if stderr_f is not subprocess.DEVNULL:
            stderr_f.close()  # the child keeps its inherited copy
    proc._torchft_store = store  # keep the store alive with the proc
    return proc


def _read_events(path: str) -> List[Dict]:
    # same JSONL contract as the telemetry trail, including tolerance for
    # the torn line a SIGKILLed writer leaves behind — share the parser
    from torchft_tpu.telemetry import read_trail

    return read_trail(path)


def _wait_for(path: str, pred, timeout_s: float, procs=()) -> Dict:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        for e in _read_events(path):
            if pred(e):
                return e
        for p in procs:
            if p.poll() not in (None, 0):
                raise RuntimeError(f"worker died early (rc={p.poll()})")
        time.sleep(0.02)
    raise TimeoutError("recovery bench: expected event never arrived")


def measure_recovery(
    total_steps: int = 30,
    kill_at_step: int = 8,
    step_sleep: float = 0.05,
    op_timeout: float = 1.0,
    heartbeat_timeout_ms: int = 1000,
    timeout_s: float = 120.0,
    num_groups: int = 2,
    rejoin_slo_s: float = 1.0,
) -> RecoveryResult:
    """Kill 1 of ``num_groups`` replica groups and measure the envelope
    (``num_groups=4`` is the BASELINE north-star shape: survive killing
    1-of-4 and re-quorum in < 1 step)."""
    from torchft_tpu.coordination import LighthouseServer

    victim_gid = num_groups - 1
    tmp = tempfile.mkdtemp(prefix="tft_recovery_")
    logs = [os.path.join(tmp, f"g{g}.jsonl") for g in range(num_groups)]
    # each worker's Manager writes its FT event trail here (telemetry
    # module, TORCHFT_EVENT_TRAIL env) — the flight-recorder view of the
    # same kill the wall-clock numbers summarize
    trails = [os.path.join(tmp, f"g{g}.trail.jsonl") for g in range(num_groups)]
    lighthouse = LighthouseServer(
        bind="[::]:0",
        min_replicas=1,
        join_timeout_ms=100,
        heartbeat_timeout_ms=heartbeat_timeout_ms,
    )
    addr = lighthouse.address().split("//", 1)[-1]
    common = {
        "TORCHFT_LIGHTHOUSE": addr,
        "TORCHFT_BENCH_STEPS": str(total_steps),
        "TORCHFT_BENCH_STEP_SLEEP": str(step_sleep),
        "TORCHFT_BENCH_OP_TIMEOUT": str(op_timeout),
        # hang forensics land next to the trails (flight dumps per pid)
        "TORCHFT_FLIGHT_DIR": tmp,
        # rejoin-to-commit SLO (telemetry/slo.py BurnRateSlo): the
        # rejoiner's Manager evaluates it live; the bench row reports the
        # latch state next to the measured wall-clock
        "TORCHFT_SLO_REJOIN_S": str(rejoin_slo_s),
    }
    procs: List[Optional[subprocess.Popen]] = [None] * num_groups
    try:
        for g in range(num_groups):
            procs[g] = _spawn(
                g,
                {
                    **common,
                    "TORCHFT_EVENT_LOG": logs[g],
                    "TORCHFT_EVENT_TRAIL": trails[g],
                },
                num_groups,
            )

        # let the victim reach the kill step
        _wait_for(
            logs[victim_gid],
            lambda e: e["event"] == "commit" and e["step"] >= kill_at_step,
            timeout_s,
            procs=[p for p in procs if p],
        )
        victim = procs[victim_gid]
        t_kill = time.time()
        victim.send_signal(signal.SIGKILL)
        victim.wait()
        victim._torchft_store.shutdown()

        # respawn the victim fresh (the launcher's restart, done by hand so
        # the respawn time is known exactly)
        t_respawn = time.time()
        procs[victim_gid] = _spawn(
            victim_gid,
            {
                **common,
                "TORCHFT_EVENT_LOG": logs[victim_gid],
                "TORCHFT_EVENT_TRAIL": trails[victim_gid],
            },
            num_groups,
        )

        # survivor's first commit after the kill
        post = _wait_for(
            logs[0],
            lambda e: e["event"] == "commit" and e["t"] > t_kill,
            timeout_s,
            procs=[p for p in procs if p],
        )
        # rejoiner's first commit after respawn
        rejoin = _wait_for(
            logs[victim_gid],
            lambda e: e["event"] == "commit" and e["t"] > t_respawn,
            timeout_s,
            procs=[p for p in procs if p],
        )

        for g, p in enumerate(procs):
            rc = p.wait(timeout=timeout_s)
            if rc != 0:
                # a survivor crashing after the measured commits would
                # otherwise go unnoticed and falsify the envelope
                raise RuntimeError(f"group {g} exited rc={rc}")

        g0 = [e for e in _read_events(logs[0]) if e["event"] == "commit"]
        pre = [e for e in g0 if e["t"] <= t_kill]
        steady = [b["t"] - a["t"] for a, b in zip(pre, pre[1:])]
        steady_step = sorted(steady)[len(steady) // 2] if steady else step_sleep
        last_pre_t = pre[-1]["t"] if pre else t_kill
        last_pre_step = pre[-1]["step"] if pre else kill_at_step
        blackout = post["t"] - last_pre_t
        # committed steps the survivor would have made during the blackout,
        # minus the ones it did make: the "< 1 step" envelope in step units
        lost = max(0, int(blackout / steady_step) - (post["step"] - last_pre_step))
        from torchft_tpu.telemetry import read_trail

        ft_events: Dict[str, int] = {}
        for path in trails:
            for rec in read_trail(path):
                kind = rec.get("event", "?")
                ft_events[kind] = ft_events.get(kind, 0) + 1
        # snapshot the cluster aggregation while the lighthouse is alive:
        # the merged trace IS the incident timeline (kill -> eviction ->
        # re-quorum -> heal) across every replica
        from torchft_tpu.telemetry.native import fetch_merged_trace, poll_cluster

        merged_trace_path = os.path.join(tmp, "cluster_trace.json")
        if fetch_merged_trace(lighthouse.address(), path=merged_trace_path) is None:
            merged_trace_path = None
        cluster = poll_cluster(lighthouse.address())
        # the rejoiner's SLO verdict + heal attribution: take the LAST
        # slo/heal_stats records in its log — those are the respawned
        # incarnation's (the killed one's records, if any, precede them)
        rejoin_events = _read_events(logs[victim_gid])
        rejoin_slo = next(
            (e for e in reversed(rejoin_events) if e["event"] == "slo"), None
        )
        rejoin_heal = None
        hs = next(
            (
                e
                for e in reversed(rejoin_events)
                if e["event"] == "heal_stats" and e.get("t", 0) > t_respawn
            ),
            None,
        )
        if hs is not None and (hs.get("stats") or hs.get("stages")):
            stats = hs.get("stats") or {}
            rejoin_heal = {
                "mode": stats.get("mode"),
                "bytes": stats.get("bytes"),
                "nsources": stats.get("nsources"),
                "per_source_gbps": {
                    src: s.get("gb_per_sec")
                    for src, s in (stats.get("sources") or {}).items()
                },
                "stages_s": hs.get("stages") or stats.get("stages"),
            }
        return RecoveryResult(
            survivor_blackout_s=blackout,
            rejoin_to_commit_s=rejoin["t"] - t_respawn,
            steady_step_s=steady_step,
            survivor_steps_lost=lost,
            total_steps=total_steps,
            ft_events=ft_events,
            trail_paths=list(trails),
            t_kill_unix=t_kill,
            t_respawn_unix=t_respawn,
            merged_trace_path=merged_trace_path,
            cluster=cluster,
            rejoin_slo=rejoin_slo,
            rejoin_heal=rejoin_heal,
        )
    finally:
        for p in procs:
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
            if p is not None:
                p._torchft_store.shutdown()
        lighthouse.shutdown()


if __name__ == "__main__":
    if "TORCHFT_EVENT_LOG" in os.environ:
        _worker()
    else:
        print(json.dumps(measure_recovery().as_dict()))
