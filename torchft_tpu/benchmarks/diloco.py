"""DiLoCo 4-group cost benchmark (BASELINE.md "DiLoCo 4 groups" config).

Round-3 review missing: DiLoCo was correctness-tested but no artifact
reported its *effective* overhead — what the once-per-H-steps pseudo-
gradient averaging over the host plane actually costs. This harness runs
``examples/train_diloco.py``'s exact training configuration (d32→h64→10
MLP, AdamW inner, Nesterov-SGD outer, sync_every=8) as 4 replica-group
subprocesses over CollectivesTcp and separates wall-clock into the inner
loop vs the sync (quorum + averaging + outer step), reporting per-sync
seconds and the amortized overhead percentage.

Usage::

    python -m torchft_tpu.benchmarks.diloco [--outer-steps 6]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Dict, List


# examples/train_diloco.py's exact model/data/loss, inlined: the examples
# directory does not ship in wheels, so the bench cannot import it
def _make_dataset(n=4096, d=32, classes=10, seed=7):
    import numpy as np

    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    w_true = rng.standard_normal((d, classes)).astype(np.float32)
    y = np.argmax(x @ w_true + 0.1 * rng.standard_normal((n, classes)), axis=1)
    return x, y.astype(np.int32)


def _init_params(d=32, hidden=64, classes=10, seed=42):
    import numpy as np

    rng = np.random.default_rng(seed)
    scale = 1.0 / np.sqrt(d)
    return {
        "w1": (scale * rng.standard_normal((d, hidden))).astype(np.float32),
        "b1": np.zeros(hidden, np.float32),
        "w2": (scale * rng.standard_normal((hidden, classes))).astype(np.float32),
        "b2": np.zeros(classes, np.float32),
    }


def _loss_fn(params, x, y):
    import jax
    import optax

    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def _worker_main(argv: List[str]) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--gid", type=int, required=True)
    parser.add_argument("--num-groups", type=int, default=4)
    parser.add_argument("--outer-steps", type=int, default=6)
    parser.add_argument("--sync-every", type=int, default=8)
    args = parser.parse_args(argv)

    from datetime import timedelta

    import numpy as np

    from torchft_tpu.utils.platform import pin_platform_from_env

    os.environ["JAX_PLATFORMS"] = "cpu"
    pin_platform_from_env()

    import jax
    import optax

    from torchft_tpu.collectives import CollectivesTcp
    from torchft_tpu.local_sgd import DiLoCo
    from torchft_tpu.manager import Manager
    from torchft_tpu.store import StoreServer

    store = StoreServer()
    manager = Manager(
        collectives=CollectivesTcp(timeout=timedelta(seconds=30)),
        load_state_dict=None,
        state_dict=None,
        min_replica_size=min(2, args.num_groups),
        use_async_quorum=False,  # the example's setting (heal before sync)
        replica_id=f"dilocobench_{args.gid}",
        store_addr=store.address(),
        rank=0,
        world_size=1,
        timeout=timedelta(seconds=30),
        quorum_timeout=timedelta(seconds=120),
    )
    try:
        x, y = _make_dataset()
        inner_tx = optax.adamw(1e-3)
        outer_tx = optax.sgd(0.7, momentum=0.9, nesterov=True)
        params = _init_params()
        inner = inner_tx.init(params)
        diloco = DiLoCo(manager, outer_tx, sync_every=args.sync_every)
        diloco.save(params)
        manager.set_state_dict_fns(lambda s: None, lambda: {})

        @jax.jit
        def inner_step(params, opt_state, xb, yb):
            loss, grads = jax.value_and_grad(_loss_fn)(params, xb, yb)
            updates, opt_state = inner_tx.update(grads, opt_state, params)
            return loss, optax.apply_updates(params, updates), opt_state

        rng = np.random.default_rng(args.gid)
        batch = 64
        inner_s = 0.0
        inner_steps = 0
        sync_times: List[float] = []
        warm_syncs = 1  # first sync pays quorum formation; exclude it

        while manager.current_step() < args.outer_steps + warm_syncs:
            idx = rng.integers(0, len(x), batch)
            t0 = time.perf_counter()
            loss, params, inner = inner_step(params, inner, x[idx], y[idx])
            float(loss)  # fence
            inner_s += time.perf_counter() - t0
            inner_steps += 1
            t0 = time.perf_counter()
            synced = diloco.step(params)
            dt = time.perf_counter() - t0
            if synced is not params:
                params = synced
                inner = inner_tx.init(synced)
                if manager.current_step() > warm_syncs:
                    sync_times.append(dt)
            else:
                inner_s += dt
        n_bytes = sum(
            int(np.prod(v.shape)) * 4 for v in jax.tree_util.tree_leaves(params)
        )
        print(
            json.dumps(
                {
                    "gid": args.gid,
                    "inner_s": inner_s,
                    "inner_steps": inner_steps,
                    "sync_times": sync_times,
                    "payload_bytes": n_bytes,
                }
            ),
            flush=True,
        )
    finally:
        manager.shutdown(wait=False)
        store.shutdown()


def measure_diloco(
    num_groups: int = 4, outer_steps: int = 6, sync_every: int = 8
) -> Dict[str, object]:
    from torchft_tpu.coordination import LighthouseServer

    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=num_groups)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["TORCHFT_LIGHTHOUSE"] = lighthouse.address().split("//", 1)[-1]
    pkg_parent = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_parent, env.get("PYTHONPATH")) if p
    )
    procs = []
    try:
        for gid in range(num_groups):
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        "-m",
                        "torchft_tpu.benchmarks.diloco",
                        "--worker",
                        "--gid",
                        str(gid),
                        "--num-groups",
                        str(num_groups),
                        "--outer-steps",
                        str(outer_steps),
                        "--sync-every",
                        str(sync_every),
                    ],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    env=env,
                )
            )
        # drain all pipes CONCURRENTLY: the workers are barrier-coupled,
        # so sequentially draining one while another blocks on a full
        # stderr pipe would stall the whole cohort. Inner timeout stays
        # below bench.py's outer 600s cap so worker stderr survives.
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=len(procs)) as pool:
            futs = [pool.submit(p.communicate, None, 500) for p in procs]
            outs = [f.result() for f in futs]
        results = []
        for p, (out, err) in zip(procs, outs):
            if p.returncode != 0:
                raise RuntimeError(
                    f"diloco worker rc={p.returncode}: {err.decode()[-2000:]}"
                )
            results.append(json.loads(out.decode().strip().splitlines()[-1]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        lighthouse.shutdown()

    # per outer round, the slowest group's sync gates everyone
    per_sync = [max(r["sync_times"][i] for r in results)
                for i in range(min(len(r["sync_times"]) for r in results))]
    sync_s = sum(per_sync)
    inner_s = max(r["inner_s"] for r in results)
    inner_steps = results[0]["inner_steps"]
    total = inner_s + sync_s
    return {
        "num_groups": num_groups,
        "sync_every": sync_every,
        "outer_steps_measured": len(per_sync),
        "inner_steps_per_sec": round(inner_steps / inner_s, 2) if inner_s else None,
        "per_sync_seconds": round(sync_s / max(1, len(per_sync)), 4),
        "overhead_pct": round(100.0 * sync_s / total, 2) if total else None,
        "payload_bytes": results[0]["payload_bytes"],
        "config": "examples/train_diloco.py MLP (d32 h64 c10), adamw inner, "
        "nesterov-sgd outer, host TCP plane, sync quorum; first sync "
        "(quorum formation) excluded",
    }


def main() -> None:
    if "--worker" in sys.argv:
        _worker_main([a for a in sys.argv[1:] if a != "--worker"])
        return
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--num-groups", type=int, default=4)
    parser.add_argument("--outer-steps", type=int, default=6)
    parser.add_argument("--sync-every", type=int, default=8)
    args = parser.parse_args()
    print(
        json.dumps(
            measure_diloco(args.num_groups, args.outer_steps, args.sync_every)
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
