"""Pipelined-vs-sync commit at a config where the vote RPC costs something.

Companion to ``quorum_overlap`` (same protocol): TWO replica groups over
the host TCP plane, with a synthetic round-trip injected into the
``should_commit`` vote RPC (``--rtt-ms``, default 10 — the off-host
control-plane hop of the reference README topology; for a multi-host
group the rank-0 manager server is a network hop away from every other
rank, so the vote barrier pays it every step). Sync mode pays
``work + rtt`` serially per step; pipelined mode issues the vote
asynchronously and the NEXT step's forward pass covers the RTT
(``max(work, rtt)``), with the speculative-update/rollback machinery
live (no faults are injected here, so no rollbacks occur — the
fault-path parity is covered by tests/test_commit_pipeline.py).

Protocol: interleaved A/B (pipelined, sync, pipelined, ...) with
``--runs`` pairs (default 7), reporting per-variant median and spread —
one hot pair would let host contamination on a single leg fabricate the
result.

Run: ``python -m torchft_tpu.benchmarks.commit_pipeline`` (CPU platform;
prints one JSON line).
"""

import json
import time
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from typing import List


def _install_vote_rtt(rtt_s: float) -> None:
    """Inject the synthetic RTT into EVERY ManagerClient.should_commit in
    this process (class-level, so the pipelined variant's dedicated vote
    client takes the identical delayed path as the sync variant's shared
    client). The quorum RPC is untouched: async quorum already hides it,
    and this extra isolates the COMMIT barrier."""
    from torchft_tpu.coordination import ManagerClient

    if getattr(ManagerClient, "_cp_bench_patched", False):
        return
    real = ManagerClient.should_commit

    def slow(self, *args, **kwargs):
        time.sleep(rtt_s)
        return real(self, *args, **kwargs)

    ManagerClient.should_commit = slow
    ManagerClient._cp_bench_patched = True


def _train_group(
    replica_id: int,
    lighthouse_addr: str,
    pipelined: bool,
    steps: int,
    work_ms: float,
) -> float:
    """One replica group (thread): real Manager + TCP collectives, a
    fixed-duration 'forward pass', and the per-step quorum+commit path.
    Returns steps/s for the timed window."""
    import numpy as np

    from torchft_tpu.collectives import CollectivesTcp
    from torchft_tpu.manager import Manager
    from torchft_tpu.store import StoreServer

    store = StoreServer()
    manager = Manager(
        collectives=CollectivesTcp(timeout=timedelta(seconds=20)),
        load_state_dict=lambda s: None,
        state_dict=lambda: {},
        min_replica_size=2,
        replica_id=f"cp_{replica_id}",
        store_addr=store.address(),
        rank=0,
        world_size=1,
        lighthouse_addr=lighthouse_addr,
        use_async_quorum=True,
        commit_pipeline=pipelined,
        timeout=timedelta(seconds=20),
    )

    grad = np.ones(1 << 16, dtype=np.float32)
    try:
        def step() -> None:
            manager.start_quorum()
            # the "forward pass": sleep, not a busy-wait — two groups
            # share this box and a GIL-holding spin would starve the
            # async quorum/vote threads, corrupting the very ratio being
            # measured. sleep models off-host device compute faithfully.
            # In pipelined mode the PREVIOUS step's vote RTT hides here.
            time.sleep(work_ms / 1e3)
            if pipelined:
                manager.resolve_pending_commit()
            manager.allreduce(grad.copy()).wait()
            if pipelined and manager.speculation_allowed():
                # same gate the trainers use: a healing/doomed step takes
                # the sync path (e.g. the cold-start quorum marks the
                # later joiner healing)
                manager.should_commit_async()
            else:
                manager.should_commit()

        for _ in range(3):
            step()  # warmup: first quorum forms the group
        t0 = time.perf_counter()
        for _ in range(steps):
            step()
        if pipelined:
            # the trailing vote belongs to the timed work — resolve it
            # inside the window so both variants count `steps` full votes
            manager.resolve_pending_commit(rearm=False)
        return steps / (time.perf_counter() - t0)
    finally:
        manager.shutdown(wait=False)
        store.shutdown()


def _one_run(lighthouse_addr: str, pipelined: bool, steps: int,
             work_ms: float) -> float:
    with ThreadPoolExecutor(max_workers=2) as ex:
        futs = [
            ex.submit(
                _train_group, g, lighthouse_addr, pipelined, steps, work_ms
            )
            for g in range(2)
        ]
        rates = [f.result() for f in futs]
    return min(rates)  # the group rate is gated by the slower member


def main() -> None:
    import argparse

    from torchft_tpu import telemetry
    from torchft_tpu.coordination import LighthouseServer

    ap = argparse.ArgumentParser()
    ap.add_argument("--rtt-ms", type=float, default=10.0)
    ap.add_argument("--runs", type=int, default=7)
    # 25 steps/leg: shorter legs let setup jitter dominate the medians
    # (15-step legs swung ±45% on this box; 25-step legs hold ~±2%)
    ap.add_argument("--steps", type=int, default=25)
    ap.add_argument("--work-ms", type=float, default=30.0)
    args = ap.parse_args()

    _install_vote_rtt(args.rtt_ms / 1e3)

    piped_runs: List[float] = []
    sync_runs: List[float] = []
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    try:
        for _ in range(args.runs):  # interleaved: both see the same drift
            piped_runs.append(
                _one_run(lighthouse.address(), True, args.steps, args.work_ms)
            )
            sync_runs.append(
                _one_run(lighthouse.address(), False, args.steps, args.work_ms)
            )
    finally:
        lighthouse.shutdown()

    piped_runs.sort()
    sync_runs.sort()
    p_med = piped_runs[len(piped_runs) // 2]
    s_med = sync_runs[len(sync_runs) // 2]
    print(json.dumps({
        "pipelined_steps_per_sec": round(p_med, 3),
        "sync_steps_per_sec": round(s_med, 3),
        "pipelined_gain_pct": round((p_med / s_med - 1) * 100.0, 2),
        "pipelined_runs": [round(r, 3) for r in piped_runs],
        "sync_runs": [round(r, 3) for r in sync_runs],
        "pipelined_spread_pct": round(
            (piped_runs[-1] - piped_runs[0]) / p_med * 100.0, 1
        ),
        "sync_spread_pct": round(
            (sync_runs[-1] - sync_runs[0]) / s_med * 100.0, 1
        ),
        # no faults injected: any rollback here would be a bug
        "rollbacks": int(telemetry.COMMIT_PIPELINE_ROLLBACKS.value),
        "config": f"2 groups, host TCP plane, synthetic +{args.rtt_ms} ms "
        f"RTT on the should_commit RPC, {args.work_ms} ms forward, "
        f"interleaved median of {args.runs}",
    }))


if __name__ == "__main__":
    main()
