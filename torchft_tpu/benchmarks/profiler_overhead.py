"""Always-on profiler overhead: armed-at-default-Hz vs disarmed, the
SAME headline FT leg, interleaved A/B medians (ISSUE 12).

The diagnosis plane's whole premise is that the samplers are cheap
enough to leave on for the life of the trainer — this row is that claim
as a measured gate instead of an assumption. Each leg runs the real
headline loop (quorum + grads + commit vote through the instrumented
Manager, the same path ``bench.py``'s headline measures) with BOTH
samplers either armed at the default rate (native SIGPROF sampler over
the dp/rpc threads + the Python ``sys._current_frames`` thread) or
fully disarmed (hz=0 — the zero-cost path). Legs interleave so both
variants see the same box drift; medians are compared.

Acceptance: ``overhead_pct <= gate_pct`` (2%). ``--smoke`` runs a
reduced config and exits nonzero past the gate — the
``scripts/premerge.sh`` leg.

Prints one JSON object on the last stdout line (the
``_run_json_subprocess`` contract).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def measure(
    runs: int, steps: int, warmup: int, batch: int, seq: int
) -> dict:
    # import inside: bench.py's subprocess contract, and the headline
    # model config must come from bench.py so the two rows can never
    # silently diverge
    sys.path.insert(
        0,
        os.path.normpath(
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "..", "..")
        ),
    )
    from bench import headline_config, train_bench

    from torchft_tpu.telemetry.profiler import (
        DEFAULT_HZ,
        PROFILER,
        native_set_hz,
        poll_native_samples,
    )

    cfg = headline_config()
    armed: list = []
    disarmed: list = []

    def set_armed(on: bool) -> None:
        hz = DEFAULT_HZ if on else 0.0
        PROFILER.set_hz(hz)
        native_set_hz(hz)

    # one throwaway leg first: jit compilation must not land inside
    # either variant's timed window
    set_armed(False)
    train_bench(cfg, batch, seq, 1, 1, averaging=True)

    for _ in range(runs):  # interleaved: both variants see the same drift
        set_armed(True)
        armed.append(train_bench(cfg, batch, seq, steps, warmup,
                                 averaging=True)[0])
        set_armed(False)
        disarmed.append(train_bench(cfg, batch, seq, steps, warmup,
                                    averaging=True)[0])
    set_armed(True)  # leave the process in the always-on default
    native_samples = poll_native_samples()
    py_samples = PROFILER.samples_total()

    armed.sort()
    disarmed.sort()
    a = armed[len(armed) // 2]
    d = disarmed[len(disarmed) // 2]
    overhead = (d - a) / d * 100.0 if d else 0.0
    return {
        "_gate_presence": True,
        "steps_per_sec": round(a, 4),
        "steps_per_sec_disarmed": round(d, 4),
        "overhead_pct": round(overhead, 2),
        "gate_pct": 2.0,
        "within_gate": overhead <= 2.0,
        "hz": DEFAULT_HZ,
        "runs_armed": [round(r, 4) for r in armed],
        "runs_disarmed": [round(r, 4) for r in disarmed],
        "py_samples": int(py_samples),
        "native_samples": int(native_samples),
        "config": {"batch": batch, "seq": seq, "steps": steps,
                   "warmup": warmup, "runs": runs},
        "note": "headline FT leg armed at default Hz vs disarmed, "
        "interleaved medians; the always-on claim's measured gate "
        "(<=2%). Single-run medians on a loaded 1-core box can swing "
        "past the gate on weather — re-run before believing a breach.",
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument(
        "--smoke", action="store_true",
        help="reduced premerge leg: tiny batch/seq, exit 1 past the gate",
    )
    args = ap.parse_args()

    if args.smoke:
        batch, seq, steps = 2, 64, args.steps or 3
    else:
        batch, seq, steps = 4, 128, args.steps or 5

    row = measure(args.runs, steps, args.warmup, batch, seq)
    print(json.dumps({"profiler_overhead": row}))
    if args.smoke and not row["within_gate"]:
        print(
            f"profiler overhead {row['overhead_pct']}% exceeds the "
            f"{row['gate_pct']}% gate",
            file=sys.stderr,
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
