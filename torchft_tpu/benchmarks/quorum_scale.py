"""Quorum fan-out latency vs group count — the HA open item's measurement.

The ROADMAP's HA control-plane item names the single lighthouse as an
O(N) fan-in bottleneck and asks for "a bench row for quorum p50/p99 vs
group count" before any hierarchical-quorum work can claim a win. PR 8
landed the measurement substrate (the native ``quorum.fanout`` latency
histogram — one observation per ManagerSrv ``lh.quorum`` long-poll round
trip); this module drives it at scale: **N simulated manager clients
against ONE lighthouse** for N in ``--groups`` (default
``8,32,64,128,256`` — the ROADMAP explicitly asks for 256+), each doing
``--rounds`` full quorum rounds, then snapshots the in-process lathist
and reports per-N ``quorum.fanout`` p50/p99.

"Simulated" means real protocol, minimal weight: every group is a real
in-process ``ManagerServer`` (world_size=1 — heartbeat loop, lh.quorum
long-poll, the exact fan-in the lighthouse pays) plus one thread driving
``mgr.quorum`` through a real ``ManagerClient``. Everything shares this
process, so ``_native.lathist_snapshot`` sees every fan-out observation
and the numbers are pure control-plane cost (no training, no data
plane).

Caveat recorded in the row: all N servers time-share this host's cores,
so large N on a small box measures scheduling pressure as well as
protocol cost — the cross-N *shape* (does p99 grow superlinearly?) is
the signal, the absolute values are box-bound like every other row.

Run: ``python -m torchft_tpu.benchmarks.quorum_scale`` (CPU platform;
prints one JSON line: ``{"quorum_scale": {...}}``).
"""

import argparse
import json
import threading
import time
from datetime import timedelta
from typing import Dict, List


def _quorum_round(client, rank: int, step: int, timeout_s: float) -> None:
    client._quorum(
        rank=rank,
        step=step,
        checkpoint_metadata="",
        shrink_only=False,
        timeout=timedelta(seconds=timeout_s),
    )


def measure_groups(n: int, rounds: int, timeout_s: float) -> Dict:
    """One lighthouse, ``n`` manager servers + clients, ``rounds`` full
    quorum rounds; returns the ``quorum.fanout`` digest for exactly this
    configuration (the histogram is reset on entry)."""
    from torchft_tpu import _native
    from torchft_tpu.coordination import (
        LighthouseServer,
        ManagerClient,
        ManagerServer,
    )
    from torchft_tpu.telemetry.anatomy import lathist_quantile

    _native.lathist_reset()
    lighthouse = LighthouseServer(
        bind="[::]:0",
        min_replicas=n,
        # long join window: N servers booting on a small box must not
        # split the first quorum round
        join_timeout_ms=60000,
    )
    managers: List[ManagerServer] = []
    clients: List[ManagerClient] = []
    errors: List[str] = []
    t_setup = time.perf_counter()
    try:
        for i in range(n):
            managers.append(
                ManagerServer(
                    replica_id=f"qs_{i}",
                    lighthouse_addr=lighthouse.address(),
                    hostname="localhost",
                    bind="[::]:0",
                    store_addr="unused:0",
                    world_size=1,
                    # modest heartbeat so N groups don't saturate the
                    # box with heartbeat traffic between rounds
                    heartbeat_interval=timedelta(milliseconds=500),
                    connect_timeout=timedelta(seconds=timeout_s),
                )
            )
        clients = [
            ManagerClient(
                m.address(), connect_timeout=timedelta(seconds=timeout_s)
            )
            for m in managers
        ]
        setup_s = time.perf_counter() - t_setup

        t0 = time.perf_counter()
        for rnd in range(rounds):
            threads = []
            for i, c in enumerate(clients):
                th = threading.Thread(
                    target=lambda c=c, i=i: (
                        errors.append(f"g{i}: fail")
                        if _try(_quorum_round, c, 0, rnd, timeout_s)
                        else None
                    ),
                    name=f"qs_client_{i}",
                )
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
        wall_s = time.perf_counter() - t0

        snap = _native.lathist_snapshot().get("quorum.fanout", {})
        count = int(snap.get("count", 0))
        out = {
            "groups": n,
            "rounds": rounds,
            "fanout_count": count,
            "fanout_p50_s": round(lathist_quantile(snap, 0.5), 6)
            if count
            else None,
            "fanout_p99_s": round(lathist_quantile(snap, 0.99), 6)
            if count
            else None,
            "setup_s": round(setup_s, 3),
            "wall_s": round(wall_s, 3),
            "errors": len(errors),
        }
        if count < n * rounds:
            out["note"] = (
                f"only {count}/{n * rounds} fan-outs recorded "
                "(client errors or joins folded into one round)"
            )
        return out
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        for m in managers:
            try:
                m.shutdown()
            except Exception:  # noqa: BLE001
                pass
        lighthouse.shutdown()


def _try(fn, *args) -> bool:
    """Returns True on FAILURE (reads nicer at the call site above)."""
    try:
        fn(*args)
        return False
    except Exception:  # noqa: BLE001 — counted, not raised
        return True


def _raise_fd_limit(n: int) -> None:
    """256 manager servers need ~8 fds each (listener + lighthouse
    quorum/digest/heartbeat clients + accepted conns on the lighthouse
    side); the default 1024 soft limit dies around N=128. Raise the soft
    limit toward the hard limit, best-effort."""
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    want = min(hard, max(soft, n))
    if want > soft:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))
        except (ValueError, OSError):
            pass


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", default="8,32,64,128,256",
                    help="comma-separated group counts")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--timeout", type=float, default=120.0)
    args = ap.parse_args()
    _raise_fd_limit(
        16 * max(
            [int(x) for x in args.groups.split(",") if x] or [1]
        )
    )

    rows: Dict[str, Dict] = {}
    for n in [int(x) for x in args.groups.split(",") if x]:
        try:
            rows[f"groups_{n}"] = measure_groups(
                n, args.rounds, args.timeout
            )
        except Exception as e:  # noqa: BLE001 — partial results still land
            rows[f"groups_{n}"] = {"error": str(e)}
    print(json.dumps({
        "quorum_scale": {
            "_gate_presence": True,
            **rows,
            "note": "quorum.fanout p50/p99 per group count (N in-process "
            "manager servers against one lighthouse, native lathist "
            "substrate from PR 8); shape-over-N is the signal, absolutes "
            "are box-bound",
        }
    }))


if __name__ == "__main__":
    main()
