"""Quorum fan-out latency vs group count — the HA open item's measurement.

The ROADMAP's HA control-plane item names the single lighthouse as an
O(N) fan-in bottleneck and asks for "a bench row for quorum p50/p99 vs
group count" before any hierarchical-quorum work can claim a win. PR 8
landed the measurement substrate (the native ``quorum.fanout`` latency
histogram — one observation per ManagerSrv ``lh.quorum`` long-poll round
trip); this module drives it at scale: **N simulated manager clients
against ONE lighthouse** for N in ``--groups`` (default
``8,32,64,128,256`` — the ROADMAP explicitly asks for 256+), each doing
``--rounds`` full quorum rounds, then snapshots the in-process lathist
and reports per-N ``quorum.fanout`` p50/p99.

"Simulated" means real protocol, minimal weight: every group is a real
in-process ``ManagerServer`` (world_size=1 — heartbeat loop, lh.quorum
long-poll, the exact fan-in the lighthouse pays) plus one thread driving
``mgr.quorum`` through a real ``ManagerClient``. Everything shares this
process, so ``_native.lathist_snapshot`` sees every fan-out observation
and the numbers are pure control-plane cost (no training, no data
plane). Default group counts are ``8,32,64,128,256,512,1024`` — the
512/1024 points are the ISSUE 16 sublinear-telemetry evidence.

Each N additionally runs two telemetry legs (ISSUE 16): the same
synthetic per-round report shipped as the legacy full-JSON payload vs
the delta encoding, with wire bytes per step per replica recorded for
both, plus /fleet.json scrape p50/p99 against the full /cluster.json
sweep it replaces. The delta steady-state number is the acceptance
signal: it must stay ~flat as N grows while the full-JSON leg scales
with report size.

Caveat recorded in the row: all N servers time-share this host's cores,
so large N on a small box measures scheduling pressure as well as
protocol cost — the cross-N *shape* (does p99 grow superlinearly?) is
the signal, the absolute values are box-bound like every other row.

Run: ``python -m torchft_tpu.benchmarks.quorum_scale`` (CPU platform;
prints one JSON line: ``{"quorum_scale": {...}}``).
"""

import argparse
import json
import threading
import time
import urllib.request
from datetime import timedelta
from typing import Dict, List, Optional


def _quorum_round(
    client,
    rank: int,
    step: int,
    timeout_s: float,
    telemetry_payload: Optional[Dict] = None,
):
    return client._quorum(
        rank=rank,
        step=step,
        checkpoint_metadata="",
        shrink_only=False,
        timeout=timedelta(seconds=timeout_s),
        telemetry_payload=telemetry_payload,
    )


def _synthetic_report(i: int, step: int) -> Dict:
    """Per-group report with realistic churn: the health scalars move
    every step, ONE histogram bucket increments, the counters digest
    bumps a couple of counters. Deterministic (no RNG) so full-JSON and
    delta legs encode byte-identical logical content."""
    bucket = 10 + (i % 5)
    return {
        "step": step,
        "epoch": 1,
        "stuck": False,
        "slo_breach": False,
        "local_step_p50_s": 0.1 + (i % 17) * 1e-3,
        "last_heal_ts": 0.0,
        "summary": {
            "quorums": step,
            "commits": step,
            "heals_recv": 0,
            "participants": 1,
        },
        "anatomy": {
            "steps": step,
            "wall_p50_s": 0.2,
            "wall_p99_s": 0.3,
            "local_p50_s": 0.1,
            "phases": {
                "compute": {"p50_s": 0.08, "p99_s": 0.1, "total_s": 0.1 * step},
                "quorum_wait": {
                    "p50_s": 0.02,
                    "p99_s": 0.05,
                    "total_s": 0.02 * step,
                },
            },
        },
        "hist": {
            "wall": {str(bucket): step, str(bucket + 1): 1},
            "local": {str(bucket - 1): step},
        },
        "series": {"step_wall_s": 0.2, "step_local_s": 0.1},
    }


def _telemetry_legs(
    n: int,
    clients: List,
    base_step: int,
    timeout_s: float,
    lighthouse_addr: str,
) -> Dict:
    """ISSUE 16 evidence: the same synthetic per-round report shipped
    through the legacy full-JSON payload vs the delta encoding, bytes
    measured with the real wire codec on both legs — plus /fleet.json
    scrape percentiles and the full /cluster.json sweep they replace."""
    from torchft_tpu.telemetry.fleetdelta import DeltaEncoder
    from torchft_tpu.utils.wire import encode as wire_encode

    out: Dict = {}
    lock = threading.Lock()

    def drive(payload_fn, rounds: int) -> List[int]:
        """Run `rounds` telemetry-carrying quorum rounds; returns
        per-round total wire bytes across all n groups."""
        per_round: List[int] = []
        for rnd in range(rounds):
            step = base_step + rnd
            total = [0]
            threads = []

            def go(i, c, step=step, total=total):
                payload = payload_fn(i, step)
                nbytes = len(wire_encode(payload))
                try:
                    r = _quorum_round(c, 0, step, timeout_s, payload)
                except Exception:  # noqa: BLE001 — counted upstream
                    return
                with lock:
                    total[0] += nbytes
                ack_fn = getattr(payload_fn, "on_ack", None)
                if ack_fn is not None and r.telemetry_ack:
                    ack_fn(i, r.telemetry_ack)

            for i, c in enumerate(clients):
                th = threading.Thread(target=lambda i=i, c=c: go(i, c))
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
            per_round.append(total[0])
        return per_round

    # --- full-JSON leg (TORCHFT_TELEMETRY_DELTA=0 shape): the whole
    # report re-serialized and re-sent every round
    def full_payload(i: int, step: int) -> Dict:
        rep = _synthetic_report(i, step)
        return {
            "summary": json.dumps(rep["summary"], separators=(",", ":")),
            "anatomy": json.dumps(rep["anatomy"], separators=(",", ":")),
            "local_step_p50_s": rep["local_step_p50_s"],
            "slo_breach": rep["slo_breach"],
            "step": rep["step"],
            "epoch": rep["epoch"],
            "stuck": rep["stuck"],
            "last_heal_ts": rep["last_heal_ts"],
            "series": rep["series"],
        }

    full_rounds = drive(full_payload, 2)
    out["full_bytes_per_step_per_replica"] = round(
        sum(full_rounds) / (len(full_rounds) * n), 1
    )

    # --- delta leg: one encoder per group, acks fed back from the
    # quorum reply; round 0 is the FULL bootstrap, later rounds are the
    # steady state the 1000-group scaling claim is about
    encoders = [DeltaEncoder() for _ in range(n)]

    def delta_payload(i: int, step: int) -> Dict:
        return {"tdelta": encoders[i].encode(_synthetic_report(i, step))}

    delta_payload.on_ack = lambda i, ack: encoders[i].on_ack(ack)
    delta_rounds = drive(delta_payload, 3)
    out["delta_first_full_bytes_per_replica"] = round(delta_rounds[0] / n, 1)
    steady = delta_rounds[1:]
    out["delta_bytes_per_step_per_replica"] = round(
        sum(steady) / (len(steady) * n), 1
    )

    # --- scrape latencies: the O(#hists) rollup vs the O(fleet) sweep
    def scrape(path: str):
        t0 = time.perf_counter()
        with urllib.request.urlopen(
            f"{lighthouse_addr}{path}", timeout=timeout_s
        ) as resp:
            body = resp.read()
        return time.perf_counter() - t0, len(body)

    fleet_lats: List[float] = []
    fleet_bytes = 0
    for _ in range(15):
        dt, fleet_bytes = scrape("/fleet.json")
        fleet_lats.append(dt)
    fleet_lats.sort()
    out["fleet_scrape_p50_s"] = round(
        fleet_lats[len(fleet_lats) // 2], 6
    )
    out["fleet_scrape_p99_s"] = round(fleet_lats[-1], 6)
    out["fleet_json_bytes"] = fleet_bytes
    sweep_s, sweep_bytes = scrape("/cluster.json")
    out["cluster_sweep_s"] = round(sweep_s, 6)
    out["cluster_json_bytes"] = sweep_bytes
    return out


def measure_groups(n: int, rounds: int, timeout_s: float) -> Dict:
    """One lighthouse, ``n`` manager servers + clients, ``rounds`` full
    quorum rounds; returns the ``quorum.fanout`` digest for exactly this
    configuration (the histogram is reset on entry)."""
    from torchft_tpu import _native
    from torchft_tpu.coordination import (
        LighthouseServer,
        ManagerClient,
        ManagerServer,
    )
    from torchft_tpu.telemetry.anatomy import lathist_quantile

    _native.lathist_reset()
    lighthouse = LighthouseServer(
        bind="[::]:0",
        min_replicas=n,
        # long join window: N servers booting on a small box must not
        # split the first quorum round
        join_timeout_ms=60000,
    )
    managers: List[ManagerServer] = []
    clients: List[ManagerClient] = []
    errors: List[str] = []
    t_setup = time.perf_counter()
    try:
        for i in range(n):
            managers.append(
                ManagerServer(
                    replica_id=f"qs_{i}",
                    lighthouse_addr=lighthouse.address(),
                    hostname="localhost",
                    bind="[::]:0",
                    store_addr="unused:0",
                    world_size=1,
                    # modest heartbeat so N groups don't saturate the
                    # box with heartbeat traffic between rounds
                    heartbeat_interval=timedelta(milliseconds=500),
                    connect_timeout=timedelta(seconds=timeout_s),
                )
            )
        clients = [
            ManagerClient(
                m.address(), connect_timeout=timedelta(seconds=timeout_s)
            )
            for m in managers
        ]
        setup_s = time.perf_counter() - t_setup

        t0 = time.perf_counter()
        for rnd in range(rounds):
            threads = []
            for i, c in enumerate(clients):
                th = threading.Thread(
                    target=lambda c=c, i=i: (
                        errors.append(f"g{i}: fail")
                        if _try(_quorum_round, c, 0, rnd, timeout_s)
                        else None
                    ),
                    name=f"qs_client_{i}",
                )
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
        wall_s = time.perf_counter() - t0

        # snapshot BEFORE the telemetry legs so fanout_p50/p99 keep
        # their original meaning (bare-quorum fan-in cost)
        snap = _native.lathist_snapshot().get("quorum.fanout", {})
        count = int(snap.get("count", 0))
        out = {
            "groups": n,
            "rounds": rounds,
            "fanout_count": count,
            "fanout_p50_s": round(lathist_quantile(snap, 0.5), 6)
            if count
            else None,
            "fanout_p99_s": round(lathist_quantile(snap, 0.99), 6)
            if count
            else None,
            "setup_s": round(setup_s, 3),
            "wall_s": round(wall_s, 3),
            "errors": len(errors),
        }
        if count < n * rounds:
            out["note"] = (
                f"only {count}/{n * rounds} fan-outs recorded "
                "(client errors or joins folded into one round)"
            )
        try:
            out["telemetry"] = _telemetry_legs(
                n, clients, rounds, timeout_s, lighthouse.address()
            )
        except Exception as e:  # noqa: BLE001 — fanout row still lands
            out["telemetry"] = {"error": str(e)}
        return out
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        for m in managers:
            try:
                m.shutdown()
            except Exception:  # noqa: BLE001
                pass
        lighthouse.shutdown()


def _try(fn, *args) -> bool:
    """Returns True on FAILURE (reads nicer at the call site above)."""
    try:
        fn(*args)
        return False
    except Exception:  # noqa: BLE001 — counted, not raised
        return True


def _raise_fd_limit(n: int) -> None:
    """256 manager servers need ~8 fds each (listener + lighthouse
    quorum/digest/heartbeat clients + accepted conns on the lighthouse
    side); the default 1024 soft limit dies around N=128. Raise the soft
    limit toward the hard limit, best-effort."""
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    want = min(hard, max(soft, n))
    if want > soft:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))
        except (ValueError, OSError):
            pass


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", default="8,32,64,128,256,512,1024",
                    help="comma-separated group counts")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--timeout", type=float, default=120.0)
    args = ap.parse_args()
    _raise_fd_limit(
        16 * max(
            [int(x) for x in args.groups.split(",") if x] or [1]
        )
    )

    rows: Dict[str, Dict] = {}
    for n in [int(x) for x in args.groups.split(",") if x]:
        try:
            rows[f"groups_{n}"] = measure_groups(
                n, args.rounds, args.timeout
            )
        except Exception as e:  # noqa: BLE001 — partial results still land
            rows[f"groups_{n}"] = {"error": str(e)}
    print(json.dumps({
        "quorum_scale": {
            "_gate_presence": True,
            **rows,
            "note": "quorum.fanout p50/p99 per group count (N in-process "
            "manager servers against one lighthouse, native lathist "
            "substrate from PR 8); shape-over-N is the signal, absolutes "
            "are box-bound",
        }
    }))


if __name__ == "__main__":
    main()
