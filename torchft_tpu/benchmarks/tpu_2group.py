"""Two replica-group PROCESSES time-sharing the real TPU chip.

Round-4 review weak #7/#8: ``cpu_mesh_2group`` is a CPU proxy and the
r02 "~2% on-chip" figure predates the native plane. This row runs the
real topology this box supports: two OS processes, each driving the one
tunneled chip (the tunnel time-multiplexes clients), cross-group
averaging over the HOST plane (CMA/TCP). The device-dist plane cannot
run here — the axon tunnel plugin ignores multi-controller
``jax.distributed`` (each process still sees process_count()==1, so a
2-process cohort can never own >= 1 device each); that constraint is
itself a finding this row records.

A second box constraint shapes the model size: the tunnel moves
device<->host arrays at ~20 MB/s (measured: 20-35 s/step for the
58M-param headline model's 234 MB gradient round trip), so full-size
host-plane averaging of on-chip grads is tunnel-bound, not
averaging-bound. On a real v5e host D2H is PCIe-fast and the wire cost
is what cpu_mesh_2group / crossgroup_host_plane price; THIS row
therefore uses a small model (~2M params, 9 MB grads) so the numbers
mean "chip time-sharing + averaging", not "tunnel RPC bandwidth".

Protocol: first a SINGLE group at the same per-group batch measures the
solo rate R1 (own process, chip to itself). Then two groups run
concurrently; ideal time-sharing with free averaging would give each
R1/2. The reported overhead is how far the slower group falls below
that ideal.

Run: ``python -m torchft_tpu.benchmarks.tpu_2group`` — prints one JSON
line. Internal worker mode: ``--worker`` (driven by main()).
"""

import json
import os
import subprocess
import sys
import time
from datetime import timedelta

_STEPS = 6
_WARMUP = 2
_BATCH = 4  # per group
_SEQ = 512


def _worker(min_groups: int, lighthouse_addr: str, gid: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchft_tpu.collectives import CollectivesTcp
    from torchft_tpu.ddp import allreduce_gradients
    from torchft_tpu.manager import Manager
    from torchft_tpu.models.transformer import TransformerConfig
    from torchft_tpu.parallel.mesh import MeshConfig, make_mesh
    from torchft_tpu.parallel.train_step import TrainStep
    from torchft_tpu.store import StoreServer

    # small on purpose: grads must fit the tunnel's ~20 MB/s D2H (see
    # module docstring) or the row measures the tunnel, not the framework
    cfg = TransformerConfig(
        vocab_size=8192, d_model=128, n_layers=2, n_heads=4,
        head_dim=32, d_ff=384, dtype=jnp.bfloat16,
    )
    store = StoreServer()
    manager = Manager(
        collectives=CollectivesTcp(timeout=timedelta(seconds=60)),
        load_state_dict=lambda s: None,
        state_dict=lambda: {},
        min_replica_size=min_groups,
        replica_id=f"tpu2g_{gid}",
        store_addr=store.address(),
        rank=0,
        world_size=1,
        lighthouse_addr=lighthouse_addr,
        timeout=timedelta(seconds=60),
    )
    try:
        mesh = make_mesh(MeshConfig(dp=1))
        ts = TrainStep(cfg, optax.adamw(3e-4), mesh)
        params = ts.init_params(jax.random.PRNGKey(0))
        opt_state = ts.init_opt(params)
        rng = np.random.default_rng(gid)
        tokens = ts.shard_batch(
            jnp.asarray(
                rng.integers(0, cfg.vocab_size, (_BATCH, _SEQ)), jnp.int32
            )
        )

        def ft_step(params, opt_state):
            manager.start_quorum()
            loss, grads = ts.grads(params, tokens)
            grads = allreduce_gradients(manager, grads)
            if manager.should_commit():
                params, opt_state = ts.apply(params, opt_state, grads)
            return loss, params, opt_state

        for _ in range(_WARMUP):
            loss, params, opt_state = ft_step(params, opt_state)
        float(loss)  # host fence (tunnel: block_until_ready lies)
        t0 = time.perf_counter()
        for _ in range(_STEPS):
            loss, params, opt_state = ft_step(params, opt_state)
        float(loss)
        sps = _STEPS / (time.perf_counter() - t0)
        print(json.dumps({
            "steps_per_sec": round(sps, 4),
            "plane": manager._collectives.plane_info()
            if hasattr(manager._collectives, "plane_info") else "?",
        }))
    finally:
        manager.shutdown(wait=False)
        store.shutdown()


def _spawn(min_groups: int, lighthouse_addr: str, gid: int):
    return subprocess.Popen(
        [
            sys.executable, "-m", "torchft_tpu.benchmarks.tpu_2group",
            "--worker", str(min_groups), lighthouse_addr, str(gid),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        env=dict(os.environ),
    )


def _collect(procs, timeout_s: float):
    outs = []
    deadline = time.monotonic() + timeout_s
    try:
        for p in procs:
            out, _ = p.communicate(
                timeout=max(1.0, deadline - time.monotonic())
            )
            if p.returncode != 0:
                raise RuntimeError(f"worker rc={p.returncode}")
            outs.append(json.loads(out.decode().strip().splitlines()[-1]))
        return outs
    except BaseException:
        # a failed/timed-out worker must not leave its sibling running
        # against the single chip while bench.py moves to the next extra
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        raise


def main() -> None:
    from torchft_tpu.coordination import LighthouseServer

    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        _worker(int(sys.argv[2]), sys.argv[3], int(sys.argv[4]))
        return

    # solo reference: same per-group batch, chip to itself
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=1)
    try:
        solo = _collect([_spawn(1, lighthouse.address(), 0)], 600)[0]
    finally:
        lighthouse.shutdown()

    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    try:
        pair = _collect(
            [_spawn(2, lighthouse.address(), g) for g in range(2)], 900
        )
    finally:
        lighthouse.shutdown()

    r1 = solo["steps_per_sec"]
    pair_rates = sorted(p["steps_per_sec"] for p in pair)
    ideal = r1 / 2.0
    print(json.dumps({
        "solo_steps_per_sec": r1,
        "pair_steps_per_sec": pair_rates,
        "pair_combined_tokens_per_sec": round(
            sum(pair_rates) * _BATCH * _SEQ
        ),
        "overhead_vs_timeshare_pct": round(
            (1.0 - pair_rates[0] / ideal) * 100.0, 1
        ),
        "plane": pair[0]["plane"],
        "config": f"2 processes x 1 real chip (tunnel time-multiplexed), "
        f"d128 L2 b{_BATCH} s{_SEQ} per group (~2M params), full-gradient "
        f"host-plane averaging; overhead is vs ideal R_solo/2 and is an "
        f"UPPER bound (the ~27 MB/step tunnel transfer does not halve "
        f"with chip time-sharing). Small model because the tunnel's "
        f"~20 MB/s D2H dominates otherwise; device-dist impossible here "
        f"(tunnel plugin ignores multi-controller jax.distributed)",
    }))


if __name__ == "__main__":
    main()
