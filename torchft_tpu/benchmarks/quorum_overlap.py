"""Async-vs-sync quorum at a config where the quorum RPC costs something.

Round-4 review weak #2/#3: the old ``quorum_overlap`` extra compared
async/sync at the single-group headline, where a localhost quorum RPC is
sub-millisecond against a ~50 ms step — the measured 0.19% "gain" was
noise, and citing it as evidence for ``use_async_quorum=True`` was
wrong. This module measures the regime the flag EXISTS for: TWO replica
groups over the host TCP plane with a synthetic round-trip injected into
the quorum RPC (``--rtt-ms``, default 10 — a modest intra-region DCN
hop; the lighthouse is the one deployment component expected off-host,
reference README topology). Async overlaps that RPC with the forward
pass; sync pays it serially every step.

Protocol: interleaved A/B (async, sync, async, ...) with ``--runs``
pairs (default 7), reporting per-variant median and spread — one hot
pair would let host contamination on a single leg fabricate the result.

Run: ``python -m torchft_tpu.benchmarks.quorum_overlap`` (CPU platform;
prints one JSON line).
"""

import json
import time
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from typing import List


def _train_group(
    replica_id: int,
    lighthouse_addr: str,
    use_async: bool,
    rtt_s: float,
    steps: int,
    work_ms: float,
) -> float:
    """One replica group (thread): real Manager + TCP collectives, a
    fixed-duration 'forward pass', and the per-step quorum+commit path.
    Returns steps/s for the timed window."""
    import numpy as np

    from torchft_tpu.collectives import CollectivesTcp
    from torchft_tpu.manager import Manager
    from torchft_tpu.store import StoreServer

    store = StoreServer()
    manager = Manager(
        collectives=CollectivesTcp(timeout=timedelta(seconds=20)),
        load_state_dict=lambda s: None,
        state_dict=lambda: {},
        min_replica_size=2,
        replica_id=f"qo_{replica_id}",
        store_addr=store.address(),
        rank=0,
        world_size=1,
        lighthouse_addr=lighthouse_addr,
        use_async_quorum=use_async,
        timeout=timedelta(seconds=20),
    )
    # Synthetic RTT on the quorum RPC only (the long-poll the flag is
    # meant to hide). Injected at the client wrapper so async and sync
    # take the identical delayed path; commit votes ride the group's OWN
    # manager server on localhost and stay fast, as in a real deployment
    # where the lighthouse is the remote component.
    real_quorum = manager._client._quorum

    def slow_quorum(*args, **kwargs):
        time.sleep(rtt_s)
        return real_quorum(*args, **kwargs)

    manager._client._quorum = slow_quorum

    grad = np.ones(1 << 16, dtype=np.float32)
    try:
        def step() -> None:
            manager.start_quorum()
            # the "forward pass": sleep, not a busy-wait — two groups
            # share this 1-core box, and a GIL-holding spin would stretch
            # the nominal work_ms and starve the async-quorum executor,
            # corrupting the very ratio being measured. sleep models
            # off-host device compute faithfully (the host thread is idle
            # while the chip works).
            time.sleep(work_ms / 1e3)
            manager.allreduce(grad.copy()).wait()
            manager.should_commit()

        for _ in range(3):
            step()  # warmup: first quorum forms the group
        t0 = time.perf_counter()
        for _ in range(steps):
            step()
        return steps / (time.perf_counter() - t0)
    finally:
        manager.shutdown(wait=False)
        store.shutdown()


def _one_run(lighthouse_addr: str, use_async: bool, rtt_s: float,
             steps: int, work_ms: float) -> float:
    with ThreadPoolExecutor(max_workers=2) as ex:
        futs = [
            ex.submit(
                _train_group, g, lighthouse_addr, use_async, rtt_s, steps,
                work_ms,
            )
            for g in range(2)
        ]
        rates = [f.result() for f in futs]
    return min(rates)  # the group rate is gated by the slower member


def main() -> None:
    import argparse

    from torchft_tpu.coordination import LighthouseServer

    ap = argparse.ArgumentParser()
    ap.add_argument("--rtt-ms", type=float, default=10.0)
    ap.add_argument("--runs", type=int, default=7)
    ap.add_argument("--steps", type=int, default=15)
    ap.add_argument("--work-ms", type=float, default=30.0)
    args = ap.parse_args()

    async_runs: List[float] = []
    sync_runs: List[float] = []
    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
    try:
        for _ in range(args.runs):  # interleaved: both see the same drift
            async_runs.append(
                _one_run(lighthouse.address(), True, args.rtt_ms / 1e3,
                         args.steps, args.work_ms)
            )
            sync_runs.append(
                _one_run(lighthouse.address(), False, args.rtt_ms / 1e3,
                         args.steps, args.work_ms)
            )
    finally:
        lighthouse.shutdown()

    async_runs.sort()
    sync_runs.sort()
    a_med = async_runs[len(async_runs) // 2]
    s_med = sync_runs[len(sync_runs) // 2]
    print(json.dumps({
        "async_steps_per_sec": round(a_med, 3),
        "sync_steps_per_sec": round(s_med, 3),
        "async_gain_pct": round((a_med / s_med - 1) * 100.0, 2),
        "async_runs": [round(r, 3) for r in async_runs],
        "sync_runs": [round(r, 3) for r in sync_runs],
        "async_spread_pct": round(
            (async_runs[-1] - async_runs[0]) / a_med * 100.0, 1
        ),
        "sync_spread_pct": round(
            (sync_runs[-1] - sync_runs[0]) / s_med * 100.0, 1
        ),
        "config": f"2 groups, host TCP plane, synthetic +{args.rtt_ms} ms "
        f"RTT on the quorum RPC, {args.work_ms} ms forward, interleaved "
        f"median of {args.runs}",
    }))


if __name__ == "__main__":
    main()
