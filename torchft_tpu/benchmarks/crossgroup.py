"""Cross-process gradient-plane benchmark.

The round-2 review's top gap: nothing measured what the host data plane
(separate-process replica groups → D2H + TCP ring + H2D per step, the
topology of the BASELINE north-star 4×8-chip job) costs at 7B scale, and
the serial path left D2H, wire and H2D time additive.

This tool runs the REAL path: two replica groups as separate OS processes,
each with a full Manager (C++ lighthouse + quorum + commit) and a
``CollectivesTcp`` ring, averaging a synthetic gradient pytree through
``allreduce_gradients`` — once with the round-3 per-bucket pipeline, once
with the round-2 serial schedule (all transfers, then one wire op), with
and without bf16 wire compression. From the measured bytes/s it derives
the per-step averaging cost of the llama2-7b preset (the number the
review asked for), labeled as derived, not measured.

Usage::

    python -m torchft_tpu.benchmarks.crossgroup [--total-mb 256]

(Workers force ``JAX_PLATFORMS=cpu`` so the bench never competes with a
training job for the local chip; the wire path is identical either way —
only the D2H/H2D legs differ, and those are measured separately by the
headline bench on real HBM.)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from datetime import timedelta
from typing import Dict, List, Optional

# llama2-7b preset (examples/train_hsdp.py PRESETS) parameter count:
# embeddings + 32 × (4·d² attn + 3·d·d_ff mlp + 2·d norms) + final norm
# + (tied) output head — matches models/transformer.py's layout.
_7B_D, _7B_FF, _7B_L, _7B_V = 4096, 11008, 32, 32000
LLAMA2_7B_PARAMS = (
    _7B_V * _7B_D
    + _7B_L * (4 * _7B_D * _7B_D + 3 * _7B_D * _7B_FF + 2 * _7B_D)
    + _7B_D
    + _7B_V * _7B_D
)


def _raw_worker_main(argv: List[str]) -> None:
    """Pure data-plane rate: two processes, one big f32 allreduce, no
    Manager/quorum/JAX in the loop — isolates what the transport itself
    moves (the number comparable to a NCCL busbw measurement)."""
    parser = argparse.ArgumentParser()
    parser.add_argument("--gid", type=int, required=True)
    parser.add_argument("--store", required=True)
    parser.add_argument("--total-mb", type=float, required=True)
    parser.add_argument("--rounds", type=int, required=True)
    parser.add_argument("--wire-dtype", default="")
    args = parser.parse_args(argv)

    import numpy as np

    from torchft_tpu.collectives import CollectivesTcp, ReduceOp

    c = CollectivesTcp(
        timeout=timedelta(seconds=120),
        hostname="localhost",
        wire_dtype=args.wire_dtype or None,
    )
    c.configure(args.store, args.gid, 2)
    n = int(args.total_mb * 1024 * 1024 / 4)
    arr = np.full(n, float(args.gid + 1), dtype=np.float32)
    c.allreduce([arr], ReduceOp.AVG).wait(timedelta(seconds=120))  # warmup
    t0 = time.perf_counter()
    for _ in range(args.rounds):
        c.allreduce([arr], ReduceOp.AVG).wait(timedelta(seconds=120))
    elapsed = (time.perf_counter() - t0) / args.rounds
    print(
        json.dumps(
            {
                "gid": args.gid,
                "seconds_per_round": elapsed,
                "total_bytes": n * 4,
                "plane": c.plane_info(),
            }
        ),
        flush=True,
    )
    c.shutdown()


def _heal_worker_main(argv: List[str]) -> None:
    """Checkpoint-heal throughput: rank 0 serves a 256MB-class state over
    CollectivesTransport, rank 1 receives (the live-heal data path). With
    the p2p CMA fast path the payload is pulled at memcpy-class speed."""
    parser = argparse.ArgumentParser()
    parser.add_argument("--gid", type=int, required=True)
    parser.add_argument("--store", required=True)
    parser.add_argument("--total-mb", type=float, required=True)
    args = parser.parse_args(argv)

    from datetime import timedelta

    import numpy as np

    from torchft_tpu.checkpointing.collectives_transport import (
        CollectivesTransport,
    )
    from torchft_tpu.collectives import CollectivesTcp

    n = int(args.total_mb * 1024 * 1024 / 4 / 8)
    state = {
        f"w{i}": np.random.default_rng(i).standard_normal(n).astype(np.float32)
        for i in range(8)
    }
    c = CollectivesTcp(timeout=timedelta(seconds=120), hostname="localhost")
    c.configure(args.store, args.gid, 2)
    t = CollectivesTransport(c, timeout=timedelta(seconds=120))
    if args.gid == 0:
        t.send_checkpoint([1], 0, state, timedelta(seconds=120))
        print(json.dumps({"gid": 0, "plane": c.plane_info()}), flush=True)
    else:
        t0 = time.perf_counter()
        got = t.recv_checkpoint(0, t.metadata(), 0, timedelta(seconds=120))
        dt = time.perf_counter() - t0
        ok = bool(
            np.array_equal(np.asarray(got["w0"]), state["w0"])
        )
        print(
            json.dumps(
                {
                    "gid": 1,
                    "seconds": dt,
                    "total_bytes": n * 8 * 4,
                    "ok": ok,
                    "plane": c.plane_info(),
                }
            ),
            flush=True,
        )
    c.shutdown()


def _heal_state(total_mb: float) -> Dict[str, object]:
    """Deterministic 8-leaf state tree of ``total_mb`` (shared by the
    striped-heal server processes and the in-parent verifier)."""
    import numpy as np

    n = int(total_mb * 1024 * 1024 / 4 / 8)
    return {
        f"w{i}": np.random.default_rng(i).standard_normal(n).astype(np.float32)
        for i in range(8)
    }


def _striped_heal_server_main(argv: List[str]) -> None:
    """One striped-heal source: stage the deterministic state on an
    HTTPTransport (native blob plane included) and serve until the
    parent closes stdin."""
    parser = argparse.ArgumentParser()
    parser.add_argument("--total-mb", type=float, required=True)
    args = parser.parse_args(argv)

    from datetime import timedelta

    from torchft_tpu.checkpointing.http_transport import HTTPTransport

    t = HTTPTransport(timeout=timedelta(seconds=300), hostname="localhost")
    t.send_checkpoint(
        [1], 0, _heal_state(args.total_mb), timedelta(seconds=300)
    )
    print(json.dumps({"metadata": t.metadata()}), flush=True)
    sys.stdin.readline()  # parent closes stdin when the client is done
    t.shutdown()


def _run_striped_heal(total_mb: float, nsources: int) -> Dict[str, object]:
    """The ``heal_striped_{n}src`` rows: N server processes stage the
    identical state; the healer (this process) pulls byte-balanced
    stripes from all of them in parallel over the native blob plane
    (docs/heal_plane.md). ``gb_per_sec`` is the aggregate; per-source
    throughput rides along so a slow stripe is attributable."""
    import numpy as np

    from datetime import timedelta

    from torchft_tpu.checkpointing.http_transport import HTTPTransport

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    procs = []
    urls: List[str] = []
    try:
        for _ in range(nsources):
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        "-m",
                        "torchft_tpu.benchmarks.crossgroup",
                        "--striped-heal-server",
                        "--total-mb",
                        str(total_mb),
                    ],
                    stdin=subprocess.PIPE,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    env=env,
                )
            )
        for p in procs:
            line = p.stdout.readline().decode().strip()
            if not line:
                raise RuntimeError(
                    f"striped-heal server died: {p.stderr.read().decode()[-2000:]}"
                )
            urls.append(json.loads(line)["metadata"])
        rx = HTTPTransport(timeout=timedelta(seconds=300), hostname="localhost")
        try:
            t0 = time.perf_counter()
            got = rx.recv_checkpoint_multi(
                urls, 0, timedelta(seconds=300)
            )
            dt = time.perf_counter() - t0
            stats = dict(rx.last_heal_stats)
        finally:
            rx.shutdown()
        expect = _heal_state(total_mb)
        assert bool(
            np.array_equal(np.asarray(got["w0"]), expect["w0"])
            and np.array_equal(np.asarray(got["w7"]), expect["w7"])
        ), "striped heal payload corrupted"
        total_bytes = sum(int(np.asarray(v).nbytes) for v in expect.values())
        return {
            "seconds": round(dt, 4),
            "gb_per_sec": round(total_bytes / dt / 1e9, 3),
            "nsources": stats.get("nsources", nsources),
            "per_source_gbps": {
                src: s.get("gb_per_sec")
                for src, s in (stats.get("sources") or {}).items()
            },
            "stages_s": stats.get("stages"),
        }
    finally:
        for p in procs:
            try:
                if p.stdin:
                    p.stdin.close()
            except OSError:
                pass
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()


def _run_heal_pair(total_mb: float, env_extra: Dict[str, str]) -> Dict[str, object]:
    from torchft_tpu.store import StoreServer

    store = StoreServer()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.update(env_extra)
    procs = []
    try:
        for gid in range(2):
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        "-m",
                        "torchft_tpu.benchmarks.crossgroup",
                        "--heal-worker",
                        "--gid",
                        str(gid),
                        "--store",
                        store.address(),
                        "--total-mb",
                        str(total_mb),
                    ],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    env=env,
                )
            )
        results = []
        for p in procs:
            out, err = p.communicate(timeout=500)
            if p.returncode != 0:
                raise RuntimeError(
                    f"heal worker rc={p.returncode}: {err.decode()[-2000:]}"
                )
            results.append(json.loads(out.decode().strip().splitlines()[-1]))
    finally:
        store.shutdown()
    r = next(r for r in results if r["gid"] == 1)
    assert r["ok"], "heal payload corrupted"
    return {
        "seconds": round(r["seconds"], 4),
        "gb_per_sec": round(r["total_bytes"] / r["seconds"] / 1e9, 3),
        "plane": r["plane"],
    }


def _worker_main(argv: List[str]) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--gid", type=int, required=True)
    parser.add_argument("--lighthouse", required=True)
    parser.add_argument("--total-mb", type=float, required=True)
    parser.add_argument("--rounds", type=int, required=True)
    parser.add_argument("--wire-dtype", default="")
    parser.add_argument("--serial", action="store_true")
    args = parser.parse_args(argv)

    import numpy as np

    from torchft_tpu.collectives import CollectivesTcp
    from torchft_tpu.ddp import allreduce_gradients, flatten_buckets
    from torchft_tpu.manager import Manager
    from torchft_tpu.store import StoreServer

    import jax.numpy as jnp

    from torchft_tpu.utils.platform import pin_platform_from_env

    # the worker must NEVER occupy the chip or pay tunnel transfers —
    # force cpu unconditionally (the docstring guarantee), then pin it so
    # a sitecustomize-registered TPU plugin can't win over the env var
    os.environ["JAX_PLATFORMS"] = "cpu"
    pin_platform_from_env()

    store = StoreServer()
    coll = CollectivesTcp(
        timeout=timedelta(seconds=120),
        hostname="localhost",
        wire_dtype=args.wire_dtype or None,
    )
    manager = Manager(
        collectives=coll,
        load_state_dict=lambda s: None,
        state_dict=lambda: {},
        min_replica_size=2,
        replica_id=f"xg{args.gid}",
        store_addr=store.address(),
        rank=0,
        world_size=1,
        lighthouse_addr=args.lighthouse,
        timeout=timedelta(seconds=120),
        quorum_timeout=timedelta(seconds=120),
        use_async_quorum=False,
    )
    try:
        # ~4 MB leaves → ~25 MB buckets hold ~6 each; jnp so the full
        # leaf→host→ring→device path runs
        leaf_elems = 1 << 20
        n_leaves = max(1, int(args.total_mb * 1024 * 1024 / 4 / leaf_elems))
        rng = np.random.default_rng(args.gid)
        grads = {
            f"g{i}": jnp.asarray(
                rng.standard_normal(leaf_elems).astype(np.float32)
            )
            for i in range(n_leaves)
        }
        total_bytes = n_leaves * leaf_elems * 4

        def serial_round() -> None:
            # the round-2 schedule: every leaf to host first, then ONE
            # managed op over all buckets, then back
            host = [np.ascontiguousarray(np.asarray(v)) for v in grads.values()]
            buckets = flatten_buckets(host)
            manager.allreduce_many([b for b, _ in buckets]).wait()
            for b, _ in buckets:
                jnp.asarray(b)

        def pipelined_round() -> None:
            allreduce_gradients(manager, grads)

        run = serial_round if args.serial else pipelined_round

        # warmup (also forms the quorum)
        manager.start_quorum()
        run()
        assert manager.should_commit(), "warmup step failed to commit"

        # per-stage attribution (host-copy / quantize / wire /
        # dequantize-reduce, docs/wire_plane.md): reset AFTER warmup so
        # the breakdown covers exactly the timed rounds — this is what
        # explains a wire-row delta instead of leaving it a mystery
        from torchft_tpu.collectives import wire_stage_snapshot

        wire_stage_snapshot(reset=True)
        t0 = time.perf_counter()
        for _ in range(args.rounds):
            manager.start_quorum()
            run()
            assert manager.should_commit(), "bench step failed to commit"
        elapsed = (time.perf_counter() - t0) / args.rounds
        stages = {
            k: round(v / args.rounds, 4)
            for k, v in wire_stage_snapshot().items()
        }

        print(
            json.dumps(
                {
                    "gid": args.gid,
                    "seconds_per_round": elapsed,
                    "total_bytes": total_bytes,
                    "plane": coll.plane_info(),
                    "wire_codec": coll.wire_codec(),
                    "stages_per_round_s": stages,
                }
            ),
            flush=True,
        )
    finally:
        manager.shutdown(wait=False)
        store.shutdown()


def _run_pair(
    lighthouse_addr: str,
    total_mb: float,
    rounds: int,
    wire_dtype: str,
    serial: bool,
    env_extra: Optional[Dict[str, str]] = None,
) -> Dict[str, object]:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    if env_extra:
        env.update(env_extra)
    procs = []
    for gid in range(2):
        cmd = [
            sys.executable,
            "-m",
            "torchft_tpu.benchmarks.crossgroup",
            "--worker",
            "--gid",
            str(gid),
            "--lighthouse",
            lighthouse_addr,
            "--total-mb",
            str(total_mb),
            "--rounds",
            str(rounds),
            "--wire-dtype",
            wire_dtype,
        ]
        if serial:
            cmd.append("--serial")
        procs.append(
            subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env
            )
        )
    results = []
    for p in procs:
        out, err = p.communicate(timeout=600)
        if p.returncode != 0:
            raise RuntimeError(
                f"crossgroup worker failed rc={p.returncode}: "
                f"{err.decode()[-2000:]}"
            )
        results.append(json.loads(out.decode().strip().splitlines()[-1]))
    slow = max(results, key=lambda r: r["seconds_per_round"])
    secs = slow["seconds_per_round"]
    total_bytes = results[0]["total_bytes"]
    return {
        "seconds_per_round": secs,
        "gb_per_sec": total_bytes / secs / 1e9,
        "total_bytes": total_bytes,
        "plane": slow.get("plane", "?"),
        "wire_codec": slow.get("wire_codec", "f32"),
        # the slower worker's breakdown: that is the rank the row's
        # seconds_per_round actually measures
        "stages_per_round_s": slow.get("stages_per_round_s", {}),
    }


def _run_raw_pair(
    total_mb: float, rounds: int, wire_dtype: str, env_extra: Dict[str, str]
) -> Dict[str, object]:
    from torchft_tpu.store import StoreServer

    store = StoreServer()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.update(env_extra)
    procs = []
    try:
        for gid in range(2):
            cmd = [
                sys.executable,
                "-m",
                "torchft_tpu.benchmarks.crossgroup",
                "--raw-worker",
                "--gid",
                str(gid),
                "--store",
                store.address(),
                "--total-mb",
                str(total_mb),
                "--rounds",
                str(rounds),
                "--wire-dtype",
                wire_dtype,
            ]
            procs.append(
                subprocess.Popen(
                    cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env
                )
            )
        results = []
        for p in procs:
            out, err = p.communicate(timeout=600)
            if p.returncode != 0:
                raise RuntimeError(
                    f"raw worker failed rc={p.returncode}: {err.decode()[-2000:]}"
                )
            results.append(json.loads(out.decode().strip().splitlines()[-1]))
    finally:
        store.shutdown()
    secs = max(r["seconds_per_round"] for r in results)
    return {
        "seconds_per_round": round(secs, 4),
        "gb_per_sec": round(results[0]["total_bytes"] / secs / 1e9, 3),
        "total_bytes": results[0]["total_bytes"],
        "plane": results[0]["plane"],
    }


def measure_crossgroup(
    total_mb: float = 256.0, rounds: int = 3
) -> Dict[str, object]:
    """Run the 2-process averaging matrix; returns the bench dict."""
    from torchft_tpu.coordination import LighthouseServer

    out: Dict[str, object] = {
        "topology": "2 replica groups, separate OS processes, native "
        "striped data plane (CMA same-host / multi-socket TCP), e2e "
        "variants through full Manager quorum+commit",
        "tree_mb": total_mb,
    }
    grad_bytes_7b = LLAMA2_7B_PARAMS * 4  # f32 gradient tree

    # RAW transport matrix: what the plane itself moves (busbw analogue).
    # CMA = one-copy process_vm_readv pulls (same-host; NCCL SHM/P2P
    # analogue); tcp-striped = the cross-host path, forced here via env;
    # python-ring = the pre-round-4 interpreter path, kept for comparison.
    raw_variants = {
        "raw_cma": dict(wire_dtype="", env_extra={}),
        "raw_tcp_striped": dict(
            wire_dtype="", env_extra={"TORCHFT_DP_CMA": "0"}
        ),
        "raw_tcp_striped_bf16": dict(
            wire_dtype="bfloat16", env_extra={"TORCHFT_DP_CMA": "0"}
        ),
        "raw_python_ring": dict(
            wire_dtype="", env_extra={"TORCHFT_NATIVE_PLANE": "0"}
        ),
    }
    for name, kw in raw_variants.items():
        try:
            res = _run_raw_pair(total_mb, rounds, **kw)  # type: ignore[arg-type]
        except Exception as e:  # noqa: BLE001 — best-effort matrix row
            out[name] = {"error": str(e)}
            continue
        res["derived_llama2_7b_avg_s"] = round(
            grad_bytes_7b * res["seconds_per_round"] / res["total_bytes"], 2
        )
        del res["total_bytes"]
        out[name] = res

    # live-heal throughput (the rejoin data path) with and without the
    # p2p CMA fast path
    for name, env_extra in (
        ("heal_cma", {}),
        ("heal_tcp", {"TORCHFT_DP_CMA": "0"}),
    ):
        try:
            out[name] = _run_heal_pair(total_mb, env_extra)
        except Exception as e:  # noqa: BLE001 — best-effort matrix row
            out[name] = {"error": str(e)}

    # striped multi-source heal (ISSUE 9): same bytes pulled from 1 vs 2
    # sources over the native blob plane; the speedup row is the
    # per-source parallel scaling the sub-second-heal acceptance reads
    for name, nsrc in (("heal_striped_1src", 1), ("heal_striped_2src", 2)):
        try:
            out[name] = _run_striped_heal(total_mb, nsrc)
        except Exception as e:  # noqa: BLE001 — best-effort matrix row
            out[name] = {"error": str(e)}
    try:
        s1 = out["heal_striped_1src"]["gb_per_sec"]  # type: ignore[index]
        s2 = out["heal_striped_2src"]["gb_per_sec"]  # type: ignore[index]
        out["heal_striped_speedup"] = round(s2 / s1, 3) if s1 else None
    except (KeyError, TypeError):
        out["heal_striped_speedup"] = None

    variants = {
        "serial_r2": dict(wire_dtype="", serial=True),
        "pipelined": dict(wire_dtype="", serial=False),
        "pipelined_bf16_wire": dict(wire_dtype="bfloat16", serial=False),
    }
    for name, kw in variants.items():
        lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
        try:
            res = _run_pair(
                lighthouse.address(), total_mb, rounds, **kw
            )
        finally:
            lighthouse.shutdown()
        res["derived_llama2_7b_avg_s"] = round(
            grad_bytes_7b * res["seconds_per_round"] / res["total_bytes"], 2
        )
        res["seconds_per_round"] = round(res["seconds_per_round"], 4)
        res["gb_per_sec"] = round(res["gb_per_sec"], 3)
        del res["total_bytes"]
        out[name] = res

    ser = out["serial_r2"]["seconds_per_round"]  # type: ignore[index]
    pipe = out["pipelined"]["seconds_per_round"]  # type: ignore[index]
    out["pipeline_speedup"] = round(ser / pipe, 3) if pipe else None
    out["note"] = (
        "raw_* rows isolate the transport (one allreduce, no Manager); "
        "e2e rows include full per-round quorum+commit and JAX<->host "
        "copies. derived_llama2_7b_avg_s extrapolates measured bytes/s to "
        "the 7B preset's f32 gradient tree; workers run on CPU so the "
        "wire path is measured without occupying the chip"
    )
    return out


def measure_compressed(
    total_mb: float = 128.0, rounds: int = 2
) -> Dict[str, object]:
    """The ``crossgroup_compressed`` bench row: the int8-quantized wire
    (4x fewer bytes per hop, per-chunk scale factors, error feedback
    handled one level up) over the forced tcp-striped native plane —
    ``TORCHFT_DP_CMA=0`` models the cross-host link, where CMA does not
    exist and compression is the whole point. ``serial`` is the
    round-2 schedule; ``streamed`` is the per-bucket pipeline that
    overlaps host-copy / wire / H2D per bucket. ``gb_per_sec`` counts
    APPLICATION bytes (the f32 gradient tree), so the row composes with
    derived_llama2_7b_avg_s and the uncompressed rows directly."""
    from torchft_tpu.coordination import LighthouseServer

    out: Dict[str, object] = {
        "topology": "2 replica groups, separate OS processes, int8 wire "
        "codec on the forced tcp-striped native plane (TORCHFT_DP_CMA=0 "
        "— the cross-host model); gb_per_sec counts f32 tree bytes",
        "tree_mb": total_mb,
        "codec": "int8",
    }
    grad_bytes_7b = LLAMA2_7B_PARAMS * 4
    for name, serial in (("serial", True), ("streamed", False)):
        lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)
        try:
            res = _run_pair(
                lighthouse.address(), total_mb, rounds,
                wire_dtype="int8", serial=serial,
                env_extra={"TORCHFT_DP_CMA": "0"},
            )
        except Exception as e:  # noqa: BLE001 — best-effort matrix row
            out[name] = {"error": str(e)}
            continue
        finally:
            lighthouse.shutdown()
        res["derived_llama2_7b_avg_s"] = round(
            grad_bytes_7b * res["seconds_per_round"] / res["total_bytes"], 2
        )
        res["seconds_per_round"] = round(res["seconds_per_round"], 4)
        res["gb_per_sec"] = round(res["gb_per_sec"], 3)
        del res["total_bytes"]
        out[name] = res
    return out


def main() -> None:
    if "--striped-heal-server" in sys.argv:
        argv = [a for a in sys.argv[1:] if a != "--striped-heal-server"]
        _striped_heal_server_main(argv)
        return
    if "--heal-worker" in sys.argv:
        argv = [a for a in sys.argv[1:] if a != "--heal-worker"]
        _heal_worker_main(argv)
        return
    if "--raw-worker" in sys.argv:
        argv = [a for a in sys.argv[1:] if a != "--raw-worker"]
        _raw_worker_main(argv)
        return
    if "--worker" in sys.argv:
        argv = [a for a in sys.argv[1:] if a != "--worker"]
        _worker_main(argv)
        return
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--total-mb", type=float, default=256.0)
    parser.add_argument("--rounds", type=int, default=3)
    parser.add_argument(
        "--compressed", action="store_true",
        help="run only the crossgroup_compressed matrix (int8 wire, "
        "serial + streamed)",
    )
    args = parser.parse_args()
    # ONE line: callers (bench.py) parse the last stdout line as JSON
    fn = measure_compressed if args.compressed else measure_crossgroup
    print(json.dumps(fn(args.total_mb, args.rounds)), flush=True)


if __name__ == "__main__":
    main()
