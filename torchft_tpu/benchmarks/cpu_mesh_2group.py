"""Real 2-group 'ft'-axis averaging overhead on a virtual CPU mesh.

The round-2 review called out that the headline bench's "averaging" is a
world-size-1 no-op on a single chip (`CollectivesDevice.allreduce` short-
circuits at world==1), so the reported overhead measured nothing. One chip
can't host two device-path groups — but a virtual 8-device CPU mesh can:
this worker runs TWO replica groups (threads sharing one JAX runtime,
4 devices each, the in-process registry path), each through a full Manager
(C++ lighthouse, per-step quorum + commit), and measures steps/s with the
REAL cross-group 'ft'-axis psum vs. without any averaging on identical
configs. The relative overhead is the honest number for what device-path
averaging costs; absolute CPU steps/s is meaningless and not reported
upstream.

Run standalone (must be a fresh process — the flags must precede jax
import)::

    python -m torchft_tpu.benchmarks.cpu_mesh_2group
"""

from __future__ import annotations

import json
import os
import sys


def _ensure_cpu_mesh() -> None:
    """Re-exec with the virtual-mesh flags if jax could already be live.

    Importing this module via ``-m`` runs the package ``__init__`` (which
    pulls in jax) before any code here, so mutating ``os.environ`` in-
    process is too late — a child process with the flags set is the only
    reliable way to get 8 virtual CPU devices."""
    if os.environ.get("_TFT_CPU2G") == "1":
        return
    import subprocess

    env = dict(os.environ)
    env["_TFT_CPU2G"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    sys.exit(
        subprocess.call(
            [sys.executable, "-m", "torchft_tpu.benchmarks.cpu_mesh_2group"],
            env=env,
        )
    )


def _measure(averaging: bool, steps: int, warmup: int) -> float:
    """Mean steps/s across 2 concurrent replica groups."""
    import time
    from concurrent.futures import ThreadPoolExecutor
    from datetime import timedelta

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchft_tpu.collectives_device import CollectivesDevice
    from torchft_tpu.coordination import LighthouseServer
    from torchft_tpu.ddp import allreduce_gradients
    from torchft_tpu.manager import Manager
    from torchft_tpu.models.transformer import TransformerConfig
    from torchft_tpu.parallel.mesh import MeshConfig, make_mesh
    from torchft_tpu.parallel.train_step import TrainStep
    from torchft_tpu.store import StoreServer

    import os as _os

    from torchft_tpu.utils.platform import pin_platform_from_env

    # this bench must NEVER run on (or occupy) a real accelerator — force
    # cpu unconditionally, then pin it so a sitecustomize-registered TPU
    # plugin can't win over the env var
    _os.environ["JAX_PLATFORMS"] = "cpu"
    pin_platform_from_env()
    devs = jax.devices()
    assert len(devs) >= 8, "needs xla_force_host_platform_device_count=8"

    cfg = TransformerConfig(
        vocab_size=1024,
        d_model=256,
        n_layers=4,
        n_heads=4,
        head_dim=64,
        d_ff=704,
        dtype=jnp.float32,
    )
    batch, seq = 4, 128

    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=2)

    def one_group(gid: int) -> float:
        mesh = make_mesh(MeshConfig(dp=4), devices=devs[gid * 4 : (gid + 1) * 4])
        ts = TrainStep(cfg, optax.adamw(3e-4), mesh)
        params = ts.init_params(jax.random.PRNGKey(0))
        opt_state = ts.init_opt(params)
        store = StoreServer()
        manager = Manager(
            collectives=CollectivesDevice(timeout=timedelta(seconds=60)),
            load_state_dict=lambda s: None,
            state_dict=lambda: {},
            min_replica_size=2,
            replica_id=f"cpu2g{gid}",
            store_addr=store.address(),
            rank=0,
            world_size=1,
            lighthouse_addr=lighthouse.address(),
            timeout=timedelta(seconds=60),
            use_async_quorum=False,
        )
        rng = np.random.default_rng(gid)
        tokens = ts.shard_batch(
            jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
        )
        try:
            def ft_step(params, opt_state):
                manager.start_quorum()
                loss, grads = ts.grads(params, tokens)
                if averaging:
                    grads = allreduce_gradients(manager, grads)
                if manager.should_commit():
                    params, opt_state = ts.apply(params, opt_state, grads)
                return loss, params, opt_state

            for _ in range(warmup):
                loss, params, opt_state = ft_step(params, opt_state)
            if warmup:
                float(loss)  # fence warmup work out of the timed window
            t0 = time.perf_counter()
            for _ in range(steps):
                loss, params, opt_state = ft_step(params, opt_state)
            float(loss)
            return steps / (time.perf_counter() - t0)
        finally:
            manager.shutdown(wait=False)
            store.shutdown()

    try:
        with ThreadPoolExecutor(max_workers=2) as ex:
            rates = list(ex.map(one_group, range(2)))
    finally:
        lighthouse.shutdown()
    return sum(rates) / len(rates)


def main() -> None:
    _ensure_cpu_mesh()
    steps, warmup = 5, 2
    # Interleave the variants and keep best-of-4 per variant: on a
    # 1-core host the run-to-run noise otherwise dwarfs the psum cost
    # (the first cut measured the overhead at -80%, and round 4's
    # best-of-2 still drifted 17% between rounds — review weak #7). The
    # MAX is the right statistic here: contention only ever subtracts,
    # so the fastest run is the closest view of the machine-independent
    # cost, and 4 samples make it stable across rounds.
    avg_runs, noavg_runs = [], []
    for _ in range(4):
        avg_runs.append(_measure(True, steps, warmup))
        noavg_runs.append(_measure(False, steps, warmup))
    with_avg, without = max(avg_runs), max(noavg_runs)
    overhead = (without - with_avg) / without * 100.0 if without else 0.0
    print(
        json.dumps(
            {
                "steps_per_sec_2group_avg": round(with_avg, 4),
                "steps_per_sec_2group_noavg": round(without, 4),
                "averaging_overhead_pct": round(overhead, 2),
                "avg_runs": [round(r, 4) for r in avg_runs],
                "noavg_runs": [round(r, 4) for r in noavg_runs],
                "config": "2 groups × dp=4 virtual CPU devices, d256 L4 "
                "b4 s128 f32, device-path 'ft' psum, sync quorum; "
                "best-of-4 per variant, runs recorded",
                "limitation": "CPU-mesh proxy metric: compute here is "
                "unrealistically cheap relative to the psum, so the "
                "overhead_pct OVERSTATES the on-chip cost; a single-chip "
                "box cannot isolate the multi-chip 'ft'-psum cost at "
                "realistic model sizes (the real-chip complement is the "
                "tpu_2group_hostplane row)",
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
