"""Benchmark harnesses: recovery wall-clock, data-plane throughput.

The reference ships benchmark *tooling* but publishes no numbers
(BASELINE.md); its timing envelope lives in test assertions
(torchft/lighthouse_test.py:44-47, manager_integ_test.py:325-368). These
modules measure the same envelope — quorum-recovery wall-clock after a
replica-group kill — as reusable harnesses shared by bench.py and the
test suite.
"""
