"""Long-context + scale bench variants, subprocess-isolated.

Round 5 found the same suite-interference that hit the resnet row
(resnet_ft.py post-mortem) depressing the in-process long-context rows:
s=8192 measured 9.07 steps/s when run after the headline's six
measurement runs inside bench.py's process vs 9.9-10.0 in a fresh
process. This module runs the s=4k/8k/16k/32k variants and the 647M
scale variant in their OWN process, first touch of the chip.

Run: ``python -m torchft_tpu.benchmarks.long_context`` — prints one
JSON line with a row per variant.
"""

import json
import sys


def run() -> dict:
    import jax
    import jax.numpy as jnp

    from bench import (
        _model_flops_per_step,
        _peak_flops,
        headline_config,
        train_bench,
    )
    from torchft_tpu.models.transformer import TransformerConfig

    # the long-context rows ARE the headline model at longer S — import
    # the config so the two can never silently diverge
    cfg = headline_config()
    peak = _peak_flops(jax.devices()[0])
    attn_note = (
        "tiered chunked-scan attention (pure XLA; see "
        "ops/attention.chunked_attention + transformer._use_chunked); "
        "OWN process (round-5 interference post-mortem in this module)"
    )
    out = {}
    n_params = 0
    # DESCENDING sequence length: the s=32k config is the HBM-ceiling one
    # and collapses 4x (0.88 -> 0.23 steps/s) when it runs after the
    # smaller variants' leftover allocations; largest-first measured
    # clean for every row (0.91/3.32/10.0/15.8 in one process)
    for s, b, steps, warmup in (
        (32768, 1, 3, 1), (16384, 1, 4, 2), (8192, 1, 6, 2), (4096, 2, 10, 2)
    ):
        try:
            sps, n_params = train_bench(cfg, b, s, steps, warmup, averaging=True)
            flops = _model_flops_per_step(cfg, n_params, b, s)
            out[f"long_context_s{s}"] = {
                "steps_per_sec": round(sps, 4),
                "tokens_per_sec": round(sps * b * s),
                "mfu_pct": round(sps * flops / peak * 100.0, 2) if peak else None,
                "attention": attn_note,
            }
        except Exception as e:  # noqa: BLE001
            out[f"long_context_s{s}"] = {"error": str(e)}

    big = TransformerConfig(
        vocab_size=32000, d_model=2048, n_layers=12, n_heads=16,
        head_dim=64, d_ff=5632, dtype=jnp.bfloat16,
        # measured round 5 (FT loop, fresh process, noremat leg FIRST):
        # 6.17 vs 5.80 steps/s — at 647M recompute costs more than the
        # activation spill, the OPPOSITE of the d512 headline
        remat=False,
    )
    try:
        big_sps, big_n = train_bench(big, 4, 1024, 8, 2, averaging=True)
        big_flops = _model_flops_per_step(big, big_n, 4, 1024)
        out["scale_647M"] = {
            "steps_per_sec": round(big_sps, 4),
            "tokens_per_sec": round(big_sps * 4 * 1024),
            "n_params": big_n,
            "mfu_pct": round(big_sps * big_flops / peak * 100.0, 2)
            if peak
            else None,
            "config": "d2048 L12 b4 s1024 bf16, remat=False (measured "
            "faster than remat at this size); OWN process",
        }
    except Exception as e:  # noqa: BLE001
        out["scale_647M"] = {"error": str(e)}
    return out


if __name__ == "__main__":
    import os

    sys.path.insert(
        0,
        os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
    )
    print(json.dumps(run()))
