"""ResNet-18/CIFAR FT-loop benchmark, subprocess-isolated.

Round-4 review weak #1: the resnet row regressed 88 -> 49 steps/s with
the model file untouched — the row ran LAST inside bench.py's process,
after the headline, four long-context variants and the 647M scale model
had churned device/host state. Isolated re-measurement on the same box
gave 72–93 steps/s (median ~85), and re-running it after single variants
reproduced only noise-range dips — i.e. suite interference plus
unreported run variance, not a model regression. The fix is structural:
the row now runs in its OWN process (this module), first touch of the
chip, median of 5 reps with the runs list recorded.

Round-5 addendum: even isolated, per-invocation medians span 44-96
steps/s (within-invocation reps 53->98, first rep always lowest). At
b256 a step is ~10-15 ms against ~5 tunnel RPC round trips (quorum,
commit, 3 dispatches), so the row is DISPATCH-LATENCY-bound on this
tunneled box and measures tunnel weather as much as conv throughput —
the regression gate carries a wide tolerance for it (bench.py), and a
real conv regression must be judged against the runs list, not the
median alone.

Run: ``python -m torchft_tpu.benchmarks.resnet_ft`` — prints one JSON
line.
"""

import json
import sys
import time


def run(steps: int = 20, warmup: int = 3, batch: int = 256, reps: int = 5) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from bench import _single_group_ft_runtime  # repo-root bench helpers
    from torchft_tpu.ddp import allreduce_gradients
    from torchft_tpu.models import resnet

    runs = []
    for _ in range(reps):
        with _single_group_ft_runtime("bench_resnet") as manager:
            cfg = resnet.ResNetConfig(dtype=jnp.bfloat16)
            params, bn = resnet.init(jax.random.PRNGKey(0), cfg)
            tx = optax.sgd(0.1, momentum=0.9)
            opt_state = tx.init(params)

            rng = np.random.default_rng(0)
            x = jnp.asarray(rng.standard_normal((batch, 32, 32, 3)), jnp.float32)
            y = jnp.asarray(rng.integers(0, 10, batch), jnp.int32)

            @jax.jit
            def grads_fn(params, bn):
                (loss, new_bn), grads = jax.value_and_grad(
                    lambda p: resnet.loss_fn(p, bn, x, y, cfg), has_aux=True
                )(params)
                return loss, grads, new_bn

            @jax.jit
            def apply_fn(params, opt_state, grads):
                updates, opt_state = tx.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), opt_state

            def ft_step(params, opt_state, bn):
                manager.start_quorum()
                loss, grads, new_bn = grads_fn(params, bn)
                grads = allreduce_gradients(manager, grads)
                if manager.should_commit():
                    params, opt_state = apply_fn(params, opt_state, grads)
                    bn = new_bn
                return loss, params, opt_state, bn

            for _ in range(warmup):
                loss, params, opt_state, bn = ft_step(params, opt_state, bn)
            if warmup:
                float(loss)  # host fence (tunnel: block_until_ready lies)
            t0 = time.perf_counter()
            for _ in range(steps):
                loss, params, opt_state, bn = ft_step(params, opt_state, bn)
            float(loss)
            runs.append(steps / (time.perf_counter() - t0))
    runs.sort()
    sps = runs[len(runs) // 2]
    return {
        "steps_per_sec": round(sps, 4),
        "imgs_per_sec": round(sps * batch),
        "runs_steps_per_sec": [round(r, 4) for r in runs],
        "spread_pct": round((runs[-1] - runs[0]) / sps * 100.0, 1),
        "config": f"resnet18-cifar NHWC bf16 b{batch}, single-group FT "
        f"loop, OWN process (median of {reps}; dispatch-latency-bound "
        "through the tunnel — see module docstring for both post-mortems)",
    }


if __name__ == "__main__":
    import os

    sys.path.insert(
        0,
        os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
    )
    print(json.dumps(run()))
