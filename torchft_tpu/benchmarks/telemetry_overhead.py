"""Telemetry piggyback overhead: armed vs disarmed, the SAME headline FT
leg, interleaved A/B medians (ISSUE 16 self-metering budget).

The sublinear-telemetry claim has two halves: bytes (quorum_scale's
full-JSON vs delta legs) and CPU. This row is the CPU half as a measured
gate: each leg runs the real headline loop (quorum + grads + commit vote
through the instrumented Manager — the path that builds and delta-encodes
the piggyback every step) with the telemetry piggyback either armed
(``TORCHFT_TELEMETRY_PIGGYBACK=1``, the always-on default: report build,
delta encode, span drain) or disarmed (``=0`` — the kill-switch path that
skips the whole builder). Legs interleave so both variants see the same
box drift; medians are compared.

Acceptance: ``overhead_pct <= gate_pct`` where the gate defaults to 1%
and is tunable via ``TORCHFT_TELEMETRY_BUDGET_PCT``. ``--smoke`` runs a
reduced config and exits nonzero past the gate — the
``scripts/premerge.sh`` leg. Where the cost LIVES (encode vs scrape vs
spans) is a separate question answered by
``tft_telemetry_bytes_total{channel}`` and the ``telemetry`` anatomy
phase; this row only guards the total.

Prints one JSON object on the last stdout line (the
``_run_json_subprocess`` contract).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def gate_pct() -> float:
    """Budget gate: telemetry may cost at most this % of step rate."""
    try:
        return float(os.environ.get("TORCHFT_TELEMETRY_BUDGET_PCT", "1.0"))
    except ValueError:
        return 1.0


def measure(
    runs: int, steps: int, warmup: int, batch: int, seq: int
) -> dict:
    # import inside: bench.py's subprocess contract, and the headline
    # model config must come from bench.py so the two rows can never
    # silently diverge
    sys.path.insert(
        0,
        os.path.normpath(
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "..", "..")
        ),
    )
    from bench import headline_config, train_bench

    from torchft_tpu import telemetry

    cfg = headline_config()
    armed: list = []
    disarmed: list = []

    def set_armed(on: bool) -> None:
        # the kill switch is read per-call in Manager._telemetry_payload,
        # so an env flip takes effect on the next step
        os.environ["TORCHFT_TELEMETRY_PIGGYBACK"] = "1" if on else "0"

    # one throwaway leg first: jit compilation must not land inside
    # either variant's timed window
    set_armed(False)
    train_bench(cfg, batch, seq, 1, 1, averaging=True)

    for _ in range(runs):  # interleaved: both variants see the same drift
        set_armed(True)
        armed.append(train_bench(cfg, batch, seq, steps, warmup,
                                 averaging=True)[0])
        set_armed(False)
        disarmed.append(train_bench(cfg, batch, seq, steps, warmup,
                                    averaging=True)[0])
    set_armed(True)  # leave the process in the always-on default

    piggyback_bytes = telemetry.TELEMETRY_BYTES.labels(
        channel="piggyback"
    ).value
    span_bytes = telemetry.TELEMETRY_BYTES.labels(channel="spans").value

    armed.sort()
    disarmed.sort()
    a = armed[len(armed) // 2]
    d = disarmed[len(disarmed) // 2]
    overhead = (d - a) / d * 100.0 if d else 0.0
    gate = gate_pct()
    return {
        "_gate_presence": True,
        "steps_per_sec": round(a, 4),
        "steps_per_sec_disarmed": round(d, 4),
        "overhead_pct": round(overhead, 2),
        "gate_pct": gate,
        "within_gate": overhead <= gate,
        "piggyback_bytes": int(piggyback_bytes),
        "span_bytes": int(span_bytes),
        "runs_armed": [round(r, 4) for r in armed],
        "runs_disarmed": [round(r, 4) for r in disarmed],
        "config": {"batch": batch, "seq": seq, "steps": steps,
                   "warmup": warmup, "runs": runs},
        "note": "headline FT leg with the telemetry piggyback armed vs "
        "disarmed, interleaved medians; the self-metering budget gate "
        "(<=1% default, TORCHFT_TELEMETRY_BUDGET_PCT). Single-run "
        "medians on a loaded 1-core box can swing past the gate on "
        "weather — re-run before believing a breach.",
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument(
        "--smoke", action="store_true",
        help="reduced premerge leg: tiny batch/seq, exit 1 past the gate",
    )
    args = ap.parse_args()

    if args.smoke:
        batch, seq, steps = 2, 64, args.steps or 3
    else:
        batch, seq, steps = 4, 128, args.steps or 5

    row = measure(args.runs, steps, args.warmup, batch, seq)
    print(json.dumps({"telemetry_overhead": row}))
    if args.smoke and not row["within_gate"]:
        print(
            f"telemetry overhead {row['overhead_pct']}% exceeds the "
            f"{row['gate_pct']}% gate",
            file=sys.stderr,
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
