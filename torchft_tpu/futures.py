"""Futures with deadline enforcement.

The reference wraps ``torch.futures.Future`` with a timeout manager backed by
a lazily-started asyncio thread (/root/reference/torchft/futures.py:43-165).
Here the framework is torch-free, so we provide our own chainable ``Future``
(continuations via ``then``, error propagation) plus a single daemon timer
thread that fails futures past their deadline.
"""

from __future__ import annotations

import heapq
import threading
from datetime import timedelta
from typing import Any, Callable, Generic, List, Optional, Tuple, TypeVar

T = TypeVar("T")
S = TypeVar("S")

__all__ = ["Future", "future_timeout", "future_wait", "run_in_executor"]


class Future(Generic[T]):
    """A chainable future.

    ``then(cb)`` schedules ``cb(fut)`` when this future completes and returns
    a new Future holding ``cb``'s result (exceptions propagate), matching the
    continuation style the reference relies on for gradient normalization and
    error swallowing (torchft/manager.py:280-293, 348-362).
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._done = False
        self._value: Optional[T] = None
        self._exception: Optional[BaseException] = None
        self._callbacks: List[Callable[["Future[T]"], None]] = []

    # -- producer side --
    def set_result(self, value: T) -> None:
        with self._cond:
            if self._done:
                return
            self._value = value
            self._done = True
            callbacks = self._callbacks
            self._callbacks = []
            self._cond.notify_all()
        for cb in callbacks:
            self._run_callback(cb)

    def set_exception(self, exc: BaseException) -> None:
        with self._cond:
            if self._done:
                return
            self._exception = exc
            self._done = True
            callbacks = self._callbacks
            self._callbacks = []
            self._cond.notify_all()
        for cb in callbacks:
            self._run_callback(cb)

    # -- consumer side --
    def done(self) -> bool:
        with self._cond:
            return self._done

    def wait(self, timeout: Optional[timedelta] = None) -> T:
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self._done,
                timeout.total_seconds() if timeout is not None else None,
            )
            if not ok:
                raise TimeoutError("future wait timed out")
        return self.value()

    def value(self) -> T:
        with self._cond:
            assert self._done, "future is not complete"
            if self._exception is not None:
                raise self._exception
            return self._value  # type: ignore[return-value]

    def exception(self) -> Optional[BaseException]:
        with self._cond:
            assert self._done, "future is not complete"
            return self._exception

    def then(self, callback: Callable[["Future[T]"], S]) -> "Future[S]":
        out: Future[S] = Future()

        def run(fut: "Future[T]") -> None:
            try:
                out.set_result(callback(fut))
            except BaseException as e:  # noqa: BLE001 — error futures carry anything
                out.set_exception(e)

        with self._cond:
            if not self._done:
                self._callbacks.append(run)
                return out
        run(self)
        return out

    def _run_callback(self, cb: Callable[["Future[T]"], None]) -> None:
        try:
            cb(self)
        except BaseException:  # noqa: BLE001 — continuation errors land in `out`
            pass

    @staticmethod
    def completed(value: T) -> "Future[T]":
        f: Future[T] = Future()
        f.set_result(value)
        return f

    @staticmethod
    def failed(exc: BaseException) -> "Future[Any]":
        f: Future[Any] = Future()
        f.set_exception(exc)
        return f


class _TimeoutManager:
    """Single daemon timer thread enforcing future deadlines (the asyncio
    event-loop analogue of torchft/futures.py:43-117)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        # heap entries hold a one-element SLOT, cleared when the future
        # completes, so payloads are never pinned for the full deadline
        self._heap: List[Tuple[float, int, List[Optional[Future[Any]]]]] = []
        self._seq = 0
        self._thread: Optional[threading.Thread] = None

    def register(self, fut: Future[Any], timeout: timedelta) -> None:
        import time

        deadline = time.monotonic() + timeout.total_seconds()
        # the heap entry must not pin the future (and its payload — e.g. a
        # whole gradient pytree on device) for the full deadline after it
        # completes: clear the slot on completion, the timer skips it
        slot: List[Optional[Future[Any]]] = [fut]
        fut.then(lambda _f: slot.__setitem__(0, None))
        with self._cond:
            self._seq += 1
            heapq.heappush(self._heap, (deadline, self._seq, slot))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="tft_timeout_manager", daemon=True
                )
                self._thread.start()
            self._cond.notify()

    def _run(self) -> None:
        import time

        while True:
            with self._cond:
                while not self._heap:
                    self._cond.wait()
                deadline, _, slot = self._heap[0]
                now = time.monotonic()
                if deadline > now:
                    self._cond.wait(timeout=deadline - now)
                    continue
                heapq.heappop(self._heap)
            fut = slot[0]
            if fut is not None and not fut.done():
                from torchft_tpu import telemetry

                telemetry.FUTURE_TIMEOUTS.inc()
                # a deadline on the FT data plane usually means a wedged
                # collective: capture the per-rank op history NOW, while
                # the evidence (last completed / first stuck op) is fresh.
                # Rate-limited inside dump(); must never fail the timeout.
                try:
                    telemetry.FLIGHT.dump("deadline")
                except Exception:  # noqa: BLE001
                    pass
                fut.set_exception(
                    TimeoutError("future did not complete within deadline")
                )


_TIMEOUT_MANAGER = _TimeoutManager()


def future_timeout(fut: Future[T], timeout: timedelta) -> Future[T]:
    """Return a future that mirrors ``fut`` but fails with TimeoutError if it
    is not complete within ``timeout`` (torchft/futures.py:123-135)."""
    from torchft_tpu.faultinject.core import fault_point

    # deadline-machinery injection site: `error` (exc=TimeoutError)
    # simulates an expired deadline without waiting it out; `delay` stalls
    # the registering thread like a slow op-issue path would
    fault_point("future.deadline", ms_budget=timeout.total_seconds() * 1000)
    out: Future[T] = Future()

    def copy(f: Future[T]) -> None:
        exc = f.exception()
        if exc is not None:
            out.set_exception(exc)
        else:
            out.set_result(f.value())

    fut.then(lambda f: copy(f))
    _TIMEOUT_MANAGER.register(out, timeout)
    return out


def future_wait(fut: Future[T], timeout: timedelta) -> T:
    """Block on ``fut`` up to ``timeout`` (torchft/futures.py:138-165)."""
    return fut.wait(timeout)


def run_in_executor(executor: Any, fn: Callable[..., T], *args: Any, **kwargs: Any) -> Future[T]:
    """Run ``fn`` on ``executor`` (a ``concurrent.futures`` executor) and
    return a chainable :class:`Future` for the result.

    Bridges the stdlib executor world into this module's continuation
    style so callers can ``then``/``wait`` the result uniformly — the
    Manager's pipelined commit vote uses this to ship the
    ``should_commit`` RPC onto its vote thread while the trainer runs the
    next step's compute."""
    out: Future[T] = Future()

    def task() -> None:
        try:
            out.set_result(fn(*args, **kwargs))
        except BaseException as e:  # noqa: BLE001 — error futures carry anything
            out.set_exception(e)

    executor.submit(task)
    return out
