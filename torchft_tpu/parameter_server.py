"""Fault-tolerant parameter server on reconfigurable collectives.

Reference: torchft/parameter_server.py:31-195 — lighthouse-free fault
tolerance: each client asks ``/new_session`` over HTTP, the server hijacks
that request thread for the session's lifetime, and both sides configure a
fresh two-rank collectives epoch through a session-scoped store namespace.
A dead peer simply means the session dies; the client creates a new one —
no global coordination needed.

Server is always rank 0, client rank 1.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import uuid
from abc import ABC, abstractmethod
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from torchft_tpu import telemetry
from torchft_tpu.collectives import Collectives
from torchft_tpu.store import StoreServer

logger = logging.getLogger(__name__)

__all__ = ["ParameterServer"]


class _IPv6Server(ThreadingHTTPServer):
    address_family = socket.AF_INET6
    request_queue_size = 1024
    daemon_threads = True


class ParameterServer(ABC):
    """Threaded parameter server over reconfigurable collectives."""

    def __init__(self, port: int = 0) -> None:
        self.store = StoreServer()
        ps = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"  # connection closes after response

            def log_message(self, fmt, *args):
                pass

            def do_GET(self) -> None:
                # Prometheus exposition, same route every HTTPTransport
                # serves — the parameter server runs its own HTTP surface
                # and was missed in PR 1's exposition sweep.
                if self.path.rstrip("/") == "/metrics":
                    body = telemetry.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    try:
                        self.wfile.write(body)
                    except BrokenPipeError:
                        pass
                    return
                if self.path != "/new_session":
                    self.send_error(400, f"invalid path {self.path}")
                    return
                session_id = str(uuid.uuid4())
                store_addr = f"{ps.store.address()}/session/{session_id}"
                logger.info("creating new session %s", session_id)
                body = (
                    json.dumps(
                        {"session_id": session_id, "store_addr": store_addr}
                    )
                    + "\n"
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                # close eagerly so the client knows the JSON is complete,
                # then hijack this thread for the whole session
                self.finish()
                self.connection.close()
                try:
                    ps._handle_session(session_id, store_addr)
                except Exception:
                    logger.exception("session %s failed", session_id)

        self._server = _IPv6Server(("::", port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def address(self) -> str:
        """``http://host:port/new_session``."""
        port = self._server.socket.getsockname()[1]
        return f"http://{socket.gethostname()}:{port}/new_session"

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self.store.shutdown()

    # -- subclass interface --

    @classmethod
    @abstractmethod
    def new_collectives(cls) -> Collectives:
        """A fresh unconfigured Collectives backend (configured per session)."""

    @abstractmethod
    def forward(self, session_id: str, collectives: Collectives) -> None:
        """Runs once per session on a dedicated thread (loop inside for
        multi-op sessions). Errors free the session; the client reconnects."""

    # -- wiring --

    def _handle_session(self, session_id: str, store_addr: str) -> None:
        coll = self.new_collectives()
        coll.configure(store_addr, rank=0, world_size=2)
        try:
            self.forward(session_id, coll)
        finally:
            coll.shutdown()

    @classmethod
    def new_session(cls, address: str, timeout: float = 60.0) -> Collectives:
        """Client side: create a session, return rank-1-configured
        collectives."""
        import urllib.request

        with urllib.request.urlopen(address, timeout=timeout) as f:
            data = json.load(f)
        logger.info("connecting to session %s", data["session_id"])
        coll = cls.new_collectives()
        coll.configure(data["store_addr"], rank=1, world_size=2)
        return coll
