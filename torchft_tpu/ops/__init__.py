"""TPU compute ops: attention (plain, ring/sequence-parallel, pallas
flash), normalization, rotary embeddings, and MoE dispatch.

The reference has no compute ops of its own (it wraps torch modules); this
package exists because the TPU-native framework owns its training stack.
Everything is jit-/AD-compatible and mesh-aware.
"""

from torchft_tpu.ops.attention import attention, ring_attention
from torchft_tpu.ops.layers import rms_norm, rotary_embed, swiglu

__all__ = ["attention", "ring_attention", "rms_norm", "rotary_embed", "swiglu"]
