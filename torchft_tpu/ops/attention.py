"""Attention: plain softmax attention and ring attention for sequence
parallelism.

Ring attention (Liu et al., arxiv 2310.01889) is the long-context mechanism
the reference lacks entirely (SURVEY.md §5.7): the sequence axis is sharded
over the ``sp`` mesh axis; each device holds a Q block and streams K/V
blocks around the ring via ``ppermute``, maintaining a numerically-stable
running softmax (the flash-attention recurrence), so attention memory is
O(S/sp) per chip and the K/V transfer overlaps compute on the ICI ring.

Implemented with ``lax.scan`` (reverse-differentiable, unlike fori_loop)
inside a partial-manual ``shard_map`` over only the ``sp`` axis — dp/tp
stay under GSPMD so the same code serves every mesh layout.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import torchft_tpu.utils.jax_compat  # noqa: F401 — polyfills older jax

__all__ = [
    "attention",
    "chunked_attention",
    "ring_attention",
    "ring_attention_local",
]

_NEG_INF = -1e30


def _causal_mask(q_pos: jnp.ndarray, k_pos: jnp.ndarray) -> jnp.ndarray:
    """[Sq, Sk] True where k may attend (k_pos <= q_pos)."""
    return k_pos[None, :] <= q_pos[:, None]


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
) -> jnp.ndarray:
    """Plain attention. q/k/v: [B, S, H, Dh] -> [B, S, H, Dh]."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        s = q.shape[1]
        pos = jnp.arange(s)
        scores = jnp.where(_causal_mask(pos, pos)[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def chunked_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    chunk: int = 512,
    tiers: Optional[int] = None,
) -> jnp.ndarray:
    """Plain attention, one q-block at a time: same contract and numerics
    as :func:`attention` ([B, S, H, Dh] -> [B, S, H, Dh]) but the [S, S]
    score matrix is never materialized — a ``lax.scan`` over S/chunk
    q-blocks computes [chunk, S] scores with the softmax fused into the
    block, and ``jax.checkpoint`` recomputes them in the backward.

    This is the HBM-bandwidth fix for long context on TPU: plain
    attention's f32 scores round-trip HBM ([B,H,S,S] ~2 GB at s=8192),
    while here per-block scores stay fusion-local. Measured on v5e at
    b1 h8 s8192 hd64 (fwd+bwd): 57 ms vs 277 ms plain — and it BEATS the
    official pallas flash kernel (71 ms) while remaining pure XLA: it
    needs no shard_map manual region, so it composes with GSPMD sharding
    and the pipeline's manual region where a Mosaic kernel cannot.

    Causal runs additionally skip provably-masked key blocks via static
    k-prefix TIERS: q-segment t of ``tiers`` only scores against keys
    ``[0, (t+1)·S/tiers)`` — at 4 tiers that is 62.5% of the full S²
    score flops (53% at 16) for ~tiers compiled bodies (still one jit).
    ``tiers=None`` adapts to S: more tiers pay off once segments stay
    ~2k rows (v5e sweep: s=32k fwd+bwd 140→121 ms going 4→16 tiers;
    s=8k prefers 4–8).

    Requires ``S % chunk == 0`` (callers fall back to plain otherwise).
    """
    b, s, h, d = q.shape
    assert s % chunk == 0, f"seq {s} not divisible by chunk {chunk}"
    if tiers is None:
        # round-5 v5e sweep (full-model grads, C=128): s<=8k prefers 4
        # tiers (9.92 vs 9.44 steps/s at 8k going 4->8), s>=16k prefers
        # 16 (16k: 3.34 vs 3.29 at 8; 32k: 1046 ms at 16 vs 1089 at 8)
        tiers = 16 if s >= 16384 else 4
    # the divisibility gate below would otherwise silently drop tiering
    # for (s, chunk) pairs the pick doesn't divide — fall to the largest
    # compatible tier count instead. Applies to EXPLICIT tier counts too:
    # an env override hitting the gate would otherwise disable tiering
    # entirely rather than degrade gracefully (round-5 review).
    while tiers > 1 and s % (tiers * chunk) != 0:
        tiers -= 1
    scale = d**-0.5

    def scan_segment(q_seg: jnp.ndarray, k_seg, v_seg, q0: int) -> jnp.ndarray:
        """q_seg [B,Sq,H,D] against k_seg/v_seg [B,Sk,H,D]; q positions
        start at q0 (static)."""
        sq = q_seg.shape[1]
        nq = sq // chunk
        qb = jnp.moveaxis(q_seg.reshape(b, nq, chunk, h, d), 1, 0)
        k_pos = jnp.arange(k_seg.shape[1])

        def body(carry, xs):
            qc, i = xs
            scores = jnp.einsum("bqhd,bkhd->bhqk", qc, k_seg) * scale
            if causal:
                q_pos = q0 + i * chunk + jnp.arange(chunk)
                m = k_pos[None, :] <= q_pos[:, None]
                scores = jnp.where(m[None, None], scores, _NEG_INF)
            p = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(
                q_seg.dtype
            )
            return carry, jnp.einsum("bhqk,bkhd->bqhd", p, v_seg)

        _, out = jax.lax.scan(jax.checkpoint(body), 0, (qb, jnp.arange(nq)))
        return jnp.moveaxis(out, 0, 1).reshape(b, sq, h, d)

    if not causal or tiers <= 1 or s % (tiers * chunk) != 0:
        return scan_segment(q, k, v, 0)
    seg = s // tiers
    outs = []
    for t in range(tiers):
        outs.append(
            scan_segment(
                q[:, t * seg : (t + 1) * seg],
                k[:, : (t + 1) * seg],
                v[:, : (t + 1) * seg],
                t * seg,
            )
        )
    return jnp.concatenate(outs, axis=1)


def ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    sp_size: int,
    causal: bool = True,
    axis: str = "sp",
) -> jnp.ndarray:
    """Per-shard ring attention body: q/k/v are the local [B, Sl, H, Dh]
    blocks. Call directly when already inside a manual region over ``sp``
    (e.g. the pp pipeline — Shardy forbids nesting another shard_map);
    otherwise use :func:`ring_attention`, which wraps this in its own
    shard_map."""
    my = jax.lax.axis_index(axis)
    b, sl, h, dh = q.shape
    scale = dh**-0.5
    q_pos = my * sl + jnp.arange(sl)

    qf = q.astype(jnp.float32)

    def step(carry, _):
        # k/v blocks rotate right each step, so at step t we hold the block
        # originally owned by shard (my - t) % sp
        acc, m, l, k_cur, v_cur, owner = carry
        k_pos = owner * sl + jnp.arange(sl)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, k_cur.astype(jnp.float32))
        scores = scores * scale
        if causal:
            mask = _causal_mask(q_pos, k_pos)
            scores = jnp.where(mask[None, None], scores, _NEG_INF)

        blk_max = jnp.max(scores, axis=-1)  # [B,H,Sl]
        new_m = jnp.maximum(m, blk_max)
        # rescale previous accumulator, add this block's contribution
        correction = jnp.exp(m - new_m)
        p = jnp.exp(scores - new_m[..., None])  # [B,H,Sq,Sk]
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32)
        )
        l = l * correction + jnp.sum(p, axis=-1)

        perm = [(r, (r + 1) % sp_size) for r in range(sp_size)]
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        owner = (owner - 1) % sp_size
        return (acc, new_m, l, k_nxt, v_nxt, owner), ()

    # Initial accumulators must carry the same varying-manual-axes type as
    # the scan outputs (jax>=0.9 VMA typing). Deriving them from q (zeroed,
    # XLA folds it) inherits q's full varying set — which includes any
    # *other* manual axes active when ring attention is nested inside e.g.
    # the pp pipeline, not just 'sp'.
    zero_bhq = jnp.einsum("bqhd->bhq", qf) * 0.0
    acc0 = jnp.einsum("bqhd->bhqd", qf) * 0.0
    m0 = zero_bhq + _NEG_INF
    l0 = zero_bhq
    (acc, m, l, _, _, _), _ = jax.lax.scan(
        step, (acc0, m0, l0, k, v, my), None, length=sp_size
    )
    # rows with no visible keys (can't happen with causal self-attn) guard
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh,
    causal: bool = True,
    axis: str = "sp",
) -> jnp.ndarray:
    """Sequence-parallel attention over mesh axis ``axis``.

    q/k/v: [B, S, H, Dh] with S sharded over ``axis``; other axes remain
    GSPMD-managed. Falls back to plain attention when the axis is size 1.
    """
    sp_size = mesh.shape[axis]
    if sp_size == 1:
        return attention(q, k, v, causal=causal)

    body = functools.partial(
        ring_attention_local, sp_size=sp_size, causal=causal, axis=axis
    )
    spec = P(None, axis, None, None)
    # mesh is intentionally not forwarded: inside another partial-manual
    # region (e.g. the pp pipeline) the context mesh already has those axes
    # marked Manual, and shard_map requires an exact match — the ambient
    # mesh is always the right one. `mesh` is only used for sp_size above.
    return jax.shard_map(
        body,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={axis},
    )(q, k, v)
