"""Building-block layers: RMSNorm, rotary embeddings, SwiGLU, MoE dispatch.

All pure functions over explicit params — XLA fuses the elementwise chains
into the adjacent matmuls, so there is nothing to hand-schedule here
(pallas is reserved for attention, where fusion across the softmax is
beyond XLA).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["rms_norm", "rotary_embed", "swiglu", "moe_dispatch"]


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(dtype) * weight


def rotary_embed(
    x: jnp.ndarray, positions: jnp.ndarray, theta: float = 10000.0
) -> jnp.ndarray:
    """RoPE. x: [B, S, H, Dh], positions: [S] (global positions, so the
    same code is correct under sequence sharding)."""
    dh = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, dh, 2, dtype=jnp.float32) / dh)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [S, Dh/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., ::2], x[..., 1::2]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out1 = xf1 * cos - xf2 * sin
    out2 = xf1 * sin + xf2 * cos
    out = jnp.stack([out1, out2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def swiglu(x: jnp.ndarray, w_gate: jnp.ndarray, w_in: jnp.ndarray, w_out: jnp.ndarray) -> jnp.ndarray:
    """SwiGLU FFN: (silu(x@w_gate) * (x@w_in)) @ w_out."""
    h = jax.nn.silu(x @ w_gate) * (x @ w_in)
    return h @ w_out


def moe_dispatch(
    gates: jnp.ndarray, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-2 token→expert dispatch with capacity (mesh-tensorflow style —
    static shapes, einsum-friendly, so XLA turns the expert axis sharding
    into an all-to-all over ``ep``).

    gates: [G, E] softmax router probabilities for G tokens.
    Returns (dispatch [G, E, C] one-hot-ish float, combine [G, E, C]).
    Tokens over capacity are dropped (standard MoE behavior).
    """
    g, e = gates.shape

    # top-1 choice
    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = jax.nn.one_hot(idx1, e, dtype=gates.dtype)  # [G, E]
    # top-2: mask out the first choice
    gates2 = gates * (1.0 - mask1)
    idx2 = jnp.argmax(gates2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=gates.dtype)

    # position of each token within its expert's buffer (first-come order)
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1  # [G, E], 0-indexed
    # second choices queue behind all first choices
    pos2 = (jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0)[None, :]) * mask2

    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)

    # renormalize the two gate values over the kept choices
    g1 = jnp.sum(gates * keep1, axis=-1)
    g2 = jnp.sum(gates * keep2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    c_range = jnp.arange(capacity, dtype=gates.dtype)
    onehot_pos1 = (pos1[..., None] == c_range) * keep1[..., None]  # [G,E,C]
    onehot_pos2 = (pos2[..., None] == c_range) * keep2[..., None]

    dispatch = onehot_pos1 + onehot_pos2
    combine = onehot_pos1 * g1[:, None, None] + onehot_pos2 * g2[:, None, None]
    return dispatch, combine
