"""Flash attention (causal) as pallas TPU kernels, fwd + bwd.

FlashAttention-2 style: the [Sq, Sk] score matrix never materializes in
HBM; probabilities are recomputed blockwise in the backward from a saved
logsumexp. The K/V (resp. Q/dO) block axis is the innermost *grid*
dimension — pallas double-buffers each block's HBM→VMEM DMA against the
previous block's compute — with the running accumulators (acc/m/l, dq,
dk/dv) living in VMEM scratch that persists across the inner grid
sweep (TPU grids execute sequentially per core).

Causal scheduling masks the diagonal blocks and skips compute above the
diagonal via ``pl.when``.

Matmuls keep their storage dtype (bf16) into the MXU and request
``preferred_element_type=float32`` (f32 accumulate). On CPU the kernels
run under ``interpret=True`` so unit tests check numerics against
``ops.attention``.

Role: this kernel is the MEMORY-CEILING path — it makes sequences whose
[S,S] scores can't fit HBM trainable at all (32k tokens on one v5e chip).
It is not the speed path: at d=64 each 128×128 block is ~2 microscopic
matmuls, so the grid is DMA/sequencing-latency-bound and XLA's fused
attention is an order of magnitude faster wherever it fits (measured 19x
fwd at s=8192 on v5e). The standard remedies — larger blocks, grouping
heads per grid step — are rejected by this environment's Mosaic compiler
(remote-compile crashes on any non-(1,128,128) block structure), so the
crossover is handled in policy instead: models/transformer.py
``_use_flash`` engages this kernel only above the scores-memory
threshold.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

_NEG_INF = -1e30
_LANES = 128  # m/l scratch padded to a full lane tile


def _should_interpret() -> bool:
    return jax.default_backend() == "cpu"


def _iota(n: int) -> jnp.ndarray:
    # 1D iota is unsupported on TPU; build 2D and squeeze
    return jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0)[:, 0]


def _dot(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# forward: grid (bh, nq, nk) — nk innermost, acc/m/l in scratch
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, bq, bk, scale, causal,
):
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # blocks strictly above the diagonal contribute nothing
    run = (j * bk <= i * bq + bq - 1) if causal else True

    @pl.when(run)
    def _compute():
        # inputs keep their storage dtype (bf16): the MXU takes bf16
        # operands at full rate and accumulates f32 via
        # preferred_element_type — upcasting first costs an extra VPU pass
        s = _dot(q_ref[0], k_ref[0], ((1,), (1,))) * scale
        if causal:
            q_pos = i * bq + _iota(bq)
            k_pos = j * bk + _iota(bk)
            s = jnp.where(k_pos[None, :] <= q_pos[:, None], s, _NEG_INF)
        m_prev = m_ref[:, 0]
        blk_max = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, blk_max)
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        acc_ref[...] = acc_ref[...] * corr[:, None] + _dot(
            p.astype(v_ref.dtype), v_ref[0], ((1,), (0,))
        )
        l_ref[:, 0] = l_ref[:, 0] * corr + jnp.sum(p, axis=1)
        m_ref[:, 0] = m_new

    @pl.when(j == nk - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)
        lse = m_ref[:, 0] + jnp.log(l)
        lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, bq))


def _fwd(q, k, v, bq, bk, scale, causal, interpret):
    bh, s, d = q.shape
    grid = (bh, s // bq, s // bk)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, bq=bq, bk=bk, scale=scale, causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref,
    *, bq, bk, scale, causal,
):
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = (j * bk <= i * bq + bq - 1) if causal else True

    @pl.when(run)
    def _compute():
        lse = lse_ref[0, 0, :]
        delta = delta_ref[0, 0, :]
        s = _dot(q_ref[0], k_ref[0], ((1,), (1,))) * scale
        if causal:
            q_pos = i * bq + _iota(bq)
            k_pos = j * bk + _iota(bk)
            s = jnp.where(k_pos[None, :] <= q_pos[:, None], s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = _dot(do_ref[0], v_ref[0], ((1,), (1,)))
        ds = p * (dp - delta[:, None]) * scale
        acc_ref[...] += _dot(ds.astype(k_ref.dtype), k_ref[0], ((1,), (0,)))

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, bq, bk, scale, causal,
):
    j, i = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = (i * bq + bq - 1 >= j * bk) if causal else True

    @pl.when(run)
    def _compute():
        lse = lse_ref[0, 0, :]
        delta = delta_ref[0, 0, :]
        s = _dot(q_ref[0], k_ref[0], ((1,), (1,))) * scale
        if causal:
            q_pos = i * bq + _iota(bq)
            k_pos = j * bk + _iota(bk)
            s = jnp.where(k_pos[None, :] <= q_pos[:, None], s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dv_acc[...] += _dot(p.astype(do_ref.dtype), do_ref[0], ((0,), (0,)))
        dp = _dot(do_ref[0], v_ref[0], ((1,), (1,)))
        ds = p * (dp - delta[:, None]) * scale
        dk_acc[...] += _dot(ds.astype(q_ref.dtype), q_ref[0], ((0,), (0,)))

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(bq, bk, scale, causal, interpret, res, do):
    q, k, v, o, lse = res
    bh, s, d = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, None, :], (bh, 8, s))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, bq=bq, bk=bk, scale=scale, causal=causal),
        grid=(bh, s // bq, s // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, bq, d), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, bq=bq, bk=bk, scale=scale, causal=causal),
        grid=(bh, s // bk, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, bq), lambda b, j, i: (b, 0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, bq), lambda b, j, i: (b, 0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, bq, bk, causal, interpret):
    scale = q.shape[-1] ** -0.5
    o, _ = _fwd(q, k, v, bq, bk, scale, causal, interpret)
    return o


def _flash_fwd(q, k, v, bq, bk, causal, interpret):
    scale = q.shape[-1] ** -0.5
    o, lse = _fwd(q, k, v, bq, bk, scale, causal, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(bq, bk, causal, interpret, res, do):
    scale = res[0].shape[-1] ** -0.5
    return _bwd(bq, bk, scale, causal, interpret, res, do)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Causal flash attention. q/k/v: [B, S, H, Dh] -> [B, S, H, Dh].

    Requires S % block == 0 (pick smaller blocks for short sequences).
    Differentiable (custom FlashAttention-2 backward)."""
    b, s, h, d = q.shape
    bq = min(block_q, s)
    bk = min(block_k, s)
    if s % bq or s % bk:
        raise ValueError(f"seq len {s} must be a multiple of block sizes ({bq},{bk})")
    if interpret is None:
        interpret = _should_interpret()

    def pack(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    o = _flash(pack(q), pack(k), pack(v), bq, bk, causal, interpret)
    return o.reshape(b, h, s, d).transpose(0, 2, 1, 3)
