"""Pallas TPU kernels for the hot ops.

XLA fuses elementwise chains into matmuls on its own; these kernels cover
what it can't — fusion *across* the attention softmax (flash attention's
O(S) memory recurrence). CPU tests run the same kernels in interpreter
mode.
"""

from torchft_tpu.ops.pallas.flash_attention import flash_attention

__all__ = ["flash_attention"]
