"""Manager — the per-replica fault-tolerance runtime.

Re-implements the reference's Manager state machine
(/root/reference/torchft/manager.py:87-728) for a JAX data plane:

* ``start_quorum`` kicks off an async quorum on a worker thread so the
  quorum RPC overlaps the forward pass (manager.py:366-416).
* ``allreduce`` averages host gradient buffers across replica groups via
  the reconfigurable collectives; healing/spare replicas contribute zeros
  and the division is by ``num_participants()``, not world size
  (manager.py:243-304).
* ``should_commit`` is the per-step commit barrier: drain pending work,
  apply any staged recovery state, vote through the manager server; the
  optimizer steps only on a unanimous True (manager.py:546-599).

TPU framing: within a replica group, parallelism is a jax Mesh and XLA's
own ICI collectives (torchft_tpu.parallel); the Manager governs only the
*cross-replica-group* axis, which lives outside jit on host buffers so the
compiled train step never recompiles when membership changes.
"""

from __future__ import annotations

import concurrent.futures
import logging
import os
import socket
import threading
import uuid
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, Tuple, TypeVar, cast

import numpy as np

from torchft_tpu import telemetry
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.collectives import Collectives, ReduceOp
from torchft_tpu.coordination import ManagerClient, ManagerServer
from torchft_tpu.faultinject.core import fault_point
from torchft_tpu.futures import Future, future_timeout, run_in_executor
from torchft_tpu.profiling import StepTimer
from torchft_tpu.store import StoreClient

T = TypeVar("T")

logger = logging.getLogger(__name__)

MANAGER_ADDR_KEY: str = "manager/addr"
REPLICA_ID_KEY: str = "manager/replica_id"
MANAGER_PORT_ENV: str = "TORCHFT_MANAGER_PORT"
LIGHTHOUSE_ENV: str = "TORCHFT_LIGHTHOUSE"
STORE_ADDR_ENV: str = "TORCHFT_STORE_ADDR"
COMMIT_PIPELINE_ENV: str = "TORCHFT_COMMIT_PIPELINE"

__all__ = ["Manager", "WorldSizeMode"]


class _PendingCommit:
    """Book-keeping for one in-flight (pipelined) commit vote.

    Everything the post-vote accounting needs is snapshotted at ISSUE
    time — by the time the vote resolves, the trainer is mid-way through
    the next step and the manager's live fields (``_errored``,
    ``_step_epochs``, ``_step_n``) already describe that step."""

    __slots__ = (
        "future",
        "step",
        "n_step",
        "local_vote",
        "enough_replicas",
        "mixed_epochs",
        "errored",
        "prepare_s",
        "on_resolved",
        "digest",
        "epoch",
    )

    def __init__(self) -> None:
        self.future: Optional[Future] = None
        self.step = 0
        self.n_step = 0
        self.local_vote = False
        self.enough_replicas = False
        self.mixed_epochs = False
        self.errored: Optional[Exception] = None
        self.prepare_s = 0.0
        self.on_resolved: Optional[Callable[[bool], None]] = None
        # divergence sentinel: this step's folded post-reduce digest and
        # the plane epoch it was reduced under (docs/observability.md)
        self.digest: Optional[str] = None
        self.epoch = -1


class WorldSizeMode(Enum):
    """Numerics policy when replica groups die (manager.py:55-70).

    DYNAMIC: batch size scales with the live group count — gradients divide
    by the *current* participant count.
    FIXED_WITH_SPARES: world size is pinned at ``min_replica_size``; extra
    groups are demoted to hot spares that contribute zeros, so the divisor
    (and effective batch size) never changes.
    """

    DYNAMIC = 0
    FIXED_WITH_SPARES = 1


_DIV_JIT = None


def _divide_tree(arrays: List[Any], n: int) -> List[Any]:
    """One jitted kernel dividing every array by ``n`` (device path of
    gradient normalization). ``n`` is a traced scalar so membership changes
    never recompile; the jit caches per list structure/shapes."""
    global _DIV_JIT
    import jax

    if _DIV_JIT is None:

        def f(xs, n):
            return [(x / n).astype(x.dtype) for x in xs]

        _DIV_JIT = jax.jit(f)
    return _DIV_JIT(arrays, np.float32(n))


class _ManagerLogger:
    """Prefixes every line with ``[replica_id/rank - step N]``
    (manager.py:709-728)."""

    def __init__(self, manager: "Manager", replica_id: str, rank: int) -> None:
        self._logger = logging.getLogger("torchft_tpu.manager")
        self._replica_id = replica_id
        self._rank = rank
        self._manager = manager

    def _prefix(self) -> str:
        return f"[{self._replica_id}/{self._rank} - step {self._manager.current_step()}]"

    def info(self, msg: str) -> None:
        self._logger.info(f"{self._prefix()} {msg}")

    def warn(self, msg: str) -> None:
        self._logger.warning(f"{self._prefix()} {msg}")

    def exception(self, msg: str) -> None:
        self._logger.exception(f"{self._prefix()} {msg}")


class Manager:
    """Fault-tolerance manager for one rank of one replica group."""

    def __init__(
        self,
        collectives: Collectives,
        load_state_dict: Optional[Callable[[T], None]],
        state_dict: Optional[Callable[[], T]],
        min_replica_size: int,
        use_async_quorum: bool = True,
        timeout: timedelta = timedelta(seconds=60),
        quorum_timeout: timedelta = timedelta(seconds=60),
        connect_timeout: timedelta = timedelta(seconds=60),
        rank: Optional[int] = None,
        world_size: Optional[int] = None,
        world_size_mode: WorldSizeMode = WorldSizeMode.DYNAMIC,
        store_addr: Optional[str] = None,
        lighthouse_addr: Optional[str] = None,
        replica_id: Optional[str] = None,
        port: Optional[int] = None,
        hostname: Optional[str] = None,
        heartbeat_interval: timedelta = timedelta(milliseconds=100),
        checkpoint_transport: Optional[CheckpointTransport[Dict[str, T]]] = None,
        commit_pipeline: bool = False,
    ) -> None:
        """
        Args:
            collectives: the reconfigurable cross-replica-group collectives
                (unconfigured; the Manager configures it each quorum change)
            load_state_dict / state_dict: user snapshot/restore callbacks for
                live recovery (set later via :meth:`set_state_dict_fns` if
                the model is built after the manager)
            min_replica_size: minimum replica groups for a step to commit
            use_async_quorum: overlap the quorum RPC with the forward pass
            timeout: default deadline for collectives, commit votes, and
                checkpoint transfers
            quorum_timeout: deadline for quorum formation — must exceed the
                interval between syncs (≈1h for infrequent LocalSGD syncs)
            rank / world_size: this rank within the replica group (env RANK /
                WORLD_SIZE fallback)
            store_addr: ``host:port`` of the replica group's KV store
                (TORCHFT_STORE_ADDR fallback)
            lighthouse_addr: rank-0 only; TORCHFT_LIGHTHOUSE fallback
            replica_id: rank-0 only; a uuid4 suffix is always appended so
                restarted groups are distinct lighthouse members
            port: rank-0 manager server port (TORCHFT_MANAGER_PORT fallback,
                else ephemeral)
            commit_pipeline: opt into pipelined commit — the per-step
                ``should_commit`` vote is issued asynchronously
                (:meth:`should_commit_async`) so the next step's compute
                overlaps the vote RTT; semantics stay identical to sync
                mode via snapshot/rollback in the trainer (see
                ``docs/commit_pipeline.md``). ``TORCHFT_COMMIT_PIPELINE=1``
                enables it too. All replica groups must agree on this.
        """
        self._load_state_dict = load_state_dict
        self._user_state_dict = state_dict
        # unguarded-ok: quorum-thread handoff — staged by the quorum
        #   thread during heal, applied on the main thread strictly after
        #   wait_quorum() (asserted in _apply_pending_state_dict)
        self._pending_state_dict: Optional[Dict[str, object]] = None
        self._use_async_quorum = use_async_quorum
        self._timeout = timeout
        self._quorum_timeout = quorum_timeout
        self._connect_timeout = connect_timeout
        self._world_size_mode = world_size_mode
        self._min_replica_size = min_replica_size
        self._commit_pipeline = commit_pipeline or (
            os.environ.get(COMMIT_PIPELINE_ENV, "0") == "1"
        )

        store_addr = store_addr or os.environ[STORE_ADDR_ENV]
        self._rank: int = rank if rank is not None else int(os.environ["RANK"])
        rank = self._rank
        world_size = world_size or int(os.environ["WORLD_SIZE"])

        if checkpoint_transport is None:
            checkpoint_transport = HTTPTransport(timeout=timeout, num_chunks=0)
        self._checkpoint_transport: CheckpointTransport[Dict[str, T]] = (
            checkpoint_transport
        )

        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="async_quorum"
        )
        self._quorum_future: Optional[concurrent.futures.Future] = None  # guarded-by: _qf_lock
        # guards _quorum_future replacement: the death watch may submit a
        # premature re-quorum from its monitor thread (see _on_peer_death)
        self._qf_lock = threading.Lock()
        self._shutting_down = False
        self._last_quorum_args: tuple = (True, False, None)

        self._store = StoreClient(store_addr, connect_timeout=connect_timeout)
        self._collectives = collectives
        self._manager: Optional[ManagerServer] = None

        self._lighthouse_addr: Optional[str] = None
        if rank == 0:
            if port is None:
                port = int(os.environ.get(MANAGER_PORT_ENV, 0))
            lighthouse_addr = lighthouse_addr or os.environ[LIGHTHOUSE_ENV]
            self._lighthouse_addr = lighthouse_addr
            replica_id = (replica_id or "") + str(uuid.uuid4())
            self._manager = ManagerServer(
                replica_id=replica_id,
                lighthouse_addr=lighthouse_addr,
                hostname=hostname or socket.gethostname(),
                bind=f"[::]:{port}",
                store_addr=store_addr,
                world_size=world_size,
                heartbeat_interval=heartbeat_interval,
                connect_timeout=connect_timeout,
            )
            self._store.set(MANAGER_ADDR_KEY, self._manager.address())
            self._store.set(REPLICA_ID_KEY, replica_id)

        addr = self._store.get(MANAGER_ADDR_KEY).decode()
        self._manager_addr = addr
        self._client = ManagerClient(addr, connect_timeout=connect_timeout)
        replica_id = self._store.get(REPLICA_ID_KEY).decode()
        self._logger = _ManagerLogger(self, replica_id or "", rank)
        self._replica_id = replica_id or ""

        # unguarded-ok: quorum-thread handoff — the caller's wait_quorum()
        #   barrier (and the commit drain) orders the quorum thread's heal
        #   write of _step against main-thread reads/increments
        self._step = 0
        self._step_label = 0  # physical-step coordinate (see start_quorum)
        self._quorum_id = -1
        # _participant_ids/_evicted cross three threads: the quorum thread
        # replaces membership each epoch, while the death-watch monitor and
        # main-thread error paths report evictions. The lock closes the
        # check-then-add race (a victim double-reported = a wasted
        # lighthouse liveness probe + duplicate trail records) and keeps
        # attribution reading a consistent (ids, evicted) pair
        # [found by the analysis gate: unguarded-shared-write].
        self._evict_lock = threading.Lock()
        self._participant_ids: List[str] = []  # guarded-by: _evict_lock
        self._evicted: set = set()  # guarded-by: _evict_lock
        # (plane_generation, participant_ids) armed for the death watch
        self._death_watch_snapshot: Optional[Tuple[int, List[str]]] = None
        # unguarded-ok: issue-time latch — written by the main thread and
        #   op-callback/quorum threads, read at the commit barrier after
        #   the pending-work drain (last-write-wins is the latch contract)
        self._commit_failures = 0  # pending data-plane flush request
        # unguarded-ok: error latch — any thread may latch, the commit
        #   barrier reads after draining pending work; a racing latch only
        #   changes WHICH error aborts the step, never whether it aborts
        self._errored: Optional[Exception] = None
        self._errored_epoch = -1  # quorum_id whose plane produced _errored
        self._step_epochs: set = set()  # quorum_ids this step's ops ran on
        self._step_n: Optional[int] = None  # issue-time participant count

        # Active failure detection: the data plane's sockets learn about a
        # dead peer (FIN/RST) within milliseconds — long before the next
        # collective op would trip over them. Wire that signal back so the
        # eviction + re-quorum overlap the doomed step instead of starting
        # at the next step boundary (the "<1 step" recovery envelope).
        if hasattr(collectives, "set_death_watch"):
            collectives.set_death_watch(self._on_peer_death)
        # unguarded-ok: quorum-thread handoff — wait_quorum() (async mode)
        #   or the synchronous start_quorum path is the happens-before
        #   barrier between the quorum thread's writes and main reads
        self._healing = False
        # unguarded-ok: quorum-thread handoff — same barrier as _healing
        self._group_healing = False
        self._pending_work: List[Future] = []
        # unguarded-ok: quorum-thread handoff — heal-path restore on the
        #   quorum thread, increments on the main thread post-drain
        self._batches_committed = 0

        # Pipelined commit (see docs/commit_pipeline.md): the vote RPC for
        # step k rides its own thread + socket while the trainer runs step
        # k+1's compute. _spec_cond fences the quorum thread's heal
        # send/recv paths until the main thread resolves the vote, so a
        # served checkpoint is never speculative state. At most ONE vote
        # is ever outstanding (should_commit_async asserts it).
        self._pending_commit: Optional[_PendingCommit] = None
        self._spec_cond = threading.Condition()
        self._commit_executor: Optional[ThreadPoolExecutor] = None
        # dedicated vote client: self._client serializes calls on one
        # socket, so a pipelined vote would otherwise queue behind (or
        # ahead of) the next step's long-poll quorum RPC
        self._commit_client: Optional[ManagerClient] = None
        # rolling steps/sec with quorum/heal steps tagged as outliers;
        # should_commit ticks it, so its outlier durations are the
        # recorded per-step recovery cost (telemetry step_outlier events)
        self.step_timer = StepTimer()
        # step-anatomy ledger (ISSUE 8): _finish_commit ticks the process
        # ledger so every step's wall clock is decomposed into phases;
        # attaching the timer exports its tagged-outlier digest through
        # anatomy summaries and the flight-recorder dumps
        telemetry.LEDGER.attach_timer(self.step_timer)
        # burn-rate SLO evaluators (telemetry/slo.py; env-gated — zero
        # cost unless TORCHFT_SLO_STEP_S / TORCHFT_SLO_REJOIN_S are set);
        # the latch rides the telemetry piggyback to the lighthouse
        from torchft_tpu.telemetry.slo import SloManager

        self._slo = SloManager()
        # unguarded-ok: quorum-thread handoff — set at heal begin on the
        #   quorum thread, consumed at the next committed _finish_commit
        #   on the main thread (wait_quorum is the barrier)
        self._rejoin_t0: Optional[float] = None
        # opt-in fleet straggler monitor: any Manager that knows the
        # lighthouse address can host the detector (one per fleet is
        # enough; the faultmatrix runner runs its own)
        self._fleet_monitor = None
        if (
            os.environ.get("TORCHFT_STRAGGLER_MONITOR", "0") == "1"
            and self._lighthouse_addr is not None
            and self._rank == 0
        ):
            from torchft_tpu.telemetry.slo import FleetMonitor

            self._fleet_monitor = FleetMonitor(self._lighthouse_addr).start()
        # opt-in history-plane monitors (ISSUE 11): the perf-regression
        # sentinel and the critical-path attributor both consume the
        # lighthouse's retained time series; one knob hosts both (one
        # history plane), rank 0 only, like the straggler monitor
        self._regression_monitor = None
        self._critical_path_monitor = None
        self._cp_stop = threading.Event()
        self._cp_thread: Optional[threading.Thread] = None
        if (
            os.environ.get("TORCHFT_REGRESSION_MONITOR", "0") == "1"
            and self._lighthouse_addr is not None
            and self._rank == 0
        ):
            from torchft_tpu.telemetry.critical_path import (
                CriticalPathMonitor,
            )
            from torchft_tpu.telemetry.regression import RegressionMonitor

            # one poll thread feeds BOTH consumers from one
            # /timeseries.json fetch per interval — the full-ring reply
            # can be megabytes, and two independent pollers would pay it
            # (and the lighthouse's tsdb mutex) twice
            self._regression_monitor = RegressionMonitor(
                self._lighthouse_addr
            )
            self._critical_path_monitor = CriticalPathMonitor(
                self._lighthouse_addr
            )
            self._start_history_thread()

        self._participating_rank: Optional[int] = None
        self._participating_world_size: int = 0

        # Differential heal (docs/heal_plane.md, TORCHFT_HEAL_DIFF=1):
        # a bounded per-leaf digest trail over recent committed steps.
        # Recorded on the MAIN thread at each step's start_quorum (the
        # state there is exactly the committed state at current_step);
        # the serving side's delta endpoint and this replica's own heal
        # request both read it. None when the feature is off — the
        # per-step flatten+digest is not free.
        from torchft_tpu.checkpointing import delta as _delta

        self._heal_trail = _delta.CommitTrail() if _delta.diff_enabled() else None
        if self._heal_trail is not None and hasattr(
            self._checkpoint_transport, "commit_trail"
        ):
            self._checkpoint_transport.commit_trail = self._heal_trail
        # heal-recv/compile overlap: a user-registered warmup callback,
        # fired on a daemon thread with the incoming state's spec tree as
        # soon as the transfer header is known (set_heal_warmup)
        self._heal_warmup: Optional[Callable[[Any], None]] = None

        # Hang forensics (PR 2): SIGUSR2 dumps the collective flight
        # recorder (best-effort — only possible from the main thread), and
        # the step watchdog turns a silently wedged step into a
        # watchdog_stall event + flight dump + a stuck flag the lighthouse
        # dashboard surfaces per replica.
        telemetry.install_sigusr2()
        # on_stall pushes the stuck report DIRECTLY to the lighthouse:
        # the regular piggyback rides quorum RPCs, which a wedged step
        # never issues — exactly the scenario the stuck flag exists for
        self._watchdog = telemetry.StepWatchdog(on_stall=self._on_stall)
        # Diagnosis plane (ISSUE 12): the Python stack sampler runs
        # always-on at TORCHFT_PROF_HZ (0 disarms; the native sampler
        # arms itself at thread registration), and — when
        # TORCHFT_DIAG_DIR is set — a DiagnosisEngine turns latch events
        # (straggler / perf-regression / SLO / watchdog / divergence)
        # into bounded deep-capture bundles, announced on the piggyback.
        from torchft_tpu.telemetry.diagnosis import DiagnosisEngine, diag_dir
        from torchft_tpu.telemetry.profiler import PROFILER

        PROFILER.ensure_started()
        self._diagnosis: Optional[DiagnosisEngine] = None
        if diag_dir():
            self._diagnosis = DiagnosisEngine(
                replica_id=self._replica_id,
                lighthouse_addr=self._lighthouse_addr,
            ).install()
        self._last_heal_ts = 0.0
        telemetry.TRACER.set_context(
            replica_id=self._replica_id, step=self._step, quorum_epoch=-1
        )
        # crash-durable black box (docs/observability.md "Forensics"):
        # keep its (replica, step, epoch) context in lockstep with the
        # tracer's so every mirrored record carries the clock-sync-free
        # coordinates the postmortem merge orders by
        telemetry.BLACKBOX.set_context(
            replica_id=self._replica_id, step=self._step, quorum_epoch=-1
        )

        # Divergence sentinel (docs/observability.md): digest the step's
        # post-reduce state and let the lighthouse compare it within the
        # (epoch, step) cohort at the commit boundary. The fence
        # (TORCHFT_DIVERGENCE_FENCE=1, implies the sentinel) additionally
        # vetoes the commit on a mismatch — all groups must agree on the
        # fence, like commit_pipeline. Off by default: hashing every
        # reduced buffer is not free.
        self._divergence_fence = (
            os.environ.get("TORCHFT_DIVERGENCE_FENCE", "0") == "1"
        )
        self._divergence_sentinel = self._divergence_fence or (
            os.environ.get("TORCHFT_DIVERGENCE_SENTINEL", "0") == "1"
        )
        # ordered per-op tree digests of this step's reduced outputs;
        # appended on the op-callback thread (ops resolve in issue order
        # — the op thread is serial), folded + cleared at _prepare_commit
        self._step_digests: List[str] = []
        self._divergence_latched = False

    def _start_history_thread(self) -> None:
        """Poll loop hosting the history-plane consumers (rank 0, armed
        by TORCHFT_REGRESSION_MONITOR=1): ONE /timeseries.json fetch per
        TORCHFT_REGRESSION_POLL_S feeds the regression sentinel and the
        critical-path attributor — each keeps its own per-(replica,
        series) cursor, so sharing the reply is free."""
        from torchft_tpu.telemetry.regression import _env_float
        from torchft_tpu.telemetry.timeseries import poll_timeseries

        poll_s = _env_float("TORCHFT_REGRESSION_POLL_S", 2.0)

        def run() -> None:
            while not self._cp_stop.wait(poll_s):
                try:
                    reply = poll_timeseries(self._lighthouse_addr)
                    if not reply:
                        continue
                    self._regression_monitor.poll_once(reply=reply)
                    self._critical_path_monitor.poll_once(reply=reply)
                except Exception:  # noqa: BLE001 — monitoring must not die
                    pass

        self._cp_thread = threading.Thread(
            target=run, daemon=True, name="tft_history_monitor"
        )
        self._cp_thread.start()

    def _on_stall(self, step: int, elapsed_s: float, threshold_s: float) -> None:
        """Watchdog stall callback (watchdog thread): ship the stuck
        report out-of-band. A wedged step sends no quorum RPCs, so the
        normal piggyback can't carry the flag; push one heartbeat with
        the telemetry payload straight to the lighthouse instead
        (rank 0 only — it knows the lighthouse address). Best-effort and
        time-bounded: forensics must never deepen a hang. Note this adds
        no liveness signal the C++ manager's own heartbeat loop isn't
        already sending — it only attaches the telemetry."""
        if self._lighthouse_addr is None or self._shutting_down:
            return

        def _push() -> None:
            try:
                from torchft_tpu.coordination import LighthouseClient

                # out-of-band push: always the self-contained legacy
                # JSON row, never the delta encoder — this thread racing
                # the quorum path's encode (or its heartbeat arriving
                # out of order) would break the version chain; a JSON
                # row lands regardless of the chain's state, even after
                # a lighthouse restart (the ingest is format-blind)
                client = LighthouseClient(
                    self._lighthouse_addr, connect_timeout=timedelta(seconds=5)
                )
                try:
                    client.heartbeat(
                        self._replica_id,
                        timeout=timedelta(seconds=5),
                        telemetry_payload=self._telemetry_payload_json(),
                    )
                finally:
                    client.close()
            except Exception:  # noqa: BLE001 — best effort
                pass

        threading.Thread(target=_push, daemon=True, name="tft_stall_push").start()

    def _trace_id(self) -> str:
        """Trace identity for the in-flight step: (replica, step, epoch)
        are globally agreed values, so spans from different replicas with
        equal step/epoch coordinates correlate on the merged timeline.
        Uses the physical-step label (see start_quorum) so a pipelined
        replica's spans carry the same step coordinate as the commit
        event they belong to."""
        return f"{self._replica_id}:{self._step_label}:{self._quorum_id}"

    def _delta_encoder(self):
        """Lazy per-manager DeltaEncoder (ISSUE 16). One encoder per
        Manager lifetime: its random incarnation is what lets the
        lighthouse tell a respawned replica from a delta-chain
        continuation, so it must NOT be recreated across steps."""
        enc = getattr(self, "_tdelta_encoder", None)
        if enc is None:
            from torchft_tpu.telemetry.fleetdelta import DeltaEncoder

            enc = DeltaEncoder()
            self._tdelta_encoder = enc
        return enc

    def _telemetry_report(self) -> Dict[str, Any]:
        """The nested per-replica report the delta encoder flattens:
        health scalars + counters digest + anatomy + mergeable log2
        histograms + time-series samples. Keys here ARE the wire
        vocabulary the lighthouse rebuilds /cluster.json fields from."""
        from torchft_tpu.telemetry.fleetdelta import collect_hists
        from torchft_tpu.telemetry.timeseries import build_series

        report: Dict[str, Any] = {
            "step": self._step,
            "epoch": self._quorum_id,
            "stuck": bool(self._watchdog.stalled),
            "slo_breach": bool(self._slo.breached()),
            "local_step_p50_s": float(telemetry.LEDGER.local_p50() or 0.0),
            "last_heal_ts": float(self._last_heal_ts),
            "summary": telemetry.summary(),
            "anatomy": telemetry.LEDGER.summary(),
            "hist": collect_hists(),
        }
        diagnosis = getattr(self, "_diagnosis", None)
        if diagnosis is not None and diagnosis.bundle_count:
            report["diag_bundles"] = diagnosis.bundle_count
            report["diag_last"] = diagnosis.last_bundle or ""
            report["diag_dir"] = diagnosis.directory or ""
        series = build_series(
            slo_breach=bool(self._slo.breached()),
            stuck=bool(self._watchdog.stalled),
            divergence=bool(self._divergence_latched),
        )
        if series:
            report["series"] = series
        return report

    def _telemetry_payload_delta(self) -> Optional[Dict[str, Any]]:
        """Delta-encoded piggyback (ISSUE 16): the report is flattened
        and only fields changed since the lighthouse's last ack ship, so
        steady-state bytes are O(changed), not O(report). Spans ride
        OUTSIDE the blob as the lowest-priority tier: when blob + spans
        would blow the 64KiB cap the spans are requeued for a lighter
        round instead of starving the latches inside the blob."""
        import time as _time

        from torchft_tpu.telemetry.fleetdelta import max_blob_bytes

        t0 = _time.perf_counter()
        try:
            enc = self._delta_encoder()
            blob = enc.encode(self._telemetry_report())
            payload: Dict[str, Any] = {"tdelta": blob}
            telemetry.TELEMETRY_BYTES.labels(channel="piggyback").inc(
                len(blob)
            )
            spans = telemetry.TRACER.drain_chrome_fragment()
            if spans:
                if len(blob) + len(spans) <= max_blob_bytes():
                    payload["spans"] = spans
                    telemetry.TELEMETRY_BYTES.labels(channel="spans").inc(
                        len(spans)
                    )
                else:
                    # tier 3 drops first — requeue, don't lose them
                    telemetry.TRACER.requeue_last_batch()
            return payload
        except Exception:  # noqa: BLE001 — observability must not fail quorum
            return None
        finally:
            # the telemetry plane meters itself: encode+drain cost is a
            # first-class anatomy phase, so an overhead regression shows
            # up in the same percentile tables as compute/wire
            telemetry.LEDGER.record(
                "telemetry", _time.perf_counter() - t0
            )

    def _telemetry_payload(self) -> Optional[Dict[str, Any]]:
        """Compact per-replica report piggybacked on the quorum RPC:
        counters digest + recent span batch + health scalars. The manager
        server forwards it to the lighthouse for /cluster.json and the
        merged /trace. Must never fail the quorum path. Kill switch:
        ``TORCHFT_TELEMETRY_PIGGYBACK=0``. Default wire format is the
        delta encoding (telemetry/fleetdelta.py); set
        ``TORCHFT_TELEMETRY_DELTA=0`` for the legacy full-JSON payload."""
        if os.environ.get("TORCHFT_TELEMETRY_PIGGYBACK", "1") == "0":
            return None
        from torchft_tpu.telemetry.fleetdelta import delta_enabled

        if delta_enabled():
            return self._telemetry_payload_delta()
        return self._telemetry_payload_json()

    def _telemetry_payload_json(self) -> Optional[Dict[str, Any]]:
        """The legacy full-JSON payload — the ``TORCHFT_TELEMETRY_DELTA=0``
        wire format, and ALSO the out-of-band stall push's format even in
        delta mode: the push runs on its own thread, and the delta
        encoder is thread-compatible (quorum-path-only) — touching it
        here would race the quorum thread's encode, and an out-of-order
        heartbeat would break the version chain into resync round-trips
        that drop time-series samples. The lighthouse ingest is
        format-blind, so a self-contained JSON row lands regardless of
        what the delta chain is doing."""
        import json as _json

        if os.environ.get("TORCHFT_TELEMETRY_PIGGYBACK", "1") == "0":
            return None
        try:
            # step-anatomy digest + the two detector scalars (ISSUE 8):
            # the lighthouse stores the digest verbatim (spliced into
            # /cluster.json like the summary) and serves the scalars to
            # the fleet straggler detector / dashboard SLO column
            anatomy = _json.dumps(
                telemetry.LEDGER.summary(),
                separators=(",", ":"),
                default=str,
            )
            if len(anatomy) > (1 << 16):
                # the lighthouse refuses (loudly) anything past its 64KiB
                # cap; sending the oversize anyway would only burn quorum
                # bandwidth — replace with a marker so /cluster.json
                # shows WHY the digest is missing from both ends. Warn
                # once per EPISODE (the flag resets when the digest
                # shrinks back under the cap): oversize is steady-state
                # while it lasts and this path runs at step rate, but a
                # later unrelated episode must not be silent
                if not getattr(self, "_anatomy_oversize_warned", False):
                    self._anatomy_oversize_warned = True
                    self._logger.warning(
                        "anatomy digest %d bytes exceeds the 64KiB "
                        "piggyback cap; sending an oversize marker "
                        "instead (warned once per episode)",
                        len(anatomy),
                    )
                anatomy = _json.dumps({"_oversized_bytes": len(anatomy)})
            else:
                self._anatomy_oversize_warned = False
            payload = {
                "summary": _json.dumps(
                    telemetry.summary(), separators=(",", ":"), default=str
                ),
                "anatomy": anatomy,
                "local_step_p50_s": float(
                    telemetry.LEDGER.local_p50() or 0.0
                ),
                "slo_breach": bool(self._slo.breached()),
                "step": self._step,
                # quorum epoch keys this report's time-series samples
                # alongside step — the same clock-sync-free coordinates
                # every other forensic surface orders by
                "epoch": self._quorum_id,
                "stuck": bool(self._watchdog.stalled),
                "last_heal_ts": float(self._last_heal_ts),
                "spans": telemetry.TRACER.drain_chrome_fragment(),
            }
            # diagnosis-bundle availability (ISSUE 12): counts + the
            # latest bundle name ride the same piggyback; the lighthouse
            # serves the fleet index at GET /diagnosis.json (getattr:
            # the payload builder must also work on partially-built
            # Managers — tests drive it standalone)
            diagnosis = getattr(self, "_diagnosis", None)
            if diagnosis is not None and diagnosis.bundle_count:
                payload["diag_bundles"] = diagnosis.bundle_count
                payload["diag_last"] = diagnosis.last_bundle or ""
                payload["diag_dir"] = diagnosis.directory or ""
            # per-step sample map for the lighthouse time-series store
            # (ISSUE 11): last step row's wall/local/phase seconds,
            # lathist quantiles and detector flags — telemetry/
            # timeseries.py owns the vocabulary, the lighthouse stays
            # schema-blind
            from torchft_tpu.telemetry.timeseries import build_series

            series = build_series(
                slo_breach=bool(self._slo.breached()),
                stuck=bool(self._watchdog.stalled),
                divergence=bool(self._divergence_latched),
            )
            if series:
                payload["series"] = series
            return payload
        except Exception:  # noqa: BLE001 — observability must not fail quorum
            return None

    def set_state_dict_fns(
        self, load_state_dict: Callable[[T], None], state_dict: Callable[[], T]
    ) -> None:
        self._load_state_dict = load_state_dict
        self._user_state_dict = state_dict

    def set_heal_warmup(self, fn: Callable[[Any], None]) -> None:
        """Register a warmup callback for the heal/compile overlap
        (docs/heal_plane.md): during a heal, ``fn(spec_tree)`` runs on a
        daemon thread as soon as the incoming state's header (dtypes +
        shapes) is known — while the stripes are still streaming — so jit
        compilation/warmup costs overlap the transfer instead of
        serializing after it. ``spec_tree`` mirrors the state dict with
        ``jax.ShapeDtypeStruct`` leaves. Best-effort: a failing warmup
        never fails the heal."""
        self._heal_warmup = fn

    def _heal_header_cb(self, header: bytes) -> None:
        """Transport header hook (runs on the quorum thread mid-recv):
        kick the registered warmup off-thread so recv keeps streaming."""
        fn = self._heal_warmup
        if fn is None:
            return

        def run() -> None:
            try:
                from torchft_tpu.checkpointing.serialization import (
                    spec_tree_from_header,
                )

                fn(spec_tree_from_header(header))
            except Exception:  # noqa: BLE001 — warmup is best-effort
                self._logger.exception("heal warmup failed")

        threading.Thread(
            target=run, daemon=True, name="tft_heal_warmup"
        ).start()

    def _record_commit_trail(self) -> None:
        """Record the committed state's per-leaf digests at the current
        step (main thread, step boundary — the state HERE is exactly the
        state a heal at this step would serve). Idempotent per step; the
        trail evicts past its horizon."""
        assert self._heal_trail is not None
        if self._user_state_dict is None:
            return
        try:
            from torchft_tpu.checkpointing.serialization import flatten_state

            _header, buffers = flatten_state(self._manager_state_dict())
            self._heal_trail.record(self._step, buffers)
        except Exception:  # noqa: BLE001 — the trail must never fail a step
            self._logger.exception("commit-trail record failed")

    def _heal_own_digest(self) -> Optional[tuple]:
        """This replica's flattened state + tree digest at its last
        committed step — the differential heal request's credentials.
        None when differential heal can't apply (no state callbacks, step
        0, feature off)."""
        if (
            self._heal_trail is None
            or self._user_state_dict is None
            or self._step <= 0
        ):
            return None
        try:
            from torchft_tpu.checkpointing import delta as _delta
            from torchft_tpu.checkpointing.serialization import flatten_state

            _header, buffers = flatten_state(self._manager_state_dict())
            digests = _delta.leaf_digests(buffers)
            return buffers, _delta.tree_digest(digests)
        except Exception:  # noqa: BLE001 — degrade to a full heal
            self._logger.exception("own-state digest failed")
            return None

    def _heal_sources(self, quorum) -> List[tuple]:
        """Resolve the striped-heal source list: the lighthouse-named
        primary first, then the rest of the max-step cohort, each mapped
        to its checkpoint transport URL via ``mgr.checkpoint_metadata``.
        A peer that fails the metadata RPC is dropped (it may be mid-death
        — the stripe fetch would re-stripe around it anyway, this is just
        cheaper). Returns ``[(manager_addr, transport_metadata), ...]``."""
        from torchft_tpu.checkpointing.stripes import heal_sources_limit

        addrs = [quorum.recover_src_manager_address]
        for a in quorum.recover_src_addresses:
            if a and a not in addrs:
                addrs.append(a)
        addrs = addrs[: heal_sources_limit()]
        out: List[tuple] = []
        lock = threading.Lock()

        def resolve(addr: str) -> None:
            try:
                client = ManagerClient(
                    addr, connect_timeout=self._connect_timeout
                )
                try:
                    meta = client._checkpoint_metadata(
                        self._rank, timeout=self._timeout
                    )
                finally:
                    client.close()
                with lock:
                    out.append((addr, meta))
            except Exception as e:  # noqa: BLE001 — drop the source
                self._logger.warn(
                    f"heal source {addr} metadata fetch failed: {e}"
                )

        if len(addrs) == 1:
            resolve(addrs[0])
        else:
            threads = [
                threading.Thread(
                    target=resolve, args=(a,), name="tft_heal_meta"
                )
                for a in addrs
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
        # keep the lighthouse-named primary first (deterministic plan)
        out.sort(key=lambda t: addrs.index(t[0]))
        return out

    def shutdown(self, wait: bool = True) -> None:
        """Shut down the manager, checkpoint transport and data plane."""
        self._shutting_down = True
        self._watchdog.stop()
        if self._diagnosis is not None:
            self._diagnosis.remove()
        if self._fleet_monitor is not None:
            self._fleet_monitor.stop()
        if self._regression_monitor is not None:
            self._regression_monitor.stop()
        self._cp_stop.set()
        if self._cp_thread is not None:
            self._cp_thread.join(timeout=5.0)
            self._cp_thread = None
        # unblock any quorum thread parked on the speculation fence (its
        # heal serve will fail downstream, which is fine at shutdown)
        with self._spec_cond:
            self._pending_commit = None
            self._spec_cond.notify_all()
        self._checkpoint_transport.shutdown(wait=wait)
        if self._manager is not None:
            self._manager.shutdown()
        self._executor.shutdown(wait=wait)
        if self._commit_executor is not None:
            self._commit_executor.shutdown(wait=wait)
        self._collectives.shutdown()
        if self._commit_client is not None:
            self._commit_client.close()
        self._client.close()
        self._store.close()

    # ------------------------------------------------------------------
    # quorum
    # ------------------------------------------------------------------

    def start_quorum(
        self,
        allow_heal: bool = True,
        shrink_only: bool = False,
        timeout: Optional[timedelta] = None,
    ) -> None:
        """Compute a new quorum (async by default) and ready the manager for
        a new step. Call before the forward pass; the RPC overlaps compute.

        All replicas must pass the same ``allow_heal``. With
        ``shrink_only`` the quorum can only lose members (planned
        downscale)."""
        self._errored = None
        self._healing = False
        self._group_healing = False
        self._step_epochs = set()
        self._step_n = None
        # Step coordinate for this physical step's trail events and trace
        # ids. With a pipelined vote still in flight, self._step lags one
        # behind the step now starting — label optimistically with the
        # in-flight count so quorum_start/spans/commit of ONE physical
        # step join on one value (exact in sync mode and on every
        # committed pipelined step; a veto makes that step's label one
        # ahead, flagged by its commit_rollback event).
        self._step_label = self._step + (
            1 if self._pending_commit is not None else 0
        )
        telemetry.TRACER.set_context(
            replica_id=self._replica_id,
            step=self._step_label,
            quorum_epoch=self._quorum_id,
        )
        telemetry.BLACKBOX.set_context(
            replica_id=self._replica_id,
            step=self._step_label,
            quorum_epoch=self._quorum_id,
        )
        self._watchdog.arm(self._step_label)
        telemetry.emit(
            "quorum_start",
            step=self._step_label,
            allow_heal=allow_heal,
            shrink_only=shrink_only,
        )
        if self._heal_trail is not None:
            # differential heal: digest the committed state at this step
            # boundary (with a pipelined vote outstanding the user
            # callback serves the rollback snapshot, which IS the
            # committed state at current_step — same invariant the heal
            # serve path relies on)
            self._record_commit_trail()

        # Replace-under-lock, wait-outside-lock. Replacement only happens
        # after observing a DONE future under the lock, so a death-watch
        # submission can never be silently overwritten (its exception
        # unobserved, a duplicate lighthouse RPC from this replica) — but
        # the waiting itself must not hold _qf_lock: the previous future
        # can be an in-flight death-watch re-quorum long-poll, and an
        # earlier version that held the lock across .result() blocked
        # _on_peer_death's monitor thread (stalling dead-peer eviction
        # reports) for up to quorum_timeout [found by the analysis gate:
        # blocking-under-lock].
        while True:
            with self._qf_lock:
                prev = self._quorum_future
                if prev is None or prev.done():
                    if prev is not None:
                        try:
                            exc = prev.exception()  # done ⇒ returns now
                        except Exception as e:  # noqa: BLE001 — cancelled
                            exc = e
                        if exc is not None:
                            # the failure already surfaced to the caller
                            # through wait_quorum/allreduce/should_commit
                            # on the step that scheduled it; calling
                            # start_quorum again IS the retry — start
                            # fresh instead of re-raising history forever
                            self._logger.warn(
                                f"previous quorum attempt failed ({exc}); "
                                "retrying"
                            )
                    self._last_quorum_args = (allow_heal, shrink_only, timeout)
                    self._quorum_future = self._executor.submit(
                        self._async_quorum,
                        allow_heal=allow_heal,
                        shrink_only=shrink_only,
                        quorum_timeout=timeout or self._quorum_timeout,
                    )
                    break
            # an in-flight previous attempt: wait it out with the lock
            # RELEASED, then re-check — a death-watch submission landing
            # in between is observed (not clobbered) by the next pass
            try:
                prev.result()
            except Exception:  # noqa: BLE001 — consumed under the lock above
                pass
        if not self._use_async_quorum:
            self.wait_quorum()
            if self._healing:
                # eagerly apply the recovered state so the forward pass runs
                # from a good state; no zero-grad dance needed
                self._apply_pending_state_dict()
                self._healing = False
            # sync quorum: every rank healed before the forward pass, so
            # the whole group participates with real gradients
            self._group_healing = False

    def wait_quorum(self) -> None:
        """Block until the in-flight quorum completes; the data plane is
        configured for the new membership after this returns."""
        assert (
            self._quorum_future is not None
        ), "must call start_quorum before wait_quorum"
        if self._quorum_future.done():
            self._quorum_future.result()
            return
        # step-anatomy: the time the MAIN thread actually blocked on the
        # quorum (the RPC itself overlaps compute in async mode — only
        # the tail the trainer had to wait out is step cost). Peer skew
        # lands here too: the lighthouse's long-poll waits for the whole
        # fleet, so a straggler stretches every OTHER group's quorum_wait
        # — which is exactly why the local-time signal excludes it.
        import time as _time

        t0 = _time.perf_counter()
        try:
            self._quorum_future.result()
        finally:
            telemetry.LEDGER.record(
                "quorum_wait", _time.perf_counter() - t0
            )

    def _async_quorum(
        self, allow_heal: bool, shrink_only: bool, quorum_timeout: timedelta
    ) -> None:
        import time as _time

        t_quorum = _time.perf_counter()
        with telemetry.TRACER.span(
            "quorum", trace_id=self._trace_id(), rank=self._rank
        ) as q_span:
            try:
                quorum = self._client._quorum(
                    rank=self._rank,
                    step=self._step,
                    checkpoint_metadata=self._checkpoint_transport.metadata(),
                    shrink_only=shrink_only,
                    timeout=quorum_timeout,
                    # latched data-plane errors request a flush: quorum_id
                    # bumps so all groups (healthy ones too) re-rendezvous
                    commit_failures=self._commit_failures,
                    # data-plane transport label for the lighthouse
                    # dashboard — lets an operator spot a group that fell
                    # back to a slower plane (e.g. CMA broken-latch
                    # converging everyone to TCP)
                    plane=(
                        self._collectives.plane_info()
                        if hasattr(self._collectives, "plane_info")
                        else type(self._collectives).__name__
                    ),
                    # piggybacked telemetry: counters digest + span batch
                    # for the lighthouse's /cluster.json and merged /trace
                    telemetry_payload=self._telemetry_payload(),
                )
            except BaseException:
                # the drained span batch never reached the lighthouse —
                # requeue it so the outage window keeps its spans in the
                # merged trace (the incident is exactly what /trace is for)
                telemetry.TRACER.requeue_last_batch()
                raise
            q_span.set(quorum_id=quorum.quorum_id, heal=quorum.heal)

        # telemetry-delta ack loop (ISSUE 16): the lighthouse's
        # last-applied version rides the quorum reply; feeding it to the
        # encoder is what collapses the NEXT piggyback to only-changed
        # fields (and triggers a full resync when the lighthouse lost
        # our chain — restart, eviction, version skew)
        if quorum.telemetry_ack:
            enc = getattr(self, "_tdelta_encoder", None)
            if enc is not None:
                try:
                    enc.on_ack(quorum.telemetry_ack)
                except Exception:  # noqa: BLE001 — never fail quorum
                    pass

        # Async quorum overlaps the forward pass, so a healing replica can't
        # participate this step (its state is mid-flight) — take the max-step
        # cohort. Sync quorum heals eagerly, so everyone participates.
        self._participating_rank, self._participating_world_size = (
            (quorum.max_rank, quorum.max_world_size)
            if self._use_async_quorum or not allow_heal
            else (quorum.replica_rank, quorum.replica_world_size)
        )
        # plane-consistent zero-contribution gate: if ANY local rank of
        # this group heals, every rank contributes zeros this step (see
        # coord.cc compute_quorum_results group_heal)
        self._group_healing = allow_heal and quorum.group_heal

        if self._world_size_mode == WorldSizeMode.FIXED_WITH_SPARES:
            # demote groups beyond min_replica_size to zero-contributing spares
            self._participating_world_size = min(
                self._participating_world_size, self._min_replica_size
            )
            if (
                self._participating_rank is not None
                and self._participating_rank >= self._min_replica_size
            ):
                self._participating_rank = None

        with self._evict_lock:
            prev_participants = self._participant_ids
            self._participant_ids = quorum.participant_ids
            self._evicted.clear()

        telemetry.PARTICIPANTS.set(self._participating_world_size)
        # prev_participants is [] before the first quorum: joining is not
        # membership CHURN, so don't count it (a cohort restart would
        # otherwise record N phantom changes)
        if prev_participants and set(quorum.participant_ids) != set(
            prev_participants
        ):
            telemetry.MEMBERSHIP_CHANGES.inc()
        telemetry.emit(
            "quorum_ready",
            quorum_id=quorum.quorum_id,
            step=self._step_label,
            participants=list(quorum.participant_ids),
            num_participants=self._participating_world_size,
            heal=quorum.heal,
            reconfigure=quorum.quorum_id != self._quorum_id,
            duration_s=round(_time.perf_counter() - t_quorum, 4),
        )

        if quorum.quorum_id != self._quorum_id:
            # epoch-scoped rendezvous namespace on the primary's store
            store_prefixed_addr = (
                f"{quorum.store_address}/torchft/{quorum.quorum_id}/{self._rank}"
            )
            self._logger.info(
                f"reconfiguring for quorum_id={quorum.quorum_id} store={store_prefixed_addr}"
            )
            self._collectives.configure(
                store_prefixed_addr, quorum.replica_rank, quorum.replica_world_size
            )
            if hasattr(self._collectives, "plane_generation"):
                # (gen, ids) snapshot for death-watch callbacks: published
                # AFTER configure, so a callback from the new ring that
                # races this store is dropped as stale — safe, the lease
                # still expires passively
                self._death_watch_snapshot = (
                    self._collectives.plane_generation(),
                    list(quorum.participant_ids),
                )
            self._quorum_id = quorum.quorum_id
            telemetry.TRACER.set_context(quorum_epoch=quorum.quorum_id)
            telemetry.BLACKBOX.set_context(quorum_epoch=quorum.quorum_id)
            telemetry.QUORUM_RECONFIGURES.inc()
            self.step_timer.mark_quorum()
            # fresh epoch: the flush request (if any) has been honored
            self._commit_failures = 0
            if self._rank == 0:
                self._sweep_stale_epochs(quorum.quorum_id)

        if allow_heal:
            from torchft_tpu.checkpointing.stripes import heal_sources_limit

            # Striped multi-source heal (docs/heal_plane.md): when ANYONE
            # heals this round, every max-step cohort member a healer may
            # actually contact stages a checkpoint — not just the
            # round-robin-assigned sources — so the healer can pull a
            # stripe from each of them in parallel. Members past the
            # healer-side source cap never get contacted for stripes, so
            # they skip the flatten+stage (a 32-group fleet must not pay
            # 31 full device-to-host copies for one rejoiner); a member
            # that can't FIND itself in the cohort list stages
            # conservatively (an address-format drift must degrade to
            # wasted staging, never to an unserved healer).
            _src_limit = heal_sources_limit()
            stage_for_stripes = (
                quorum.heal_pending
                and not quorum.heal
                and quorum.max_rank is not None
                and _src_limit > 1
                and (
                    self._manager_addr in quorum.recover_src_addresses[:_src_limit]
                    or self._manager_addr not in quorum.recover_src_addresses
                )
            )
            if quorum.recover_dst_ranks or quorum.heal or stage_for_stripes:
                # Pipelined commit: a speculative optimizer update may be
                # outstanding on the main thread. Serving a checkpoint now
                # would ship UNCOMMITTED state (and a veto would make the
                # healer's copy wrong); healing onto a speculative state
                # would race the rollback. Wait for the main thread to
                # resolve the vote before any heal traffic.
                self._await_speculation_settled()
            if quorum.recover_dst_ranks or stage_for_stripes:
                self._logger.info(
                    f"peers need recovery from us {quorum.recover_dst_ranks}"
                    + (" (stripe source)" if stage_for_stripes else "")
                )
                with telemetry.TRACER.span(
                    "heal_send",
                    trace_id=self._trace_id(),
                    dst_ranks=list(quorum.recover_dst_ranks),
                    step=quorum.max_step,
                ):
                    self._checkpoint_transport.send_checkpoint(
                        dst_ranks=quorum.recover_dst_ranks,
                        step=quorum.max_step,
                        state_dict=self._manager_state_dict(),
                        timeout=self._timeout,
                    )
                telemetry.HEALS_TOTAL.labels(role="send").inc(
                    len(quorum.recover_dst_ranks)
                )
            if quorum.heal:
                self._healing = True
                t_heal = _time.perf_counter()
                # rejoin-to-commit SLO clock starts at heal begin; the
                # first committed _finish_commit on the main thread
                # observes and clears it
                self._rejoin_t0 = t_heal
                telemetry.emit(
                    "heal_begin",
                    step=quorum.max_step,
                    src=quorum.recover_src_manager_address,
                )
                self._logger.info(
                    f"healing: fetching checkpoint metadata from "
                    f"{quorum.recover_src_manager_address} at step {quorum.max_step}"
                )
                # protocol invariant, NOT a retryable transfer failure —
                # keep it outside the retry handler below so a lighthouse
                # that heals us without naming a source crashes loudly
                # instead of looping on a doomed heal forever
                assert (
                    quorum.recover_src_rank is not None
                ), "must have a recover rank when healing"
                try:
                    sources = self._heal_sources(quorum)
                    if not sources:
                        raise ConnectionError(
                            "no heal source answered the checkpoint-"
                            "metadata RPC"
                        )
                    multi = getattr(
                        self._checkpoint_transport,
                        "recv_checkpoint_multi",
                        None,
                    )
                    # the user state dict is only applied from the main
                    # thread; stage it here
                    with telemetry.TRACER.span(
                        "heal_recv",
                        trace_id=self._trace_id(),
                        src=quorum.recover_src_manager_address,
                        sources=len(sources),
                        step=quorum.max_step,
                    ):
                        if multi is not None:
                            own = self._heal_own_digest()
                            self._pending_state_dict = cast(
                                Dict[str, object],
                                multi(
                                    [m for _, m in sources],
                                    step=quorum.max_step,
                                    timeout=self._timeout,
                                    since_step=(
                                        self._step if own is not None else None
                                    ),
                                    own=own,
                                    header_cb=self._heal_header_cb,
                                ),
                            )
                        else:
                            self._pending_state_dict = cast(
                                Dict[str, object],
                                self._checkpoint_transport.recv_checkpoint(
                                    src_rank=quorum.recover_src_rank,
                                    metadata=sources[0][1],
                                    step=quorum.max_step,
                                    timeout=self._timeout,
                                ),
                            )
                except Exception as e:  # noqa: BLE001 — heal must be retryable
                    # A torn/failed checkpoint transfer (the serving peer
                    # died mid-stream — fault-injection scenario
                    # ckpt_serve_death, previously a trainer-killing
                    # struct.error through wait_quorum) must not take this
                    # worker down: the quorum/plane are fine, only the
                    # state fetch failed. Stay un-healed, latch the error
                    # so the step aborts at the commit barrier, and let
                    # the next start_quorum re-request the heal (we are
                    # still behind max_step, so the lighthouse re-selects
                    # us for recovery).
                    self._healing = False
                    self._pending_state_dict = None
                    self._logger.exception(
                        f"heal transfer failed; retrying next quorum: {e}"
                    )
                    telemetry.emit(
                        "heal_failed", step=quorum.max_step, error=str(e)
                    )
                    self.report_error(e)
                    return
                self.load_state_dict(
                    cast(Dict[str, int], self._pending_state_dict["torchft"])
                )
                # the received state dict is authoritative: with pipelined
                # commit the serving side may have resolved a speculative
                # vote between REPORTING its step (in the quorum RPC) and
                # SERVING the checkpoint, so its state can be one step
                # ahead of quorum.max_step — never rewind below the state
                # the bytes actually encode
                self._step = max(self._step, quorum.max_step)
                heal_s = _time.perf_counter() - t_heal
                nbytes = getattr(
                    self._checkpoint_transport, "last_recv_bytes", 0
                )
                if not isinstance(nbytes, int):  # un-instrumented transport
                    nbytes = 0
                telemetry.HEALS_TOTAL.labels(role="recv").inc()
                telemetry.HEAL_DURATION.observe(heal_s)
                self._last_heal_ts = _time.time()
                self.step_timer.mark_heal()
                # per-source stripe throughput + stage split from the
                # multi-source transport (empty dict on legacy paths) —
                # the recovery bench and the trail both read this, so a
                # rejoin regression names its stage instead of one
                # opaque duration
                heal_stats = getattr(
                    self._checkpoint_transport, "last_heal_stats", None
                )
                telemetry.emit(
                    "heal_end",
                    step=quorum.max_step,
                    bytes=nbytes,
                    duration_s=round(heal_s, 4),
                    **(
                        {"heal_stats": heal_stats}
                        if isinstance(heal_stats, dict) and heal_stats
                        else {}
                    ),
                )

    def _sweep_stale_epochs(self, current_qid: int) -> None:
        """GC rendezvous keys from dead epochs (round-2 verdict weak #5).

        Every quorum epoch writes ``coll/addr/*`` keys under
        ``torchft/{quorum_id}/...`` on the primary's store and nothing else
        deletes them, so long jobs with flush re-quorums grow the store
        without bound. Each group's rank 0 sweeps its *own* store on every
        reconfigure, keeping one epoch of slack for groups still dialing
        the previous epoch. Best-effort: a failed sweep never fails the
        quorum."""
        try:
            for key in self._store.keys("torchft/"):
                if isinstance(key, bytes):
                    key = key.decode()
                parts = key.split("/")
                if len(parts) < 2 or parts[0] != "torchft":
                    continue
                try:
                    qid = int(parts[1])
                except ValueError:
                    continue
                if qid < current_qid - 1:
                    self._store.delete(key)
        except Exception as ex:  # noqa: BLE001 — GC must never fail a step
            self._logger.warn(f"epoch GC failed: {ex}")

    def _apply_pending_state_dict(self) -> None:
        assert self._healing, "must be in healing state"
        assert self._quorum_future is not None, "missing quorum future"
        self._quorum_future.result()
        assert self._pending_state_dict is not None, "checkpoint was not staged"
        assert self._load_state_dict is not None, "user load_state_dict not set"
        self._logger.info("applying pending state dict")
        import time as _time

        t0 = _time.perf_counter()
        self._load_state_dict(cast(T, self._pending_state_dict["user"]))
        dur = _time.perf_counter() - t0
        # step-anatomy `heal` phase: the main-thread share of a heal (the
        # staged-state apply; the transfer itself rides the quorum thread
        # and shows as quorum_wait — docs/observability.md "Step anatomy").
        # The same duration feeds the heal-stage view as `device_put` so
        # the rejoin ledger (meta/recv/decode/device_put) is complete.
        telemetry.LEDGER.record("heal", dur)
        telemetry.LEDGER.record_heal_stage("device_put", dur)
        self._pending_state_dict = None

    # ------------------------------------------------------------------
    # collectives
    # ------------------------------------------------------------------

    def device_data_plane(self) -> bool:
        """True when the configured collectives move ``jax.Array``s directly
        (ICI path, :class:`~torchft_tpu.collectives_device.CollectivesDevice`)
        — gradient averaging then skips the host round trip entirely."""
        return bool(getattr(self._collectives, "device_arrays", False))

    def wire_codec(self) -> str:
        """Name of the codec the configured data plane ships large f32
        allreduces with (``"f32"`` = exact). ``ManagedOptimizer`` keys its
        automatic error-feedback enablement off this — a lossy wire
        without residual compensation drifts (docs/wire_plane.md)."""
        fn = getattr(self._collectives, "wire_codec", None)
        return fn() if callable(fn) else "f32"

    def allreduce(self, tensor: np.ndarray) -> Future:
        """Fault-tolerant cross-replica-group allreduce of one buffer,
        scaled by ``1 / num_participants()``; see :meth:`allreduce_many`."""
        return self.allreduce_many([tensor]).then(lambda f: f.value()[0])

    def allreduce_many(self, tensors: List[Any]) -> Future:
        """Fault-tolerant cross-replica-group allreduce of a list of
        buffers (numpy, averaged in place — or ``jax.Array``s when the data
        plane is device-path, averaged on device), scaled by
        ``1 / num_participants()``.

        On error the future still completes (with the possibly-corrupt
        tensors) and the error is latched — subsequent calls no-op and the
        step fails at the commit barrier. Healing/spare replicas contribute
        zeros so the participants' average is unperturbed."""
        if not tensors or self.errored():
            return Future.completed(tensors)

        if self._pending_commit is not None:
            # a collective issued while the previous step's vote is still
            # in flight belongs to an UNRESOLVED lineage: on a veto its
            # inputs (gradients of speculative params) are garbage, and
            # blocking inside wait_quorum here could deadlock against the
            # quorum thread's speculation fence. The blessed flows
            # (FTTrainer/ManagedOptimizer/bench) all resolve first.
            raise RuntimeError(
                "pipelined commit: resolve_pending_commit() before issuing "
                "collectives for the next step"
            )
        self.wait_quorum()
        if self.errored():
            # the quorum thread may have latched a failure DURING the wait
            # (e.g. a failed heal transfer): the step is already doomed, and
            # issuing the collective anyway would park this rank in a ring
            # whose peers aborted — a full op-timeout of dead wait before
            # the inevitable abort (observed in the stripe_heal_peer_death
            # bring-up: +30s per step on the healer)
            return Future.completed(tensors)
        # record which plane epoch this op rides: a death-watch re-quorum
        # can land MID-step, and a step whose ops span two epochs mixes
        # normalization denominators — should_commit vetoes those
        self._step_epochs.add(self._quorum_id)
        # participant count captured at ISSUE time: an op can never span
        # plane epochs (configure tears down its executor, cancelling or
        # failing it), so the membership the op actually reduced over is
        # the one in force now. Reading it at COMPLETION time instead
        # would (a) mis-scale a finished op if a death-watch re-quorum
        # lands before its callback runs, and (b) deadlock: the callback
        # runs on the collectives op thread, and blocking there on the
        # re-quorum future while its configure() waits to join that very
        # thread is a cycle.
        n_at_issue = self._participating_world_size
        # ... and the COMMIT accounting must use the same snapshot: a
        # death-watch re-quorum landing between the step's last op and
        # should_commit would otherwise count the new cohort's size for
        # batches averaged over the old one (or veto on the new cohort's
        # min_replicas when the reduction was over enough replicas)
        self._step_n = n_at_issue

        # branch on the *configured* data plane, not the input type: the
        # device backend converts numpy inputs to jax.Arrays, so its results
        # must be normalized on device regardless of what the caller passed
        device = self.device_data_plane()
        if not self.is_participating():
            if device:
                import jax.numpy as jnp

                tensors = [jnp.zeros_like(t) for t in tensors]
            else:
                for t in tensors:
                    t[...] = 0  # in place: host buffers are bucket views

        # snapshot this epoch's rank→replica map: an in-flight op can fail
        # AFTER the next quorum has renumbered ranks, and a PeerGoneError
        # mapped through the new list would accuse an innocent replica
        with self._evict_lock:
            ids_snapshot = list(self._participant_ids)

        try:
            work = self._collectives.allreduce(tensors, ReduceOp.SUM)

            def normalize(fut: Future) -> List[Any]:
                try:
                    reduced = fut.value()  # surface exceptions
                except BaseException as e:  # noqa: BLE001 — annotate + rethrow
                    e._tft_participants = ids_snapshot
                    raise
                n = n_at_issue
                if n > 1:
                    if device:
                        reduced = _divide_tree(reduced, n)
                    else:
                        for t in reduced:
                            np.divide(t, n, out=t)
                if self._divergence_sentinel:
                    self._digest_reduced(reduced)
                return reduced

            fut = self.wrap_future(work.get_future().then(normalize), tensors)
            # close the issue-time race: if a death-watch reconfigure slid
            # in between the epoch read above and the submission, the two
            # reads differ and the veto catches the step
            self._step_epochs.add(self._quorum_id)
            return fut
        except Exception as e:  # noqa: BLE001 — latch and continue
            self._logger.exception(f"exception in allreduce, skipping remaining: {e}")
            self.report_error(e)
            return Future.completed(tensors)

    def _digest_reduced(self, reduced: List[Any]) -> None:
        """Divergence sentinel: fold one op's post-reduce outputs into
        this step's ordered digest list (op-callback thread; ops resolve
        in issue order, so the list is deterministic across groups —
        which is what makes equality the invariant). blake2b via the
        differential-heal digest helpers; failures degrade to "no digest
        this step", never to a failed op."""
        try:
            from torchft_tpu.checkpointing import delta as _delta

            bufs = [np.asarray(t) for t in reduced]
            self._step_digests.append(
                _delta.tree_digest(_delta.leaf_digests(bufs))
            )
        except Exception:  # noqa: BLE001 — sentinel must not fail the op
            self._logger.exception("divergence digest failed")

    def _note_divergence(self, step: int) -> None:
        """The should_commit reply carried the lighthouse's divergence
        latch: record it once per process (the lighthouse latch never
        clears, so every later vote re-reports it)."""
        if self._divergence_latched:
            return
        self._divergence_latched = True
        telemetry.DIVERGENCE_TOTAL.inc()
        telemetry.emit(
            "divergence_detected", step=step, fence=self._divergence_fence
        )
        self._logger.warn(
            f"divergence sentinel latched at step {step}: post-reduce "
            "state digests disagreed across the cohort"
            + (" (fence vetoed the commit)" if self._divergence_fence else "")
        )

    def report_error(self, e: Exception) -> None:
        """Latch an error: the current step will not commit and the data
        plane reconfigures on the next quorum. If the error names a dead
        peer (:class:`~torchft_tpu.collectives.PeerGoneError`), its
        replica is reported to the lighthouse for immediate eviction so
        the re-quorum doesn't wait out the heartbeat lease."""
        self._errored = e
        self._errored_epoch = self._quorum_id
        self._maybe_evict(e)

    def _on_peer_death(self, ring_rank: int, plane_gen: Optional[int] = None) -> None:
        """Death-watch callback (runs on the collectives monitor thread):
        a peer's socket hit EOF/error mid-epoch. Report the eviction NOW
        (liveness-probe-guarded at the lighthouse, so a false positive is
        harmless) and, if no quorum RPC is in flight, start one — by the
        time the trainer finishes the doomed step, the shrunken quorum is
        usually already delivered and the plane reconfigured, so the
        survivor pays ~one step instead of detection+quorum+reconfigure
        serialized after it.

        ``plane_gen`` tags the ring the rank belongs to: a late POLLHUP
        delivered while ``_async_quorum`` replaces membership would
        otherwise map an OLD ring rank through the NEW participant list
        and accuse a live replica (burning a lighthouse liveness probe
        and delaying the real re-quorum)."""
        from torchft_tpu.collectives import PeerGoneError

        if self._shutting_down:
            return
        snap = self._death_watch_snapshot
        if plane_gen is not None and snap is not None:
            snap_gen, snap_ids = snap
            if plane_gen != snap_gen:
                self._logger.info(
                    f"dropping stale death-watch callback for ring rank "
                    f"{ring_rank} (plane gen {plane_gen} != armed {snap_gen})"
                )
                return
        else:
            snap_ids = None
        err = PeerGoneError(
            ring_rank, f"death watch: peer {ring_rank} socket closed"
        )
        if snap_ids is not None:
            # map the ring rank through the SNAPSHOT for this generation,
            # never through whatever _participant_ids holds right now
            err._tft_participants = list(snap_ids)
        self._maybe_evict(err)
        with self._qf_lock:
            if self._shutting_down:
                return
            fut = self._quorum_future
            if fut is None or not fut.done():
                # a quorum RPC is already in flight; it observes the
                # eviction when the lighthouse re-forms the quorum
                return
            # Only pre-quorum when the SURVIVING membership can form a
            # quorum without waiting for a restart: otherwise the early
            # long-poll parks the trainer's wait_quorum on a quorum that
            # cannot form until the victim respawns — strictly worse than
            # the old fail-fast-then-retry path.
            with self._evict_lock:
                alive = len(
                    [p for p in self._participant_ids if p not in self._evicted]
                )
            if alive < max(1, self._min_replica_size):
                return
            _, shrink_only, timeout = self._last_quorum_args
            self._logger.info(
                f"death watch: peer {ring_rank} gone; starting early re-quorum"
            )
            # allow_heal=False: this quorum exists ONLY to shrink
            # membership and rebuild the plane under the doomed step.
            # Serving a heal here would read user state on a thread the
            # trainer doesn't synchronize with (it may be mid-optimizer-
            # update after a commit) — rejoiners heal one step later on
            # the regular start_quorum cadence, where checkpoint staging
            # is trainer-synchronized.
            self._quorum_future = self._executor.submit(
                self._async_quorum,
                allow_heal=False,
                shrink_only=shrink_only,
                quorum_timeout=timeout or self._quorum_timeout,
            )

    def _maybe_evict(self, e: BaseException) -> None:
        """Fire-and-forget lh.evict for a PeerGoneError's peer. Runs on a
        daemon thread: the report is an optimization (the lease still
        expires passively) and must never block or fail the training
        thread."""
        peer: Optional[int] = None
        participants = None
        seen = 0
        cause: Optional[BaseException] = e
        while cause is not None and seen < 8:  # unwrap chained causes
            if participants is None:
                participants = getattr(cause, "_tft_participants", None)
            peer = getattr(cause, "peer_rank", None)
            if peer is not None:
                break
            cause = cause.__cause__ or cause.__context__
            seen += 1
        with self._evict_lock:
            if participants is None:
                participants = list(self._participant_ids)
            if peer is None or not (0 <= peer < len(participants)):
                return
            victim = participants[peer]
            if victim in self._evicted:
                # already reported this epoch — the check-and-add must be
                # one atomic step: report_error (main/op-callback threads)
                # and the death watch race into here for the same victim
                return
            self._evicted.add(victim)
        # the trail's detection record lives HERE, not in the death-watch
        # callback: a dead peer can also surface as a PeerGoneError from a
        # failed collective/p2p op (report_error path) without the poll
        # thread ever firing — both roads converge on this dedup point
        telemetry.PEER_DEATHS.inc()
        telemetry.emit(
            "peer_death", ring_rank=peer, replica=victim, step=self._step
        )

        def _report() -> None:
            # Fresh client: self._client serializes calls on one socket, so
            # the report would otherwise park behind an in-flight long-poll
            # quorum call — the exact wait eviction exists to skip.
            try:
                client = ManagerClient(
                    self._manager_addr, connect_timeout=timedelta(seconds=5)
                )
                try:
                    evicted = client.evict(victim, timeout=timedelta(seconds=5))
                finally:
                    client.close()
                telemetry.EVICTIONS_REPORTED.labels(
                    result="evicted" if evicted else "rejected"
                ).inc()
                telemetry.emit("eviction", victim=victim, evicted=evicted)
                self._logger.info(
                    f"reported dead peer {victim}: evicted={evicted}"
                )
            except Exception as ex:  # noqa: BLE001 — best effort
                telemetry.EVICTIONS_REPORTED.labels(result="failed").inc()
                self._logger.warn(f"evict report for {victim} failed: {ex}")

        threading.Thread(target=_report, daemon=True, name="tft_evict").start()

    def errored(self) -> Optional[Exception]:
        return self._errored

    def wrap_future(
        self, fut: Future, default: Any, timeout: Optional[timedelta] = None
    ) -> Future:
        """Deadline + error-swallowing wrapper: failures complete the future
        with ``default`` and latch the error on the manager
        (manager.py:327-364)."""
        fut = future_timeout(fut, timeout or self._timeout)

        def callback(f: Future) -> Any:
            try:
                return f.value()
            except Exception as e:  # noqa: BLE001
                self._logger.exception(f"exception in future, skipping remaining: {e}")
                self.report_error(e)
                return default

        out = fut.then(callback)
        self._pending_work.append(out)
        return out

    # ------------------------------------------------------------------
    # commit
    # ------------------------------------------------------------------

    def commit_pipeline_enabled(self) -> bool:
        """Whether this manager was opted into pipelined commit
        (``commit_pipeline=True`` / ``TORCHFT_COMMIT_PIPELINE=1``)."""
        return self._commit_pipeline

    def pending_commit(self) -> Optional[Future]:
        """The in-flight pipelined vote's future, or None. Read-only peek;
        use :meth:`resolve_pending_commit` to consume it."""
        rec = self._pending_commit
        return rec.future if rec is not None else None

    def speculation_allowed(self) -> bool:
        """Whether the trainer may apply this step's optimizer update
        speculatively and vote through :meth:`should_commit_async`.

        False whenever the step is already doomed (error latched, mixed
        plane epochs, too few replicas) — speculating on a known veto just
        buys a rollback — and whenever state callbacks are in play this
        step (healing replicas NEVER speculate: the heal lands at the
        commit barrier and must not race a speculative apply)."""
        if not self._commit_pipeline or self._quorum_future is None:
            return False
        if self._pending_commit is not None:
            # at most one speculative step outstanding
            return False
        self.wait_quorum()
        if self._healing or self._group_healing:
            return False
        if self._errored is not None or len(self._step_epochs) > 1:
            return False
        n = (
            self._step_n
            if self._step_n is not None
            else self._participating_world_size
        )
        return n >= self._min_replica_size

    def _await_speculation_settled(self) -> None:
        """Quorum-thread fence: block (bounded) until no speculative
        commit is outstanding. The main thread resolves the vote early in
        every step, so in the blessed flows this wait is sub-step-length;
        the bound keeps a misbehaving caller from wedging the quorum."""
        cap = min(self._timeout.total_seconds(), 10.0)
        with self._spec_cond:
            settled = self._spec_cond.wait_for(
                lambda: self._pending_commit is None or self._shutting_down,
                timeout=cap,
            )
        if not settled:
            self._logger.warn(
                "speculation fence timed out; serving possibly-speculative "
                "state (resolve_pending_commit() is overdue on the trainer)"
            )

    def _prepare_commit(self) -> _PendingCommit:
        """Shared pre-vote half of the commit barrier: drain the step's
        pending work, land a staged heal, and snapshot everything the
        post-vote accounting needs (the live fields describe the NEXT
        step by the time a pipelined vote resolves)."""
        import time as _time

        # injection window the ROADMAP flake lives in: workers observed
        # dying silently right AFTER the commit barrier's drain — a kill
        # scheduled here reproduces that timing on demand
        fault_point("commit.vote", match="prepare", step=self._step)
        t0 = _time.perf_counter()
        for work in self._pending_work:
            if self._errored is not None:
                break
            try:
                work.wait()
            except Exception:
                # wrap_future already latched it
                pass
        self._pending_work = []

        if self._healing:
            self._apply_pending_state_dict()

        rec = _PendingCommit()
        rec.step = self._step
        # membership as of the step's OPS (issue-time snapshot), not of a
        # death-watch re-quorum that may have landed after them
        rec.n_step = (
            self._step_n if self._step_n is not None else self.num_participants()
        )
        rec.enough_replicas = rec.n_step >= self._min_replica_size
        # a step whose collectives spanned two plane epochs (death-watch
        # re-quorum mid-step) mixed normalization denominators. The span is
        # a LOCAL observation — the re-quorum can land between ops on one
        # rank and entirely after another's — but client.should_commit is a
        # global conjunction, so one rank's veto aborts the step group-wide
        rec.mixed_epochs = len(self._step_epochs) > 1
        rec.errored = self._errored
        rec.local_vote = (
            rec.enough_replicas and self._errored is None and not rec.mixed_epochs
        )
        # divergence sentinel: fold the step's ordered per-op digests
        # into ONE step digest (delta.py's tree fold) and clear for the
        # next step; the vote RPC piggybacks it to the lighthouse's
        # (epoch, step) cohort compare. A step that is not committing
        # cleanly here (error latched / incomplete digest coverage)
        # ABSTAINS ("-"): it still completes the cohort so peers' fence
        # waits never stall on an aborting group, but its partial digest
        # never enters the comparison — only committing states must
        # agree, and an aborting step commits nothing to diverge.
        rec.epoch = self._quorum_id
        if self._divergence_sentinel:
            rec.digest = "-"
            if rec.local_vote and self._step_digests:
                try:
                    from torchft_tpu.checkpointing import delta as _delta

                    rec.digest = _delta.tree_digest(self._step_digests)
                except Exception:  # noqa: BLE001 — degrade to abstain
                    rec.digest = "-"
        self._step_digests = []

        if self._errored is not None and self._errored_epoch == self._quorum_id:
            # the data plane is suspect: request a flush so the next quorum
            # reconfigures every group into a fresh rendezvous epoch. An
            # error from a PREVIOUS epoch's plane needs no flush — the
            # death-watch re-quorum already rebuilt connectivity. Recorded
            # at ISSUE time (nothing reads it before the next quorum RPC,
            # which in pipelined mode fires while the vote is in flight).
            self._commit_failures += 1
        rec.prepare_s = _time.perf_counter() - t0
        return rec

    def _finish_commit(
        self, rec: _PendingCommit, should_commit: bool, barrier_s: float,
        disallow: bool = True,
    ) -> None:
        """Shared post-vote half (MAIN thread only): telemetry, step
        counters, watchdog/step-timer bookkeeping."""
        self._watchdog.disarm()
        telemetry.COMMIT_BARRIER.observe(barrier_s)
        self._logger.info(
            f"should_commit={should_commit} "
            f"enough_replicas={rec.enough_replicas} errored={rec.errored}"
        )

        if disallow:
            # close the checkpoint-serving window: after the commit the
            # staged state is stale
            self._checkpoint_transport.disallow_checkpoint()

        # trail step number is the step that ran (pre-increment) — every
        # lifecycle record of one step (quorum_start, commit/abort,
        # step_outlier) joins on the same step value
        step_in_trail = rec.step
        if should_commit:
            telemetry.COMMITS_TOTAL.labels(outcome="committed").inc()
            telemetry.emit(
                "commit", step=step_in_trail, participants=rec.n_step
            )
            self._step += 1
            self._batches_committed += rec.n_step
            telemetry.CURRENT_STEP.set(self._step)
        else:
            telemetry.COMMITS_TOTAL.labels(outcome="aborted").inc()
            telemetry.emit(
                "abort",
                step=step_in_trail,
                enough_replicas=rec.enough_replicas,
                mixed_epochs=rec.mixed_epochs,
                errored=str(rec.errored) if rec.errored else None,
            )
        # step boundary for the rolling rate: quorum-reconfigure/heal steps
        # are tagged as outliers, so the recovery cost of an FT event is
        # readable from the trail instead of denting the headline rate
        dur = self.step_timer.tick()
        if dur is not None and self.step_timer.last_tags:
            telemetry.emit(
                "step_outlier",
                step=step_in_trail,
                duration_s=round(dur, 4),
                tags=list(self.step_timer.last_tags),
                committed=should_commit,
            )
        # step-anatomy boundary: the barrier cost joins this step's row,
        # then the row is assembled (idle = wall minus attributed phases)
        # and the SLO evaluators see the step's wall/rejoin durations
        telemetry.LEDGER.record("commit_barrier", barrier_s)
        row = telemetry.LEDGER.tick(step=step_in_trail)
        if row is not None:
            self._slo.observe_step(row["wall_s"])
        if should_commit and self._rejoin_t0 is not None:
            import time as _time

            self._slo.observe_rejoin(_time.perf_counter() - self._rejoin_t0)
            self._rejoin_t0 = None

    def should_commit(self, timeout: Optional[timedelta] = None) -> bool:
        """Per-step commit barrier: True iff every rank in the group had a
        clean step. Call after backward, step the optimizer only on True."""
        # keep the commit path loud on misuse: the pre-quorum guards on the
        # read-only participation queries must not turn a missing
        # start_quorum into a silent quorum-wide veto
        assert (
            self._quorum_future is not None
        ), "must call start_quorum before should_commit"
        import time as _time

        if self._pending_commit is not None:
            # a stray pending vote (caller mixed pipelined and sync paths,
            # e.g. LocalSGD sync on a pipelined manager): resolve it first
            # — it belongs to the PREVIOUS step; this call votes the
            # current one
            self.resolve_pending_commit()

        t_commit = _time.perf_counter()
        rec = self._prepare_commit()
        with telemetry.TRACER.span(
            "should_commit",
            trace_id=self._trace_id(),
            vote=rec.local_vote,
        ) as sc_span:
            should_commit = self._client.should_commit(
                self._rank,
                rec.step,
                rec.local_vote,
                timeout=timeout or self._timeout,
                digest=rec.digest,
                epoch=rec.epoch,
                fence=self._divergence_fence,
            )
            sc_span.set(decision=should_commit)
        # getattr: duck-typed test managers may predate the sentinel
        if getattr(self._client, "last_divergence", False) is True:
            self._note_divergence(rec.step)
        self._finish_commit(
            rec, should_commit, _time.perf_counter() - t_commit
        )
        return should_commit

    def should_commit_async(
        self,
        timeout: Optional[timedelta] = None,
        on_resolved: Optional[Callable[[bool], None]] = None,
    ) -> Future:
        """Pipelined commit barrier: issue this step's vote on the vote
        thread and return immediately so the caller can start the next
        step's compute while the RPC is in flight.

        The caller MUST apply the optimizer update speculatively *before*
        this call (keeping the pre-update state alive as a rollback
        snapshot) and MUST call :meth:`resolve_pending_commit` before
        issuing the next step's collectives or commit. ``on_resolved`` is
        invoked on the MAIN thread inside that resolution, before the
        speculation fence lifts — restore the snapshot there on a veto so
        the quorum thread can never serve a half-rolled-back state.

        Returns the vote :class:`~torchft_tpu.futures.Future` (also held
        internally as the pending record)."""
        assert (
            self._quorum_future is not None
        ), "must call start_quorum before should_commit_async"
        assert (
            self._pending_commit is None
        ), "at most one speculative commit may be outstanding"
        assert not self._healing, "healing replica must not speculate"

        rec = self._prepare_commit()
        rec.on_resolved = on_resolved
        # close the checkpoint-serving window at ISSUE time: resolution
        # happens after the NEXT step's quorum, which may re-stage a fresh
        # checkpoint for a healer — a resolution-time disallow would
        # clobber that newer window (sync mode has no such inversion)
        self._checkpoint_transport.disallow_checkpoint()
        if self._commit_executor is None:
            self._commit_executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="commit_vote"
            )
        if self._commit_client is None:
            self._commit_client = ManagerClient(
                self._manager_addr, connect_timeout=self._connect_timeout
            )
        trace_id = self._trace_id()
        vote_timeout = timeout or self._timeout

        def vote() -> bool:
            with telemetry.TRACER.span(
                "should_commit",
                trace_id=trace_id,
                vote=rec.local_vote,
                pipelined=True,
            ) as sc_span:
                decision = self._commit_client.should_commit(
                    self._rank, rec.step, rec.local_vote, timeout=vote_timeout,
                    digest=rec.digest, epoch=rec.epoch,
                    fence=self._divergence_fence,
                )
                sc_span.set(decision=decision)
            if getattr(self._commit_client, "last_divergence", False) is True:
                self._note_divergence(rec.step)
            return decision

        rec.future = run_in_executor(self._commit_executor, vote)
        # publish under the fence lock: the quorum thread checks
        # _pending_commit to decide whether heal traffic must wait
        with self._spec_cond:
            self._pending_commit = rec
        return rec.future

    def resolve_pending_commit(self, rearm: bool = True) -> Optional[bool]:
        """Resolve the in-flight pipelined vote (MAIN thread only).

        Blocks until the vote RPC lands (in steady state it already has —
        the next step's compute covered the RTT), runs the post-vote
        accounting, invokes the issue-time ``on_resolved`` callback (which
        restores the rollback snapshot on a veto), and lifts the
        speculation fence. Returns the decision, or None when no vote was
        outstanding. On a vote RPC failure the snapshot is restored (the
        step is treated as not applied, matching sync-mode semantics where
        the exception precedes the optimizer update) and the error
        re-raised.

        ``rearm`` re-arms the step watchdog for the step now in flight;
        pass False when resolving at the end of training (no step is
        running, a re-armed watchdog would false-positive an idle
        process)."""
        import time as _time

        rec = self._pending_commit
        if rec is None:
            return None
        t0 = _time.perf_counter()
        try:
            assert rec.future is not None
            decision = rec.future.wait()
        except BaseException as e:  # noqa: BLE001 — restore, then re-raise
            self._rollback(rec, error=e)
            with self._spec_cond:
                self._pending_commit = None
                self._spec_cond.notify_all()
            raise
        blocked_s = _time.perf_counter() - t0
        # COMMIT_BARRIER records what the commit COST the main thread: the
        # issue-time drain plus however long resolution actually blocked —
        # near-zero when the pipeline fully hid the RTT
        self._finish_commit(
            rec, decision, rec.prepare_s + blocked_s, disallow=False
        )
        if not decision:
            self._rollback(rec)
        elif rec.on_resolved is not None:
            try:
                rec.on_resolved(True)
            except Exception:  # noqa: BLE001
                self._logger.exception("on_resolved callback failed")
        with self._spec_cond:
            self._pending_commit = None
            self._spec_cond.notify_all()
        if rearm:
            # start_quorum for the in-flight step already armed the
            # watchdog, but _finish_commit just disarmed it — re-arm so
            # the rest of the step keeps stall coverage
            self._watchdog.arm(self._step_label)
        return decision

    def _rollback(
        self, rec: _PendingCommit, error: Optional[BaseException] = None
    ) -> None:
        """Run the caller's snapshot restore + record the rollback."""
        telemetry.COMMIT_PIPELINE_ROLLBACKS.inc()
        telemetry.emit(
            "commit_rollback",
            step=rec.step,
            errored=str(error) if error is not None else None,
        )
        self._logger.warn(
            f"pipelined commit vetoed at step {rec.step}; rolling back "
            f"speculative update"
        )
        if rec.on_resolved is not None:
            try:
                rec.on_resolved(False)
            except Exception:  # noqa: BLE001
                self._logger.exception("rollback callback failed")

    # ------------------------------------------------------------------
    # state
    # ------------------------------------------------------------------

    def load_state_dict(self, state_dict: Dict[str, int]) -> None:
        """Restore manager progress counters (pair with the user's periodic
        checkpoint of model/optimizer/dataloader state)."""
        self._step = state_dict["step"]
        self._batches_committed = state_dict["batches_committed"]

    def _manager_state_dict(self) -> Dict[str, object]:
        assert self._user_state_dict is not None, "user state_dict not set"
        return {"user": self._user_state_dict(), "torchft": self.state_dict()}

    def state_dict(self) -> Dict[str, int]:
        return {"step": self._step, "batches_committed": self._batches_committed}

    def current_step(self) -> int:
        """Current step count; incremented only on committed steps, so all
        participants agree on it."""
        return self._step

    def batches_committed(self) -> int:
        """Total batches committed across all replica groups and steps."""
        return self._batches_committed

    def num_participants(self) -> int:
        """Replica groups participating in the current step; 0 before the
        first ``start_quorum`` (no assert-crash — reference parity gap noted
        in round-1 review)."""
        if self._quorum_future is None:
            return 0
        self.wait_quorum()
        assert self._participating_world_size >= 0
        return self._participating_world_size

    def participating_rank(self) -> Optional[int]:
        """This group's rank among the participating groups, or None for
        spectators (spares, healing replicas) and before the first
        ``start_quorum``."""
        if self._quorum_future is None:
            return None
        self.wait_quorum()
        return self._participating_rank

    def is_participating(self) -> bool:
        """Whether this replica's contributions count this step; False
        before the first ``start_quorum``."""
        if self._quorum_future is None:
            return False
        self.wait_quorum()
        if self._participating_rank is None:
            return False
        if self._healing or self._group_healing:
            assert self._use_async_quorum
            return False
        return True
