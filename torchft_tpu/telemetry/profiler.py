"""Always-on Python stack sampler + collapsed-stack utilities (ISSUE 12).

The Python half of the diagnosis plane's profiler pair
(``native/profiler.h`` samples the GIL-free planes; this module samples
the interpreter threads): a daemon thread wakes at ``TORCHFT_PROF_HZ``
(default :data:`DEFAULT_HZ`, ``0`` = disarmed) and folds every live
thread's ``sys._current_frames`` stack into a collapsed-stack aggregate —
the same flamegraph-ready ``label;root;...;leaf count`` text the native
side emits, so one toolchain (``flamegraph.pl``, speedscope, the bundled
``subtract_folded``/``merge_folded`` helpers) reads both.

Sampling a Python stack is ~microseconds at single-digit Hz — cheap
enough to leave on for the life of the trainer, which is the point: when
a latch fires, the *history* is already in the aggregate, and the
diagnosis engine (:mod:`torchft_tpu.telemetry.diagnosis`) only boosts
the rate (``TORCHFT_PROF_BURST_HZ``) for a bounded window instead of
attaching a profiler after the fact.

Also here:

* :func:`merge_folded` / :func:`subtract_folded` — exact aggregation
  across processes / snapshots (counts are integers on identical keys,
  so a merge is elementwise addition and a capture window is a
  snapshot diff);
* :func:`capture_jax_trace` — the bounded ``jax.profiler.trace`` window
  for the compute phase (``TORCHFT_DIAG_JAX=1`` gates it: traces are
  large and jax may be absent on lighthouse-only hosts);
* :func:`poll_native_samples` — folds the native sampler's cumulative
  sample count into ``tft_prof_samples_total{plane="native"}``.

Knobs (registry in docs/observability.md "Profiling & diagnosis
bundles", enforced by the ``obs-env-drift`` analysis rule):
``TORCHFT_PROF_HZ``, ``TORCHFT_PROF_BURST_HZ``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import Counter
from typing import Any, Dict, Iterable, List, Optional

__all__ = [
    "DEFAULT_HZ",
    "DEFAULT_BURST_HZ",
    "PROFILER",
    "PyStackSampler",
    "env_hz",
    "burst_hz",
    "merge_folded",
    "subtract_folded",
    "parse_folded",
    "render_folded",
    "capture_jax_trace",
    "poll_native_samples",
]

# prime-ish default, matching native/profiler.h kDefaultHz: avoids
# lockstep with 10 ms schedulers and 100 Hz tick sources
DEFAULT_HZ = 11.0
DEFAULT_BURST_HZ = 97.0


def env_hz() -> float:
    """The configured always-on rate (``TORCHFT_PROF_HZ``; unset →
    :data:`DEFAULT_HZ`, ``0`` → disarmed)."""
    raw = os.environ.get("TORCHFT_PROF_HZ")
    if raw is None or raw == "":
        return DEFAULT_HZ
    try:
        return float(raw)
    except ValueError:
        return DEFAULT_HZ


def burst_hz() -> float:
    """The capture-window boost rate (``TORCHFT_PROF_BURST_HZ``)."""
    raw = os.environ.get("TORCHFT_PROF_BURST_HZ")
    try:
        return float(raw) if raw else DEFAULT_BURST_HZ
    except ValueError:
        return DEFAULT_BURST_HZ


class PyStackSampler:
    """Low-Hz ``sys._current_frames`` sampler with collapsed-stack
    aggregation.

    One instance per process (:data:`PROFILER`); the Manager calls
    :meth:`ensure_started` at init so every trainer is always-on by
    default. ``set_hz(0)`` pauses (the thread idles), ``set_hz(h)``
    resumes — the diagnosis engine's burst boost."""

    MAX_DEPTH = 48

    def __init__(self, hz: Optional[float] = None) -> None:
        self._lock = threading.Lock()
        self._agg: Counter = Counter()  # guarded-by: _lock
        self._hz = hz if hz is not None else env_hz()
        self._samples = 0  # guarded-by: _lock
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._wake = threading.Event()

    # -- control ---------------------------------------------------------

    @property
    def hz(self) -> float:
        return self._hz

    def set_hz(self, hz: float) -> None:
        self._hz = float(hz)
        self._wake.set()  # re-evaluate the sleep immediately
        if self._hz > 0:
            self.ensure_started()

    def ensure_started(self) -> "PyStackSampler":
        """Idempotent; a disarmed sampler (hz=0) starts no thread at all
        — zero cost until someone boosts it."""
        if self._hz <= 0 or self._thread is not None:
            return self
        with self._lock:
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="tft_py_profiler"
                )
                self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    # -- sampling --------------------------------------------------------

    def _thread_labels(self) -> Dict[int, str]:
        return {
            t.ident: t.name or f"tid{t.ident}"
            for t in threading.enumerate()
            if t.ident is not None
        }

    def sample_once(self) -> int:
        """One sampling pass over every live thread (also the testable
        core); returns the number of stacks recorded."""
        labels = self._thread_labels()
        me = threading.get_ident()
        n = 0
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # never sample the sampler
            stack: List[str] = []
            f: Any = frame
            depth = 0
            while f is not None and depth < self.MAX_DEPTH:
                code = f.f_code
                stack.append(f"{code.co_filename.rsplit('/', 1)[-1]}:"
                             f"{code.co_name}")
                f = f.f_back
                depth += 1
            if not stack:
                continue
            stack.reverse()  # root-first, like the native renderer
            label = labels.get(tid, f"tid{tid}")
            key = label.replace(";", ":") + ";" + ";".join(
                s.replace(";", ":") for s in stack
            )
            with self._lock:
                self._agg[key] += 1
                self._samples += 1
            n += 1
        if n:
            try:
                from torchft_tpu import telemetry

                telemetry.PROF_SAMPLES.labels(plane="py").inc(n)
            except Exception:  # noqa: BLE001 — never fail the sampler
                pass
        return n

    def _run(self) -> None:
        while not self._stop.is_set():
            hz = self._hz
            if hz <= 0:
                self._wake.wait(timeout=0.1)
                self._wake.clear()
                continue
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — sampling must never die
                pass
            self._wake.wait(timeout=max(0.001, 1.0 / hz))
            self._wake.clear()

    # -- consumers -------------------------------------------------------

    def samples_total(self) -> int:
        with self._lock:
            return self._samples

    def folded(self) -> str:
        """Collapsed stacks, sorted (same shape as
        ``_native.prof_snapshot``)."""
        with self._lock:
            items = sorted(self._agg.items())
        return "".join(f"{k} {v}\n" for k, v in items)

    def reset(self) -> None:
        with self._lock:
            self._agg.clear()
            self._samples = 0


PROFILER = PyStackSampler()


# ---------------------------------------------------------------------------
# collapsed-stack (folded) text utilities
# ---------------------------------------------------------------------------


def parse_folded(text: str) -> Dict[str, int]:
    """``"stack count"`` lines → ``{stack: count}`` (malformed lines are
    skipped — a torn capture file must not fail the merge)."""
    out: Dict[str, int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, cnt = line.rpartition(" ")
        if not stack:
            continue
        try:
            out[stack] = out.get(stack, 0) + int(cnt)
        except ValueError:
            continue
    return out


def render_folded(agg: Dict[str, int]) -> str:
    return "".join(
        f"{k} {v}\n" for k, v in sorted(agg.items()) if v > 0
    )


def merge_folded(*texts: str) -> str:
    """EXACT cross-process merge: counts are integers on identical stack
    keys, so merging N replicas' captures is elementwise addition — the
    same property the lathist grid gives histograms (and the test
    asserts: ``counts(merge) == counts(a) + counts(b)`` per key)."""
    total: Dict[str, int] = {}
    for t in texts:
        for k, v in parse_folded(t).items():
            total[k] = total.get(k, 0) + v
    return render_folded(total)


def subtract_folded(after: str, before: str) -> str:
    """The bounded-window diff: both samplers aggregate cumulatively, so
    ``snapshot(t1) − snapshot(t0)`` is exactly the samples recorded in
    the window (clamped at 0 per key to tolerate a reset in between)."""
    a = parse_folded(after)
    for k, v in parse_folded(before).items():
        a[k] = a.get(k, 0) - v
    return render_folded(a)


# ---------------------------------------------------------------------------
# jax profiler capture window
# ---------------------------------------------------------------------------


def jax_capture_enabled() -> bool:
    return os.environ.get("TORCHFT_DIAG_JAX", "0") == "1"


def capture_jax_trace(log_dir: str, duration_s: float) -> Optional[str]:
    """Bounded ``jax.profiler`` trace window for the compute phase:
    start, sleep the window, stop. Returns the trace dir, or None when
    disabled/unavailable BEFORE the window was slept (lighthouse-only
    hosts have no jax; a failed trace must never fail the capture that
    asked for it). Once ``start_trace`` succeeds the window is consumed
    exactly once and the dir is returned even if ``stop_trace`` fails —
    the caller sleeps the window itself on None, so signaling
    already-slept distinctly keeps the capture window from doubling."""
    if not jax_capture_enabled():
        return None
    try:
        import jax

        os.makedirs(log_dir, exist_ok=True)
        jax.profiler.start_trace(log_dir)
    except Exception:  # noqa: BLE001 — window NOT consumed yet
        return None
    try:
        time.sleep(duration_s)
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — trace may be torn, but the
            pass           # window was slept: report it consumed
    return log_dir


# ---------------------------------------------------------------------------
# native-side plumbing (best-effort: the native plane is optional)
# ---------------------------------------------------------------------------

_native_base = 0
_native_lock = threading.Lock()


def poll_native_samples() -> int:
    """Fold the native sampler's cumulative count into
    ``tft_prof_samples_total{plane="native"}`` (counters can only
    increase, so this tracks the delta since the last poll and re-bases
    after a native reset). Returns the cumulative native count."""
    global _native_base
    try:
        from torchft_tpu import _native

        total = _native.prof_samples_total()
    except Exception:  # noqa: BLE001
        return 0
    with _native_lock:
        delta = total - _native_base
        if delta < 0:  # native side was reset
            delta = total
        _native_base = total
    if delta > 0:
        try:
            from torchft_tpu import telemetry

            telemetry.PROF_SAMPLES.labels(plane="native").inc(delta)
        except Exception:  # noqa: BLE001
            pass
    return total


def native_set_hz(hz: float) -> bool:
    """Retarget the native sampler (burst boost / restore); False when
    the native plane is unavailable."""
    try:
        from torchft_tpu import _native

        _native.prof_set_hz(hz)
        return True
    except Exception:  # noqa: BLE001
        return False


def native_hz() -> Optional[float]:
    """The native sampler's current effective rate (None when the
    native plane is unavailable) — saved before a burst boost so the
    restore honors a rate someone set live, not just the env default."""
    try:
        from torchft_tpu import _native

        return float(_native.prof_hz())
    except Exception:  # noqa: BLE001
        return None


def native_folded() -> str:
    try:
        from torchft_tpu import _native

        return _native.prof_snapshot()
    except Exception:  # noqa: BLE001
        return ""
