"""Process-wide metrics registry: Counter / Gauge / Histogram with labeled
children and Prometheus text exposition.

The reference exports no metrics at all (SURVEY §5.5); the native
lighthouse grew a /metrics endpoint (native/coord.cc) but the Python FT
runtime — where quorum latency, heal cost and allreduce traffic actually
happen — had only ad-hoc ``logging`` lines. This registry is the substrate:
dependency-free, thread-safe, cheap enough for hot paths (a counter inc is
one lock + one float add), rendered on demand in Prometheus text format
(version 0.0.4) or dumped as a plain dict snapshot.

Semantics follow the Prometheus client-library conventions:

* a metric created with ``labelnames`` is a *family*; ``labels(...)``
  returns (creating on first use) the child for one label-value tuple and
  the family itself cannot be observed directly;
* a metric created without labels is its own single child;
* histograms use cumulative ``le`` buckets plus ``+Inf``, ``_sum`` and
  ``_count`` series.
"""

from __future__ import annotations

import math
import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
]

# Latency-oriented default: sub-ms collectives up through minute-scale
# heals land in distinct buckets.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

_LabelValues = Tuple[str, ...]


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 2**53:
        return str(int(v))
    return repr(float(v))


def _labels_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape_label_value(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _Metric:
    """Shared family machinery: child creation, rendering, dumping.

    A family with labelnames holds one child per label-value tuple; a
    label-less family is its own single child (keyed by ``()``).
    """

    type_name = "untyped"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        **kwargs,
    ) -> None:
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._kwargs = kwargs
        self._lock = threading.Lock()
        self._children: Dict[_LabelValues, _Metric] = {}
        if not self.labelnames:
            self._children[()] = self

    def labels(self, *values, **kw) -> "_Metric":
        if kw:
            if values:
                raise ValueError("pass label values positionally OR by name")
            try:
                values = tuple(kw[n] for n in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"{self.name}: missing label {e} (have {self.labelnames})"
                ) from e
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, got {values}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = type(self)(self.name, self.help, (), **self._kwargs)
                self._children[values] = child
            return child

    def _check_observable(self) -> None:
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; "
                f"call .labels(...) first"
            )

    def _snapshot_children(self) -> List[Tuple[_LabelValues, "_Metric"]]:
        with self._lock:
            return sorted(self._children.items())

    def reset(self) -> None:
        """Zero every child's observations IN PLACE (instrumented modules
        hold child references, so dropping children would silently orphan
        their future observations)."""
        for _values, child in self._snapshot_children():
            child._reset_values()

    def _reset_values(self) -> None:
        raise NotImplementedError

    # subclasses implement:
    def _render_child(
        self, names: Sequence[str], values: _LabelValues
    ) -> List[str]:
        raise NotImplementedError

    def _dump_child(self) -> Dict:
        raise NotImplementedError

    def render(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} {self.type_name}",
        ]
        for values, child in self._snapshot_children():
            lines.extend(child._render_child(self.labelnames, values))
        return lines

    def dump(self) -> Dict:
        return {
            "type": self.type_name,
            "help": self.help,
            "labelnames": list(self.labelnames),
            "samples": [
                {
                    "labels": dict(zip(self.labelnames, values)),
                    **child._dump_child(),
                }
                for values, child in self._snapshot_children()
            ],
        }


class Counter(_Metric):
    """Monotonically increasing value."""

    type_name = "counter"

    def __init__(self, name, help="", labelnames=(), **kwargs) -> None:
        self._value = 0.0
        super().__init__(name, help, labelnames, **kwargs)

    def inc(self, amount: float = 1.0) -> None:
        self._check_observable()
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _render_child(self, names, values) -> List[str]:
        return [f"{self.name}{_labels_str(names, values)} "
                f"{_format_value(self.value)}"]

    def _dump_child(self) -> Dict:
        return {"value": self.value}

    def _reset_values(self) -> None:
        with self._lock:
            self._value = 0.0


class Gauge(_Metric):
    """Value that can go up and down."""

    type_name = "gauge"

    def __init__(self, name, help="", labelnames=(), **kwargs) -> None:
        self._value = 0.0
        super().__init__(name, help, labelnames, **kwargs)

    def set(self, value: float) -> None:
        self._check_observable()
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self._check_observable()
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _render_child(self, names, values) -> List[str]:
        return [f"{self.name}{_labels_str(names, values)} "
                f"{_format_value(self.value)}"]

    def _dump_child(self) -> Dict:
        return {"value": self.value}

    def _reset_values(self) -> None:
        with self._lock:
            self._value = 0.0


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics)."""

    type_name = "histogram"

    def __init__(
        self,
        name,
        help="",
        labelnames=(),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        **kwargs,
    ) -> None:
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = tuple(bounds)
        self._counts = [0] * (len(bounds) + 1)  # +1 for +Inf
        self._sum = 0.0
        super().__init__(name, help, labelnames, buckets=self.buckets, **kwargs)

    def observe(self, value: float) -> None:
        self._check_observable()
        value = float(value)
        idx = len(self.buckets)
        for i, b in enumerate(self.buckets):
            if value <= b:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += value

    @contextmanager
    def time(self) -> Iterator[None]:
        """Observe the wall-clock duration of a block."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0)

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def raw_counts(self) -> List[int]:
        """Raw (non-cumulative) per-bucket counts, last slot is +Inf.

        Mergeable representation: folding N histograms on the same grid is
        elementwise addition, which the fleet-rollup path relies on.
        """
        with self._lock:
            return list(self._counts)

    def snapshot(self) -> Dict:
        """Cumulative bucket counts + sum + count, read under one lock."""
        with self._lock:
            counts = list(self._counts)
            total_sum = self._sum
        cumulative: List[int] = []
        acc = 0
        for c in counts:
            acc += c
            cumulative.append(acc)
        return {
            "buckets": {
                _format_value(b): cumulative[i]
                for i, b in enumerate(self.buckets)
            },
            "count": acc,
            "sum": total_sum,
        }

    def quantile(self, q: float) -> Optional[float]:
        """Approximate quantile interpolated within bucket bounds (the
        scrape-side ``histogram_quantile`` estimate; None when empty).
        Observations past the last bound clamp to it."""
        with self._lock:
            counts = list(self._counts)
        total = sum(counts)
        if not total:
            return None
        target = q * total
        acc = 0
        lo = 0.0
        for i, b in enumerate(self.buckets):
            nxt = acc + counts[i]
            if nxt >= target and counts[i]:
                frac = (target - acc) / counts[i]
                return lo + (b - lo) * min(1.0, max(0.0, frac))
            acc = nxt
            lo = b
        return self.buckets[-1]

    def _render_child(self, names, values) -> List[str]:
        snap = self.snapshot()
        le_names = tuple(names) + ("le",)
        lines = [
            f"{self.name}_bucket{_labels_str(le_names, values + (b,))} {c}"
            for b, c in snap["buckets"].items()
        ]
        lines.append(
            f"{self.name}_bucket{_labels_str(le_names, values + ('+Inf',))} "
            f"{snap['count']}"
        )
        lines.append(
            f"{self.name}_sum{_labels_str(names, values)} "
            f"{_format_value(snap['sum'])}"
        )
        lines.append(
            f"{self.name}_count{_labels_str(names, values)} {snap['count']}"
        )
        return lines

    def _dump_child(self) -> Dict:
        return self.snapshot()

    def _reset_values(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0


class MetricsRegistry:
    """Thread-safe get-or-create registry of metric families.

    ``counter/gauge/histogram`` are idempotent by name: repeat calls return
    the existing family, so module-level instrumentation and tests can both
    name a metric without coordinating construction order. A name clash
    across types raises — silent type morphing would corrupt scrapes.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kwargs) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls:
                    raise ValueError(
                        f"metric {name} already registered as {m.type_name}"
                    )
                if tuple(labelnames) != m.labelnames:
                    # a silent schema mismatch would hand the second
                    # registrant a family whose observe/inc raises later,
                    # ON the hot path — fail at registration instead
                    raise ValueError(
                        f"metric {name} already registered with labels "
                        f"{m.labelnames}, not {tuple(labelnames)}"
                    )
                buckets = kwargs.get("buckets")
                if buckets is not None:
                    req = tuple(sorted(float(b) for b in buckets))
                    # DEFAULT_BUCKETS counts as "unspecified": a plain
                    # get-by-name must not raise against a custom family
                    if req != m.buckets and req != DEFAULT_BUCKETS:
                        raise ValueError(
                            f"metric {name} already registered with "
                            f"buckets {m.buckets}, not {req}"
                        )
                return m
            m = cls(name, help, labelnames, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self, name: str, help: str = "", labelnames=(), buckets=DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def clear(self) -> None:
        """Drop every registered family (tests)."""
        with self._lock:
            self._metrics.clear()

    def reset_values(self) -> None:
        """Zero every family's observations in place (tests) — safer than
        :meth:`clear` when instrumented modules hold family references."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.reset()

    def render(self) -> str:
        """Prometheus text exposition (version 0.0.4)."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        out: List[str] = []
        for _name, m in metrics:
            out.extend(m.render())
        return "\n".join(out) + "\n"

    def dump(self) -> Dict[str, Dict]:
        """Plain-dict snapshot of every family (JSON-serializable)."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        return {name: m.dump() for name, m in metrics}
