"""Time-series plane: replica-side series publication + range-query client.

The lighthouse keeps a fixed-retention ring of samples per
``(replica, series)`` (``native/tsdb.h``), keyed by the clock-sync-free
``(epoch, step)`` coordinates and fed by the SAME quorum-piggyback
telemetry that already carries the summary/anatomy digests — zero extra
control-plane round trips. This module is both ends of that pipe:

* :func:`build_series` — the replica side. Builds the flat
  ``{name: float}`` sample map the Manager attaches to its telemetry
  payload each step: the last step row's wall/local/per-phase seconds
  (``telemetry.anatomy.StepLedger.last_row`` — raw per-step values, not
  percentiles, because percentile smoothing is exactly what would hide
  the level shifts the regression sentinel catches), the rolling local
  p50, lathist-derived native p50/p99s, and the SLO/stuck/divergence
  flags as 0/1 series. The lighthouse stays schema-blind: names are
  opaque strings, so this vocabulary can evolve without touching C++.

* :func:`poll_timeseries` — the fleet side. One ``GET /timeseries.json``
  range query (``since`` step cursor, ``max_points`` stride
  downsampling, replica/series substring filters) against the lighthouse
  that the critical-path attributor
  (:mod:`torchft_tpu.telemetry.critical_path`) and the perf-regression
  sentinel (:mod:`torchft_tpu.telemetry.regression`) both consume.

Series vocabulary published by :func:`build_series` (all seconds unless
flagged):

``wall_s`` / ``local_s``
    the last step's wall clock and LOCAL (peer-wait-excluded) time;
``local_p50_s``
    the rolling local p50 (same scalar the straggler detector reads);
``phase.<name>``
    the last step's per-phase seconds for every active anatomy phase;
``lat.<op>.p50_s`` / ``lat.<op>.p99_s``
    native latency quantiles (dp.hop / dp.stripe / rpc.serve /
    quorum.fanout) from this process's lathist snapshot;
``flag.slo_breach`` / ``flag.stuck`` / ``flag.divergence``
    detector latches as 0/1 series, so "when did it latch" is a range
    query instead of archaeology.

Knob registry (documented in docs/observability.md "Time series",
enforced both directions by the ``obs-env-drift`` analysis rule):

====================================  =====================================
``TORCHFT_TSDB_SERIES``               ``0`` disables the per-step series
                                      piggyback (default on)
``TORCHFT_TSDB_RETAIN``               lighthouse ring length per
                                      (replica, series), samples
                                      (default 512); also this client's
                                      assumption about how much history a
                                      full-range query can return
``TORCHFT_TSDB_MAX_SERIES``           per-replica series fan-out cap, both
                                      sides: the builder trims its map to
                                      this size and the lighthouse refuses
                                      (loudly: ``tsdb_dropped_series``)
                                      anything past it (default 64)
====================================  =====================================
"""

from __future__ import annotations

import json
import os
import urllib.request
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "DEFAULT_RETAIN",
    "DEFAULT_MAX_SERIES",
    "series_enabled",
    "build_series",
    "poll_timeseries",
    "iter_new_samples",
]

DEFAULT_RETAIN = 512
DEFAULT_MAX_SERIES = 64


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def retain() -> int:
    """The lighthouse-side ring length this deployment runs with (the
    native store reads the same env)."""
    return _env_int("TORCHFT_TSDB_RETAIN", DEFAULT_RETAIN)


def max_series() -> int:
    return _env_int("TORCHFT_TSDB_MAX_SERIES", DEFAULT_MAX_SERIES)


def series_enabled() -> bool:
    return os.environ.get("TORCHFT_TSDB_SERIES", "1") != "0"


def build_series(
    slo_breach: bool = False,
    stuck: bool = False,
    divergence: bool = False,
) -> Optional[Dict[str, float]]:
    """The replica's sample map for this step's piggyback (see module
    docstring for the vocabulary); None when disabled or before the
    first step row. Never raises — observability must not fail quorum."""
    if not series_enabled():
        return None
    try:
        from torchft_tpu import telemetry
        from torchft_tpu.telemetry.anatomy import lathist_quantile

        row = telemetry.LEDGER.last_row()
        if row is None:
            return None
        out: Dict[str, float] = {
            "wall_s": float(row["wall_s"]),
            "local_s": float(row["local_s"]),
        }
        p50 = telemetry.LEDGER.local_p50()
        if p50 is not None:
            out["local_p50_s"] = float(p50)
        for phase, seconds in row["phases"].items():
            out[f"phase.{phase}"] = float(seconds)
        try:
            from torchft_tpu.telemetry.native import native_latency_snapshot

            native = native_latency_snapshot()
            for op, hist in (native or {}).items():
                if int(hist.get("count", 0)):
                    out[f"lat.{op}.p50_s"] = float(
                        lathist_quantile(hist, 0.5)
                    )
                    out[f"lat.{op}.p99_s"] = float(
                        lathist_quantile(hist, 0.99)
                    )
        except Exception:  # noqa: BLE001 — native plane optional
            pass
        out["flag.slo_breach"] = 1.0 if slo_breach else 0.0
        out["flag.stuck"] = 1.0 if stuck else 0.0
        out["flag.divergence"] = 1.0 if divergence else 0.0
        cap = max_series()
        if len(out) > cap:
            # deterministic PRIORITY trim — the lighthouse would refuse
            # the overflow anyway; trimming here controls WHICH series
            # survive. Ordered by consumer criticality, not
            # alphabetically: wall/local and the phase decomposition
            # feed the critical-path and regression planes and must
            # outlive diagnostics like lat.* quantiles and the 0/1 flags
            # (a lexicographic trim would cut wall_s FIRST and keep
            # flag.* — exactly backwards).
            def rank(name: str) -> int:
                if name in ("wall_s", "local_s", "local_p50_s"):
                    return 0
                if name.startswith("phase."):
                    return 1
                if name.startswith("flag."):
                    return 2
                return 3  # lat.* and anything future

            out = dict(
                sorted(out.items(), key=lambda kv: (rank(kv[0]), kv[0]))
                [:cap]
            )
        return out
    except Exception:  # noqa: BLE001
        return None


def _base_url(addr: str) -> str:
    if "://" not in addr:
        addr = "http://" + addr
    return addr.rstrip("/")


def poll_timeseries(
    addr: str,
    replica: str = "",
    series: str = "",
    since: Optional[int] = None,
    max_points: Optional[int] = None,
    timeout: float = 3.0,
) -> Optional[Dict[str, Any]]:
    """One range query against the lighthouse's ``GET /timeseries.json``.
    Filters are substring matches; ``since`` is an exclusive step cursor
    (the reply's ``cursor.max_step`` is the next value); ``max_points``
    stride-downsamples each series (the newest sample always survives).
    Returns the parsed reply or None when unreachable — observability
    degrades, never raises."""
    params: List[str] = []
    if replica:
        params.append(f"replica={replica}")
    if series:
        params.append(f"series={series}")
    if since is not None:
        params.append(f"since={int(since)}")
    if max_points is not None:
        params.append(f"max_points={int(max_points)}")
    url = f"{_base_url(addr)}/timeseries.json"
    if params:
        url += "?" + "&".join(params)
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except Exception:  # noqa: BLE001
        return None


def iter_new_samples(
    reply: Dict[str, Any],
    cursor: Dict[Tuple[str, str], int],
) -> Iterable[Tuple[str, str, int, int, float]]:
    """Yield ``(replica, series, epoch, step, value)`` for every sample in
    ``reply`` newer than the per-(replica, series) ``cursor`` (mutated in
    place), in step order per series. The shared consumption idiom of the
    regression sentinel and the critical-path monitor: both poll the full
    ring and dedup here, so a replica lagging the fleet-wide max step
    (or a respawn restarting at step 0) never loses samples to a global
    since-cursor."""
    for rid, all_series in (reply.get("replicas") or {}).items():
        for name, body in (all_series or {}).items():
            key = (rid, name)
            last = cursor.get(key)
            for sample in body.get("samples") or []:
                try:
                    epoch, step, value = (
                        int(sample[0]), int(sample[1]), float(sample[2]),
                    )
                except (TypeError, ValueError, IndexError):
                    continue
                if last is not None and step <= last:
                    continue
                cursor[key] = step
                last = step
                yield rid, name, epoch, step, value
