"""Structured FT event trail — the flight recorder for fault-tolerance
lifecycle events.

Every quorum formation, heal, peer death, eviction and commit/abort is an
append-only JSONL record, so a recovery incident can be reconstructed from
disk (event ordering + wall-clock deltas) instead of re-run under a
profiler. The trail is process-wide: configure a sink once (or export
``TORCHFT_EVENT_TRAIL=/path/trail.jsonl`` before the process starts) and
every instrumented layer — Manager, collectives, checkpoint transports —
appends to it. An in-memory ring buffer always records the most recent
events regardless of sink, so tests and ``telemetry.dump()`` can read the
trail without touching the filesystem.

Record schema (one JSON object per line)::

    {"ts": <unix seconds, float>, "event": "<kind>", ...fields}

Canonical kinds and their fields are documented in
``docs/observability.md`` (quorum_start, quorum_ready, heal_begin,
heal_end, peer_death, eviction, commit, abort, checkpoint_send,
checkpoint_recv, step_outlier).
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

__all__ = [
    "EventTrail",
    "read_trail",
    "CANONICAL_EVENTS",
    "LIFECYCLE_EVENTS",
]

ENV_TRAIL_PATH = "TORCHFT_EVENT_TRAIL"
ENV_TRAIL_MAX_BYTES = "TORCHFT_EVENT_TRAIL_MAX_BYTES"

# Soak runs must not grow the trail unboundedly: past this many bytes the
# sink rolls to `<path>.1` (one generation kept) and starts fresh. 0
# disables rotation.
DEFAULT_TRAIL_MAX_BYTES = 64 << 20

# The documented event vocabulary (docs/observability.md "FT event trail"
# table). The drift-check test asserts doc <-> code agreement in both
# directions, so adding a kind here without documenting it (or vice versa)
# fails CI.
CANONICAL_EVENTS = (
    "quorum_start",
    "quorum_ready",
    "heal_begin",
    "heal_end",
    "heal_failed",
    "peer_death",
    "eviction",
    "commit",
    "abort",
    "commit_rollback",
    "checkpoint_send",
    "checkpoint_recv",
    "step_outlier",
    "watchdog_stall",
    "flight_dump",
    "fault_injected",
    "slo_breach",
    "slo_recovered",
    "straggler_detected",
    "straggler_cleared",
    "divergence_detected",
    "blackbox_recovered",
    "perf_regression",
    "perf_regression_cleared",
    "diagnosis_captured",
)

# The protocol-lifecycle subset of the vocabulary: the events the
# executable FT-protocol spec (torchft_tpu/analysis/protocol/) models and
# the trace-conformance checker replays. One constant, shared by the
# emitting side (this trail) and the verifying side (the spec), so the
# two can never silently disagree about which records ARE the protocol.
LIFECYCLE_EVENTS = (
    "quorum_start",
    "quorum_ready",
    "heal_begin",
    "heal_end",
    "heal_failed",
    "commit",
    "abort",
    "commit_rollback",
    "divergence_detected",
)


class EventTrail:
    """Thread-safe JSONL event sink with an in-memory ring buffer."""

    def __init__(
        self,
        path: Optional[str] = None,
        maxlen: int = 4096,
        max_bytes: Optional[int] = None,
    ) -> None:
        self._lock = threading.Lock()
        # live subscribers (the diagnosis trigger engine): called OUTSIDE
        # the trail lock, exceptions swallowed — a consumer can never
        # deadlock or fail the emitting step. guarded-by: _lock
        self._subscribers: List[Any] = []
        self._ring: Deque[Dict[str, Any]] = deque(maxlen=maxlen)
        self._file: Optional[io.TextIOBase] = None
        self._path: Optional[str] = None
        self._env_checked = False
        self._written = 0
        if max_bytes is None:
            try:
                max_bytes = int(
                    os.environ.get(
                        ENV_TRAIL_MAX_BYTES, str(DEFAULT_TRAIL_MAX_BYTES)
                    )
                )
            except ValueError:
                max_bytes = DEFAULT_TRAIL_MAX_BYTES
        self.max_bytes = max_bytes
        if path:
            self.configure(path)

    # -- sink management --

    def configure(self, path: Optional[str]) -> None:
        """Point the trail at ``path`` (append mode), or detach with None.
        Replaces any previous sink."""
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
            self._path = path
            self._env_checked = True  # explicit config wins over env
            if path:
                d = os.path.dirname(path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._file = open(path, "a", encoding="utf-8")
                self._written = self._existing_size(path)

    def path(self) -> Optional[str]:
        with self._lock:
            return self._path

    def _maybe_open_from_env(self) -> None:
        # called under self._lock
        if self._env_checked:
            return
        self._env_checked = True
        path = os.environ.get(ENV_TRAIL_PATH)
        if not path:
            return
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._file = open(path, "a", encoding="utf-8")
            self._path = path
            self._written = self._existing_size(path)
        except OSError:
            # observability must never take down training
            self._file = None
            self._path = None

    @staticmethod
    def _existing_size(path: str) -> int:
        try:
            return os.path.getsize(path)
        except OSError:
            return 0

    def _maybe_rotate(self) -> None:
        # called under self._lock, after a successful write+flush. One
        # rolled generation (`<path>.1`) bounds total disk at ~2x max_bytes
        # while keeping enough history to reconstruct a recent incident.
        if (
            self.max_bytes <= 0
            or self._file is None
            or self._path is None
            or self._written < self.max_bytes
        ):
            return
        try:
            self._file.close()
        except OSError:
            pass
        self._file = None
        try:
            os.replace(self._path, self._path + ".1")
        except OSError:
            pass  # rotation is best-effort; keep appending either way
        try:
            self._file = open(self._path, "a", encoding="utf-8")
            self._written = self._existing_size(self._path)
        except OSError:
            self._file = None

    # -- producer side --

    def emit(self, event: str, **fields: Any) -> Dict[str, Any]:
        """Append one record; returns it (with the stamped ``ts``)."""
        record = {"ts": time.time(), "event": event, **fields}
        line: Optional[str] = None
        with self._lock:
            self._maybe_open_from_env()
            self._ring.append(record)
            if self._file is not None:
                try:
                    line = json.dumps(record, default=str)
                    self._file.write(line + "\n")
                    self._file.flush()
                    self._written += len(line) + 1
                    self._maybe_rotate()
                except (OSError, ValueError):
                    pass  # a full disk must not fail a step
        # crash-durable mirror: the black box keeps the trail readable
        # even when this process is SIGKILLed with the file sink unset
        # (or mid-line) — see telemetry/blackbox.py
        from torchft_tpu.telemetry.blackbox import BLACKBOX

        BLACKBOX.record(event, **fields)
        # metric alongside the trail so dashboards can rate() FT events
        # without parsing JSONL (late import avoids a module cycle)
        from torchft_tpu.telemetry import FT_EVENTS_TOTAL

        FT_EVENTS_TOTAL.labels(event=event).inc()
        # live fan-out (ISSUE 12): the diagnosis engine turns latch
        # events into deep captures the moment they fire, instead of
        # polling the ring. Outside the lock; failures swallowed. The
        # unlocked emptiness check keeps the common no-subscriber
        # deployment from paying a second lock acquire per event — safe
        # because the list is only mutated under _lock (GIL-atomic ref
        # read) and a stale-empty read just delays one delivery.
        if self._subscribers:
            with self._lock:
                subscribers = list(self._subscribers)
            for cb in subscribers:
                try:
                    cb(record)
                except Exception:  # noqa: BLE001 — a consumer must never
                    pass           # fail the emitting step
        return record

    def subscribe(self, callback: Any) -> None:
        """Register a live consumer: ``callback(record)`` runs on the
        emitting thread after every :meth:`emit` (outside the trail
        lock). Keep callbacks fast — heavy work belongs on the
        consumer's own thread."""
        with self._lock:
            if callback not in self._subscribers:
                self._subscribers.append(callback)

    def unsubscribe(self, callback: Any) -> None:
        with self._lock:
            if callback in self._subscribers:
                self._subscribers.remove(callback)

    # -- consumer side --

    def recent(
        self, event: Optional[str] = None, limit: Optional[int] = None
    ) -> List[Dict[str, Any]]:
        """Most recent records from the ring buffer, oldest first;
        optionally filtered to one event kind."""
        with self._lock:
            records = list(self._ring)
        if event is not None:
            records = [r for r in records if r.get("event") == event]
        if limit is not None:
            records = records[-limit:]
        return records

    def clear(self) -> None:
        """Empty the ring buffer (the file sink, if any, is untouched)."""
        with self._lock:
            self._ring.clear()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


def read_trail(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL trail file back into records (skipping torn tails —
    a SIGKILLed process may leave a partial last line)."""
    records: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    continue
    except FileNotFoundError:
        pass
    return records
