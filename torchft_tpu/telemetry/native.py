"""Bridge to the C++ coordination layer's built-in counters.

The native lighthouse (native/coord.cc) already serves ``/status.json``
and a Prometheus ``/metrics`` page on its dashboard port — quorum_id,
participant steps, evictions_total, flush_requests_total, heartbeat ages.
Those counters live in the C++ process (possibly a different box), so the
Python registry can't own them; instead this module polls them over the
existing HTTP surface and either returns them as a dict
(:func:`poll_lighthouse`) or splices the raw exposition text into this
process's scrape output (:func:`scrape_lighthouse_metrics`), so one
Prometheus target can carry both layers.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any, Dict, Optional

__all__ = [
    "poll_lighthouse",
    "scrape_lighthouse_metrics",
    "poll_cluster",
    "fetch_merged_trace",
    "native_latency_snapshot",
]


def native_latency_snapshot() -> Optional[Dict[str, Any]]:
    """THIS process's native latency histograms (dp.hop / dp.stripe /
    rpc.serve / quorum.fanout) from ``_native.lathist_snapshot``: raw
    per-bucket counts on the fixed log2 grid shared with
    ``telemetry.anatomy.LOG2_BUCKETS``. Merge snapshots from several
    processes with ``telemetry.merge_lathist`` (exact — same bounds
    everywhere). None when the native plane isn't loaded."""
    try:
        from torchft_tpu import _native

        return _native.lathist_snapshot()
    except Exception:  # noqa: BLE001 — degrade, don't raise
        return None


def _base_url(addr: str) -> str:
    # LighthouseServer.address() returns "http://host:port"; accept a bare
    # host:port too (the TORCHFT_LIGHTHOUSE env convention).
    if "://" not in addr:
        addr = "http://" + addr
    return addr.rstrip("/")


def poll_lighthouse(addr: str, timeout: float = 2.0) -> Optional[Dict[str, Any]]:
    """Fetch the lighthouse's ``/status.json`` native counters
    (quorum_id, members + per-member step/plane, evictions_total,
    flush_requests_total, recent evictions). Returns None when the
    lighthouse is unreachable — observability must degrade, not raise."""
    try:
        with urllib.request.urlopen(
            f"{_base_url(addr)}/status.json", timeout=timeout
        ) as resp:
            return json.loads(resp.read().decode())
    except Exception:  # noqa: BLE001 — any failure means "no native stats"
        return None


def scrape_lighthouse_metrics(addr: str, timeout: float = 2.0) -> str:
    """Fetch the lighthouse's raw Prometheus ``/metrics`` text (the
    ``torchft_*`` family). Empty string when unreachable."""
    try:
        with urllib.request.urlopen(
            f"{_base_url(addr)}/metrics", timeout=timeout
        ) as resp:
            return resp.read().decode()
    except Exception:  # noqa: BLE001
        return ""


def poll_cluster(addr: str, timeout: float = 2.0) -> Optional[Dict[str, Any]]:
    """Fetch the lighthouse's ``/cluster.json`` aggregation: per-replica
    last report age, step, stuck flag, heal recency and counters digest
    (each replica's ``telemetry.summary()``, piggybacked on its quorum
    traffic). None when unreachable."""
    try:
        with urllib.request.urlopen(
            f"{_base_url(addr)}/cluster.json", timeout=timeout
        ) as resp:
            return json.loads(resp.read().decode())
    except Exception:  # noqa: BLE001 — degrade, don't raise
        return None


def fetch_merged_trace(
    addr: str, path: Optional[str] = None, timeout: float = 5.0
) -> Optional[Dict[str, Any]]:
    """Fetch the lighthouse's merged Chrome trace (``GET /trace``) — every
    replica's recent spans on one timeline. With ``path``, also write the
    raw JSON to disk ready to open in Perfetto. None when unreachable."""
    try:
        with urllib.request.urlopen(
            f"{_base_url(addr)}/trace", timeout=timeout
        ) as resp:
            raw = resp.read()
    except Exception:  # noqa: BLE001
        return None
    if path:
        try:
            with open(path, "wb") as f:
                f.write(raw)
        except OSError:
            pass
    try:
        return json.loads(raw.decode())
    except ValueError:
        return None
