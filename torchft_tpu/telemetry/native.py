"""Bridge to the C++ coordination layer's built-in counters.

The native lighthouse (native/coord.cc) already serves ``/status.json``
and a Prometheus ``/metrics`` page on its dashboard port — quorum_id,
participant steps, evictions_total, flush_requests_total, heartbeat ages.
Those counters live in the C++ process (possibly a different box), so the
Python registry can't own them; instead this module polls them over the
existing HTTP surface and either returns them as a dict
(:func:`poll_lighthouse`) or splices the raw exposition text into this
process's scrape output (:func:`scrape_lighthouse_metrics`), so one
Prometheus target can carry both layers.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any, Dict, Optional

__all__ = ["poll_lighthouse", "scrape_lighthouse_metrics"]


def _base_url(addr: str) -> str:
    # LighthouseServer.address() returns "http://host:port"; accept a bare
    # host:port too (the TORCHFT_LIGHTHOUSE env convention).
    if "://" not in addr:
        addr = "http://" + addr
    return addr.rstrip("/")


def poll_lighthouse(addr: str, timeout: float = 2.0) -> Optional[Dict[str, Any]]:
    """Fetch the lighthouse's ``/status.json`` native counters
    (quorum_id, members + per-member step/plane, evictions_total,
    flush_requests_total, recent evictions). Returns None when the
    lighthouse is unreachable — observability must degrade, not raise."""
    try:
        with urllib.request.urlopen(
            f"{_base_url(addr)}/status.json", timeout=timeout
        ) as resp:
            return json.loads(resp.read().decode())
    except Exception:  # noqa: BLE001 — any failure means "no native stats"
        return None


def scrape_lighthouse_metrics(addr: str, timeout: float = 2.0) -> str:
    """Fetch the lighthouse's raw Prometheus ``/metrics`` text (the
    ``torchft_*`` family). Empty string when unreachable."""
    try:
        with urllib.request.urlopen(
            f"{_base_url(addr)}/metrics", timeout=timeout
        ) as resp:
            return resp.read().decode()
    except Exception:  # noqa: BLE001
        return ""
