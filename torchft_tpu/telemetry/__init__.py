"""Unified telemetry for the FT runtime: metrics + FT event trail.

One process-wide :class:`~torchft_tpu.telemetry.registry.MetricsRegistry`
(``REGISTRY``) and one process-wide FT event trail (``EVENTS``), fed by
instrumentation in the Manager, coordination clients, collectives backends,
checkpoint transports and the futures deadline machinery. Every catalog
family is registered at import, and closed label sets (role, outcome,
kind, result) are pre-seeded so those series exist zero-valued from
process start; open-ended labels (plane, transport, event) appear on
first observation. Exposed three ways:

* ``GET /metrics`` on every checkpoint HTTP server
  (:class:`~torchft_tpu.checkpointing.http_transport.HTTPTransport`) —
  Prometheus text format, scrape the trainer directly;
* the native lighthouse's own ``/metrics`` (C++ counters; see
  :mod:`torchft_tpu.telemetry.native` to poll them from Python);
* :func:`dump` / :func:`summary` snapshots for benches and tests.

The full metric catalog and event-trail schema live in
``docs/observability.md``. All Python-side series share the ``tft_``
prefix; the C++ lighthouse keeps its pre-existing ``torchft_`` prefix, so
the two layers never collide on one scrape page.

Design constraints: stdlib-only, no import of jax/numpy (the coordination
layer must stay importable on lighthouse-only hosts), and every helper is
exception-free on the hot path — observability must never fail a step.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from torchft_tpu.telemetry.anatomy import (
    LEDGER,
    LOG2_BUCKETS,
    PHASES,
    StepLedger,
    merge_lathist,
)
from torchft_tpu.telemetry.blackbox import (
    BLACKBOX,
    BlackBox,
    read_blackbox,
    read_native_blackbox,
)
from torchft_tpu.telemetry.events import (
    CANONICAL_EVENTS,
    ENV_TRAIL_PATH,
    EventTrail,
    read_trail,
)
from torchft_tpu.telemetry.flight import (
    FLIGHT,
    FlightRecorder,
    StepWatchdog,
    install_sigusr2,
)
from torchft_tpu.telemetry.registry import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from torchft_tpu.telemetry.tracing import TRACER, Span, Tracer, chrome_trace

__all__ = [
    "REGISTRY",
    "EVENTS",
    "TRACER",
    "FLIGHT",
    "BLACKBOX",
    "BlackBox",
    "read_blackbox",
    "read_native_blackbox",
    "LEDGER",
    "LOG2_BUCKETS",
    "PHASES",
    "StepLedger",
    "merge_lathist",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "EventTrail",
    "read_trail",
    "CANONICAL_EVENTS",
    "ENV_TRAIL_PATH",
    "Span",
    "Tracer",
    "chrome_trace",
    "FlightRecorder",
    "StepWatchdog",
    "install_sigusr2",
    "counter",
    "gauge",
    "histogram",
    "emit",
    "record_collective",
    "record_checkpoint",
    "render_prometheus",
    "dump",
    "summary",
    "reset",
]

REGISTRY = MetricsRegistry()
EVENTS = EventTrail()

# Byte-count buckets (allreduce payloads span 4-byte scalars to GB-scale
# checkpoint buffers).
BYTE_BUCKETS = tuple(float(1 << s) for s in range(10, 34, 2))  # 1KiB..8GiB

# ---------------------------------------------------------------------------
# Metric catalog — pre-registered so /metrics always exposes the full
# schema (zero-valued series beat absent ones: dashboards and the
# acceptance scrape can rely on the names before the first observation).
# ---------------------------------------------------------------------------

# quorum / membership
QUORUM_LATENCY = REGISTRY.histogram(
    "tft_quorum_latency_seconds",
    "Latency of the mgr.quorum RPC (start_quorum to quorum delivery)",
)
QUORUMS_TOTAL = REGISTRY.counter(
    "tft_quorums_total", "Completed quorum RPCs"
)
QUORUM_RECONFIGURES = REGISTRY.counter(
    "tft_quorum_reconfigures_total",
    "Quorum-id changes (data-plane re-rendezvous events)",
)
MEMBERSHIP_CHANGES = REGISTRY.counter(
    "tft_membership_changes_total",
    "Quorums whose participant set differed from the previous one",
)
PARTICIPANTS = REGISTRY.gauge(
    "tft_participants", "Replica groups participating in the current step"
)

# step / commit
STEP_DURATION = REGISTRY.histogram(
    "tft_step_duration_seconds",
    "Committed-step wall-clock by kind (steady, quorum-reconfigure, heal)",
    labelnames=("kind",),
)
COMMITS_TOTAL = REGISTRY.counter(
    "tft_commits_total",
    "should_commit outcomes by result",
    labelnames=("outcome",),
)
COMMIT_BARRIER = REGISTRY.histogram(
    "tft_commit_barrier_seconds",
    "should_commit wall-clock (pending-work drain + vote RPC)",
)
CURRENT_STEP = REGISTRY.gauge(
    "tft_current_step", "Committed step counter of this replica group"
)
COMMIT_PIPELINE_ROLLBACKS = REGISTRY.counter(
    "tft_commit_pipeline_rollbacks_total",
    "Speculative optimizer updates rolled back because a pipelined "
    "commit vote resolved to veto (commit_pipeline mode only)",
)

# heal / recovery
HEALS_TOTAL = REGISTRY.counter(
    "tft_heals_total",
    "Live checkpoint recoveries by role (recv = this group healed, "
    "send = this group served a healing peer)",
    labelnames=("role",),
)
HEAL_DURATION = REGISTRY.histogram(
    "tft_heal_duration_seconds",
    "Wall-clock of a full heal (metadata fetch + checkpoint transfer + "
    "staging) on the healing side",
)
HEAL_STAGE_SECONDS = REGISTRY.counter(
    "tft_heal_stage_seconds_total",
    "Cumulative wall-clock inside the heal data path, by sub-stage "
    "(meta / recv / decode / device_put — docs/heal_plane.md)",
    labelnames=("stage",),
)
PEER_DEATHS = REGISTRY.counter(
    "tft_peer_deaths_total",
    "Dead-peer detections: death-watch socket EOF or a failed op naming "
    "the peer (deduplicated per victim per epoch)",
)
EVICTIONS_REPORTED = REGISTRY.counter(
    "tft_evictions_reported_total",
    "Eviction reports filed with the lighthouse, by result",
    labelnames=("result",),
)

# collectives / data plane
ALLREDUCE_BYTES = REGISTRY.counter(
    "tft_allreduce_bytes_total",
    "Payload bytes entering cross-group allreduce, by data plane",
    labelnames=("plane",),
)
ALLREDUCE_LATENCY = REGISTRY.histogram(
    "tft_allreduce_latency_seconds",
    "Cross-group allreduce op latency, by data plane",
    labelnames=("plane",),
)
COLLECTIVE_OPS = REGISTRY.counter(
    "tft_collective_ops_total",
    "Cross-group collective ops issued, by op and data plane",
    labelnames=("op", "plane"),
)
WIRE_STAGE_SECONDS = REGISTRY.counter(
    "tft_wire_stage_seconds_total",
    "Cumulative wall-clock inside the cross-group wire plane, by stage "
    "(host_copy / quantize / wire / dequant_reduce — docs/wire_plane.md)",
    labelnames=("stage",),
)

# checkpoint transfers
CHECKPOINT_BYTES = REGISTRY.counter(
    "tft_checkpoint_bytes_total",
    "Checkpoint payload bytes moved, by direction and transport",
    labelnames=("direction", "transport"),
)
CHECKPOINT_SECONDS = REGISTRY.histogram(
    "tft_checkpoint_transfer_seconds",
    "Checkpoint stage/transfer wall-clock, by phase and transport",
    labelnames=("phase", "transport"),
)

# futures / deadlines
FUTURE_TIMEOUTS = REGISTRY.counter(
    "tft_future_timeouts_total",
    "Futures failed by the deadline manager",
)
FUTURE_CANCELS = REGISTRY.counter(
    "tft_future_cancels_total",
    "Collective ops cancelled by reconfigure before running",
)

# event trail mirror
FT_EVENTS_TOTAL = REGISTRY.counter(
    "tft_ft_events_total",
    "FT event-trail records emitted, by event kind",
    labelnames=("event",),
)

# tracing / flight recorder / watchdog
TRACE_SPANS = REGISTRY.counter(
    "tft_trace_spans_total",
    "Distributed trace spans recorded, by span name",
    labelnames=("span",),
)
WATCHDOG_STALLS = REGISTRY.counter(
    "tft_watchdog_stalls_total",
    "Step-watchdog firings (a step exceeded the p99-derived threshold)",
)
FLIGHT_DUMPS = REGISTRY.counter(
    "tft_flight_dumps_total",
    "Collective flight-recorder dumps written, by trigger reason",
    labelnames=("reason",),
)

# fault-injection plane (torchft_tpu.faultinject): every fired scheduled
# injection is counted here AND emitted as a fault_injected trail event,
# so a chaos run's evidence is collected without extra wiring
FAULTS_INJECTED = REGISTRY.counter(
    "tft_faults_injected_total",
    "Scheduled fault injections fired, by site and action",
    labelnames=("site", "action"),
)

# step-anatomy ledger (telemetry/anatomy.py): per-step wall clock
# decomposed into named phases on the fixed log2 bucket grid shared with
# the native plane's latency histograms (native/lathist.h), so cross-
# plane/process merges are exact
STEP_PHASE_SECONDS = REGISTRY.histogram(
    "tft_step_phase_seconds",
    "Per-step seconds spent in each anatomy phase (compute / host_copy / "
    "quantize / wire / dequant_reduce / quorum_wait / commit_barrier / "
    "heal / telemetry / idle — docs/observability.md 'Step anatomy')",
    labelnames=("phase",),
    buckets=LOG2_BUCKETS,
)
STEP_WALL_SECONDS = REGISTRY.histogram(
    "tft_step_wall_seconds",
    "Per-step wall clock as the anatomy ledger measures it (tick to tick)",
    buckets=LOG2_BUCKETS,
)
STEP_LOCAL_SECONDS = REGISTRY.histogram(
    "tft_step_local_seconds",
    "Per-step LOCAL time: wall minus the peer-wait phases (wire, "
    "quorum_wait, commit_barrier, heal) — the straggler-discriminating "
    "signal piggybacked to the lighthouse",
    buckets=LOG2_BUCKETS,
)

# self-metering (ISSUE 16): bytes the telemetry plane itself moves, per
# channel. `piggyback` = delta/JSON blobs attached to quorum RPCs,
# `spans` = chrome-trace fragments riding the same RPC; the lighthouse
# meters its own `scrape` channel (HTTP bodies served) as the native
# torchft_telemetry_bytes_total counterpart. The budget gate
# (benchmarks/telemetry_overhead.py) keys off the step-rate delta, but
# this counter is what tells you WHERE an overhead regression lives.
TELEMETRY_BYTES = REGISTRY.counter(
    "tft_telemetry_bytes_total",
    "Bytes moved by the telemetry plane itself, by channel "
    "(piggyback / spans)",
    labelnames=("channel",),
)

# divergence sentinel (ISSUE 10): cross-group post-reduce digest
# mismatches latched by the lighthouse's (epoch, step) cohort compare,
# observed replica-side on the should_commit reply — the corrupt-commit
# failure mode surfaced at the commit boundary instead of at the nan
DIVERGENCE_TOTAL = REGISTRY.counter(
    "tft_divergence_total",
    "Commit-time state-digest divergence latches observed by this "
    "replica (the lighthouse's cohort compare disagreed — see "
    "docs/observability.md 'Divergence sentinel')",
)

# fleet time machine (ISSUE 11): per-commit critical-path attribution
# (telemetry/critical_path.py) and the perf-regression sentinel
# (telemetry/regression.py) over the retained time series
CRITICAL_PATH_SECONDS = REGISTRY.counter(
    "tft_critical_path_seconds_total",
    "Blamed seconds per (replica, phase): for each committed step, the "
    "excess local time of the step's gating replica over the fleet "
    "median, split across its non-barrier anatomy phases — see "
    "docs/observability.md 'Critical path'",
    labelnames=("replica", "phase"),
)
CRITICAL_PATH_WHATIF = REGISTRY.gauge(
    "tft_critical_path_whatif_steps_per_sec",
    "What-if fleet throughput: steps/s if every step's gating replica "
    "had run at the fleet median local time (Coz-style causal estimate)",
)
PERF_REGRESSION_TOTAL = REGISTRY.counter(
    "tft_perf_regression_total",
    "Page-Hinkley level-shift latches over the retained time series, by "
    "(replica, series) — the threshold-free whole-fleet-drift detector "
    "(docs/observability.md 'Perf regression')",
    labelnames=("replica", "series"),
)

# diagnosis plane (ISSUE 12): always-on profiler samples (both planes)
# and latch-triggered deep-capture bundles (telemetry/diagnosis.py)
PROF_SAMPLES = REGISTRY.counter(
    "tft_prof_samples_total",
    "Always-on profiler samples aggregated, by plane (py = the "
    "sys._current_frames thread sampler, native = the SIGPROF sampler "
    "over the GIL-free planes; native counts fold in on poll — see "
    "docs/observability.md 'Profiling & diagnosis bundles')",
    labelnames=("plane",),
)
DIAGNOSIS_BUNDLES = REGISTRY.counter(
    "tft_diagnosis_bundles_total",
    "Latch-triggered diagnosis bundles written to TORCHFT_DIAG_DIR, by "
    "trigger event",
    labelnames=("trigger",),
)

# SLO / straggler plane (telemetry/slo.py)
SLO_BREACH_TOTAL = REGISTRY.counter(
    "tft_slo_breach_total",
    "Burn-rate SLO breaches latched, by SLO (step_time / rejoin_commit)",
    labelnames=("slo",),
)
STRAGGLER_DETECTED = REGISTRY.counter(
    "tft_straggler_detected_total",
    "Straggler latches by the fleet detector, by replica group",
    labelnames=("group",),
)
STRAGGLERS = REGISTRY.gauge(
    "tft_stragglers", "Replica groups currently latched as stragglers"
)

# Pre-create the CLOSED label sets so their series exist (zero-valued)
# from process start: dashboards and absent-series alerts can then tell
# "healthy, zero heals" from "trainer not scraped". Open-ended label sets
# (plane, transport, event) appear on first observation.
for _role in ("recv", "send"):
    HEALS_TOTAL.labels(role=_role)
for _outcome in ("committed", "aborted"):
    COMMITS_TOTAL.labels(outcome=_outcome)
for _kind in ("steady", "quorum", "heal"):
    STEP_DURATION.labels(kind=_kind)
for _result in ("evicted", "rejected", "failed"):
    EVICTIONS_REPORTED.labels(result=_result)
for _reason in ("signal", "deadline", "watchdog", "manual"):
    FLIGHT_DUMPS.labels(reason=_reason)
for _stage in ("host_copy", "quantize", "wire", "dequant_reduce"):
    WIRE_STAGE_SECONDS.labels(stage=_stage)
for _stage in ("meta", "recv", "decode", "device_put"):
    HEAL_STAGE_SECONDS.labels(stage=_stage)
for _phase in PHASES:
    STEP_PHASE_SECONDS.labels(phase=_phase)
for _slo in ("step_time", "rejoin_commit"):
    SLO_BREACH_TOTAL.labels(slo=_slo)
for _plane in ("py", "native"):
    PROF_SAMPLES.labels(plane=_plane)
for _channel in ("piggyback", "spans"):
    TELEMETRY_BYTES.labels(channel=_channel)
del (
    _role,
    _outcome,
    _kind,
    _result,
    _reason,
    _stage,
    _phase,
    _slo,
    _plane,
    _channel,
)


# ---------------------------------------------------------------------------
# convenience API
# ---------------------------------------------------------------------------


def counter(name: str, help: str = "", labelnames=()) -> Counter:
    """Get-or-create a counter on the process registry."""
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames=()) -> Gauge:
    """Get-or-create a gauge on the process registry."""
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames=(), buckets=DEFAULT_BUCKETS):
    """Get-or-create a histogram on the process registry."""
    return REGISTRY.histogram(name, help, labelnames, buckets)


def emit(event: str, **fields: Any) -> Dict[str, Any]:
    """Append one record to the process FT event trail."""
    return EVENTS.emit(event, **fields)


def record_collective(
    op: str, nbytes: int, seconds: float, plane: str = "", count_op: bool = True
) -> None:
    """Account one collective op: count it, and for allreduce also record
    bytes + latency (the hot-path series the perf PRs gate on). Pass
    ``count_op=False`` when the op was already counted at submission —
    ops are counted when ISSUED (uniform across kinds, cancellations
    included) while bytes/latency are recorded at completion."""
    if count_op:
        COLLECTIVE_OPS.labels(op=op, plane=plane).inc()
    if op == "allreduce":
        ALLREDUCE_BYTES.labels(plane=plane).inc(nbytes)
        ALLREDUCE_LATENCY.labels(plane=plane).observe(seconds)


def record_checkpoint(
    phase: str, nbytes: int, seconds: float, transport: str
) -> None:
    """Account one checkpoint stage/transfer (phase: stage | send | recv)."""
    CHECKPOINT_BYTES.labels(direction=phase, transport=transport).inc(nbytes)
    CHECKPOINT_SECONDS.labels(phase=phase, transport=transport).observe(seconds)


def render_prometheus(lighthouse_addr: Optional[str] = None) -> str:
    """Prometheus text exposition of the process registry; with
    ``lighthouse_addr``, the native lighthouse's ``torchft_*`` exposition
    is appended so one scrape carries both layers."""
    text = REGISTRY.render()
    if lighthouse_addr:
        from torchft_tpu.telemetry.native import scrape_lighthouse_metrics

        native_text = scrape_lighthouse_metrics(lighthouse_addr)
        if native_text:
            text = text + native_text
    return text


def dump(lighthouse_addr: Optional[str] = None) -> Dict[str, Any]:
    """JSON-serializable snapshot: every metric family, the recent event
    ring, and (optionally) the native lighthouse's /status.json counters."""
    out: Dict[str, Any] = {
        "metrics": REGISTRY.dump(),
        "events": EVENTS.recent(),
    }
    if lighthouse_addr:
        from torchft_tpu.telemetry.native import poll_lighthouse

        out["lighthouse"] = poll_lighthouse(lighthouse_addr)
    return out


def summary() -> Dict[str, Any]:
    """Compact FT/perf digest for bench rows: one flat dict instead of the
    full exposition (quorum count, heal count, allreduce traffic, and a
    step-duration histogram summary by kind)."""
    step: Dict[str, Any] = {}
    for (kind,), child in STEP_DURATION._snapshot_children():
        if not child.count:
            continue
        step[kind] = {
            "count": child.count,
            "sum_s": round(child.sum, 4),
            "p50_s": round(child.quantile(0.5) or 0.0, 4),
            "p99_s": round(child.quantile(0.99) or 0.0, 4),
        }
    allreduce_bytes = sum(
        child.value for _v, child in ALLREDUCE_BYTES._snapshot_children()
    )
    allreduce_ops = sum(
        child.count for _v, child in ALLREDUCE_LATENCY._snapshot_children()
    )
    commits: Dict[str, float] = {
        values[0]: child.value
        for values, child in COMMITS_TOTAL._snapshot_children()
    }
    return {
        "quorums": int(QUORUMS_TOTAL.value),
        "quorum_reconfigures": int(QUORUM_RECONFIGURES.value),
        "quorum_latency_p50_s": round(QUORUM_LATENCY.quantile(0.5) or 0.0, 4),
        "heals_recv": int(HEALS_TOTAL.labels(role="recv").value),
        "heals_send": int(HEALS_TOTAL.labels(role="send").value),
        "peer_deaths": int(PEER_DEATHS.value),
        "allreduce_bytes": int(allreduce_bytes),
        "allreduce_ops": int(allreduce_ops),
        "commits": {k: int(v) for k, v in commits.items()},
        "future_timeouts": int(FUTURE_TIMEOUTS.value),
        "step_duration": step,
    }


def reset() -> None:
    """Zero every metric in place and empty the event/span/flight rings
    and the step-anatomy ledger (tests)."""
    REGISTRY.reset_values()
    EVENTS.clear()
    TRACER.clear()
    FLIGHT.clear()
    LEDGER.reset()
