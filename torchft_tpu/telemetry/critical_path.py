"""Per-commit critical-path attribution — which replica's which phase
gated the fleet, and what fixing it would buy (ISSUE 11).

In a synchronous fleet every committed step completes when its SLOWEST
group finishes local work; everyone else parks in barrier phases
(``anatomy.BARRIER_PHASES``). The straggler detector (PR 8) can *name* a
persistently slow group, but it cannot answer the operator's next two
questions: **how much** of the fleet's time does that group cost, and
**which phase** of its step is the problem. This module answers both,
Coz-style ("what-if" causal attribution, PAPERS.md): for each committed
step it takes the fleet's per-replica anatomy rows (published per step on
the time-series piggyback — ``telemetry/timeseries.py``), finds the
gating replica (largest LOCAL time), charges the step's *excess* —
gating local minus the others' median local, i.e. the seconds the rest of
the fleet provably waited — to that replica, and splits the charge across
its non-barrier phases in proportion to their own excess over the fleet
median. Accumulated blame lands in
``tft_critical_path_seconds_total{replica,phase}`` and the
:meth:`CriticalPathAttributor.report` JSON (served at
``GET /critical_path.json`` on every checkpoint HTTP server), alongside
the **what-if estimate**: fleet steps/s if the gating group had run at
the fleet median — the number that turns a straggler latch into a
prioritized action ("fixing group 1's compute phase recovers 31% step
rate").

Deliberately threshold-free and stateless per step: attribution is pure
arithmetic over the step's rows, so it composes with (not duplicates)
the SLO / straggler / regression detectors.
"""

from __future__ import annotations

import threading
from statistics import median
from typing import Any, Dict, List, Optional, Tuple

from torchft_tpu.telemetry.anatomy import BARRIER_PHASES

__all__ = [
    "CriticalPathAttributor",
    "CriticalPathMonitor",
    "attribute_step",
    "REPORTER",
    "set_reporter",
    "report_json",
]


def attribute_step(
    rows: Dict[str, Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """Attribute ONE committed step. ``rows`` maps replica →
    ``{"wall_s", "local_s", "phases": {phase: seconds}}`` (the per-step
    values each replica published). Returns None when fewer than two
    replicas reported (nothing gates anything in a fleet of one);
    otherwise::

        {"gating": replica, "phase": phase, "blame_s": s,
         "phase_blame": {phase: s}, "wall_s": fleet wall,
         "whatif_wall_s": wall minus blame}

    ``blame_s`` is the gating replica's local time minus the OTHERS'
    median local time (leave-one-out, same reasoning as the straggler
    baseline: in a small fleet the straggler's own sample drags a plain
    median toward itself), clamped at 0 — the seconds the fleet would
    have saved had the gater run at the median."""
    live = {
        r: row
        for r, row in rows.items()
        if isinstance(row, dict) and row.get("local_s") is not None
    }
    if len(live) < 2:
        return None
    locals_ = {r: float(row["local_s"]) for r, row in live.items()}
    gating = max(locals_, key=locals_.get)
    others = [v for r, v in locals_.items() if r != gating]
    baseline = median(others)
    blame = max(0.0, locals_[gating] - baseline)
    # fleet wall: the step took as long as the slowest view of it
    wall = max(float(row.get("wall_s") or 0.0) for row in live.values())

    # split the blame across the gater's NON-barrier phases by their own
    # excess over the fleet median of that phase — barrier phases are
    # waiting-for-peers and can never be a cause, only a symptom
    g_phases: Dict[str, float] = {
        p: float(s)
        for p, s in (live[gating].get("phases") or {}).items()
        if p not in BARRIER_PHASES and s and s > 0
    }
    excess: Dict[str, float] = {}
    for p, s in g_phases.items():
        peer_vals = [
            float((live[r].get("phases") or {}).get(p, 0.0))
            for r in live
            if r != gating
        ]
        excess[p] = max(0.0, s - median(peer_vals)) if peer_vals else s
    total_excess = sum(excess.values())
    phase_blame: Dict[str, float] = {}
    if blame > 0:
        if total_excess > 0:
            for p, e in excess.items():
                if e > 0:
                    phase_blame[p] = blame * e / total_excess
        elif g_phases:
            # no phase stands out vs the fleet (e.g. uniformly slower
            # box): charge the gater's largest phase so the blame is
            # still actionable rather than dropped
            p = max(g_phases, key=g_phases.get)
            phase_blame[p] = blame
        else:
            phase_blame["idle"] = blame
    top_phase = (
        max(phase_blame, key=phase_blame.get) if phase_blame else None
    )
    return {
        "gating": gating,
        "phase": top_phase,
        "blame_s": blame,
        "phase_blame": phase_blame,
        "wall_s": wall,
        "whatif_wall_s": max(baseline, wall - blame),
    }


class CriticalPathAttributor:
    """Accumulates per-step attributions into the per-(replica, phase)
    blamed-seconds ledger and the what-if throughput estimate.
    Thread-safe (monitor thread writes, HTTP route reads)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._blame: Dict[Tuple[str, str], float] = {}
        self._steps = 0
        self._sum_wall = 0.0
        self._sum_whatif = 0.0
        self._last: Optional[Dict[str, Any]] = None

    def observe_step(
        self, step: int, rows: Dict[str, Dict[str, Any]]
    ) -> Optional[Dict[str, Any]]:
        """Attribute one committed step's rows (see
        :func:`attribute_step`) and fold it into the ledger."""
        att = attribute_step(rows)
        if att is None:
            return None
        att["step"] = step
        with self._lock:
            self._steps += 1
            self._sum_wall += att["wall_s"]
            self._sum_whatif += att["whatif_wall_s"]
            for phase, s in att["phase_blame"].items():
                key = (att["gating"], phase)
                self._blame[key] = self._blame.get(key, 0.0) + s
            self._last = att
        if att["blame_s"] > 0:
            try:
                from torchft_tpu import telemetry

                for phase, s in att["phase_blame"].items():
                    telemetry.CRITICAL_PATH_SECONDS.labels(
                        replica=att["gating"], phase=phase
                    ).inc(s)
                whatif = self.report().get("whatif_steps_per_sec")
                if whatif:
                    telemetry.CRITICAL_PATH_WHATIF.set(whatif)
            except Exception:  # noqa: BLE001 — never fail the monitor
                pass
        return att

    def report(self) -> Dict[str, Any]:
        """The ``/critical_path.json`` document: blamed seconds per
        (replica, phase) with share-of-total fractions, measured vs
        what-if steps/s, and the most recent step's attribution."""
        with self._lock:
            blame = dict(self._blame)
            steps, sum_wall, sum_whatif = (
                self._steps, self._sum_wall, self._sum_whatif,
            )
            last = dict(self._last) if self._last else None
        total_blame = sum(blame.values())
        rows: List[Dict[str, Any]] = [
            {
                "replica": r,
                "phase": p,
                "blamed_s": round(s, 6),
                "share": round(s / total_blame, 4) if total_blame else 0.0,
            }
            for (r, p), s in sorted(
                blame.items(), key=lambda kv: -kv[1]
            )
        ]
        out: Dict[str, Any] = {
            "steps": steps,
            "blamed_total_s": round(total_blame, 6),
            "blame": rows,
            "measured_steps_per_sec": (
                round(steps / sum_wall, 4) if sum_wall > 0 else None
            ),
            "whatif_steps_per_sec": (
                round(steps / sum_whatif, 4) if sum_whatif > 0 else None
            ),
        }
        if last:
            out["last"] = {
                "step": last.get("step"),
                "gating": last["gating"],
                "phase": last["phase"],
                "blame_s": round(last["blame_s"], 6),
            }
        return out

    def blame_by_replica(self) -> Dict[str, float]:
        """Total blamed seconds per replica (the e2e acceptance reads
        this: the injected group must own >= 80% post-onset)."""
        with self._lock:
            out: Dict[str, float] = {}
            for (r, _p), s in self._blame.items():
                out[r] = out.get(r, 0.0) + s
            return out

    def reset(self) -> None:
        with self._lock:
            self._blame = {}
            self._steps = 0
            self._sum_wall = 0.0
            self._sum_whatif = 0.0
            self._last = None


# Process-global attributor serving GET /critical_path.json on the
# checkpoint HTTP server; a monitor installs itself here via set_reporter
# (None until one runs — the route then serves an empty report).
REPORTER: Optional[CriticalPathAttributor] = None
_REPORTER_LOCK = threading.Lock()


def set_reporter(attributor: Optional[CriticalPathAttributor]) -> None:
    global REPORTER
    with _REPORTER_LOCK:
        REPORTER = attributor


def report_json() -> str:
    """The /critical_path.json body (stable shape even with no monitor).

    The ``status`` field disambiguates the empty shapes explicitly
    (ISSUE 12 satellite — this ambiguity bit the PR 11 bring-up once):
    ``"no-monitor"`` = no CriticalPathMonitor ever installed itself here
    (the route is served but nothing feeds it — check
    ``TORCHFT_REGRESSION_MONITOR``), ``"empty"`` = a monitor is wired
    but no step has been attributed yet, ``"ok"`` = live data."""
    import json

    with _REPORTER_LOCK:
        rep = REPORTER
    if rep is None:
        return json.dumps(
            {"status": "no-monitor", "steps": 0, "blamed_total_s": 0.0,
             "blame": [], "measured_steps_per_sec": None,
             "whatif_steps_per_sec": None, "monitor": False}
        )
    out = rep.report()
    out["monitor"] = True
    out["status"] = "ok" if out.get("steps") else "empty"
    return json.dumps(out, separators=(",", ":"))


class CriticalPathMonitor:
    """Fleet-side consumer: polls the lighthouse's ``/timeseries.json``,
    reassembles per-step cross-replica rows from the ``wall_s`` /
    ``local_s`` / ``phase.*`` series, and feeds completed steps to a
    :class:`CriticalPathAttributor`. A step is *complete* once the
    fleet's max published step has moved ``slack`` steps past it (late
    reporters in a synchronous fleet are at most a step behind; a
    replica absent from a completed step — dead, healing — is simply
    absent from that step's rows). Run one per fleet, like the PR 8
    FleetMonitor (the faultmatrix runner hosts one; a Manager hosts one
    under ``TORCHFT_REGRESSION_MONITOR=1`` next to the regression
    sentinel — one history plane, one knob)."""

    def __init__(
        self,
        lighthouse_addr: str,
        attributor: Optional[CriticalPathAttributor] = None,
        slack: int = 2,
        pending_cap: int = 1024,
    ) -> None:
        self.addr = lighthouse_addr
        self.attributor = attributor or CriticalPathAttributor()
        self.slack = slack
        self.pending_cap = pending_cap
        self._cursor: Dict[Tuple[str, str], int] = {}
        # step -> replica -> partial row
        self._pending: Dict[int, Dict[str, Dict[str, Any]]] = {}
        set_reporter(self.attributor)

    def _fold(self, rid: str, name: str, step: int, value: float) -> None:
        row = self._pending.setdefault(step, {}).setdefault(
            rid, {"phases": {}}
        )
        if name == "wall_s":
            row["wall_s"] = value
        elif name == "local_s":
            row["local_s"] = value
        elif name.startswith("phase."):
            row["phases"][name[len("phase."):]] = value

    def poll_once(
        self, reply: Optional[Dict[str, Any]] = None
    ) -> List[Dict[str, Any]]:
        """One poll + attribution round; returns the step attributions
        finalized this round (also the testable core). Pass ``reply`` to
        reuse a fetch another consumer already paid for (see
        RegressionMonitor.poll_once)."""
        from torchft_tpu.telemetry.timeseries import (
            iter_new_samples,
            poll_timeseries,
        )

        if reply is None:
            reply = poll_timeseries(self.addr)
        if not reply:
            return []
        max_step = -1
        for rid, name, _epoch, step, value in iter_new_samples(
            reply, self._cursor
        ):
            if name == "wall_s" or name == "local_s" or name.startswith(
                "phase."
            ):
                self._fold(rid, name, step, value)
            max_step = max(max_step, step)
        out: List[Dict[str, Any]] = []
        for step in sorted(self._pending):
            if max_step >= 0 and step <= max_step - self.slack:
                att = self.attributor.observe_step(
                    step, self._pending.pop(step)
                )
                if att is not None:
                    out.append(att)
            elif len(self._pending) > self.pending_cap:
                self._pending.pop(step)
            else:
                break
        return out

    def drain(self) -> List[Dict[str, Any]]:
        """Finalize every pending step regardless of slack (end of a
        run: the fleet stopped publishing, nothing more is coming)."""
        out = []
        for step in sorted(self._pending):
            att = self.attributor.observe_step(
                step, self._pending.pop(step)
            )
            if att is not None:
                out.append(att)
        return out
