"""Burn-rate SLO evaluation + fleet straggler detection.

Two fleet-health detectors built on the step-anatomy ledger
(:mod:`torchft_tpu.telemetry.anatomy`):

**Burn-rate SLOs** (:class:`BurnRateSlo`, :class:`SloManager`) — the
classic multiwindow alert: an SLO says "fraction ``target`` of events must
be good" (step time under ``TORCHFT_SLO_STEP_S``; rejoin-to-commit under
``TORCHFT_SLO_REJOIN_S``). The *burn rate* of a window is the window's
bad-event fraction divided by the error budget ``1 - target``; a breach
latches only when BOTH the fast and the slow window burn past
``TORCHFT_SLO_BURN`` — the fast window gives detection latency, the slow
window suppresses blips. A breach emits the canonical ``slo_breach``
event, bumps ``tft_slo_breach_total{slo=...}``, and rides the telemetry
piggyback to the lighthouse dashboard as a red column next to the PR 2
STUCK flag. The latch clears (``slo_recovered``) once the fast window's
burn drops under 1.0 (spending slower than budget).

**Straggler detection** (:class:`StragglerDetector`, :class:`FleetMonitor`)
— per-group LOCAL step-time p50s (wall minus peer-wait phases; see
``anatomy.BARRIER_PHASES`` for why plain wall clock cannot discriminate in
a synchronous fleet) are piggybacked to the lighthouse and read back from
``/cluster.json``. A group whose p50 exceeds the leave-one-out fleet
median by ``TORCHFT_STRAGGLER_FACTOR`` for ``TORCHFT_STRAGGLER_K``
consecutive fresh observations latches ``straggler_detected`` (exactly
once per episode); it unlatches (``straggler_cleared``) after K
consecutive observations back under the hysteresis threshold. The
baseline is the median of the OTHER groups: in a small fleet the
straggler's own sample would drag a plain median toward itself, and for a
large fleet leave-one-out converges to the fleet median anyway.

Knob registry (all env, documented in docs/observability.md):

====================================  =====================================
``TORCHFT_SLO_STEP_S``                step-time SLO threshold (s); 0=off
``TORCHFT_SLO_REJOIN_S``              rejoin-to-commit SLO threshold (s);
                                      0=off
``TORCHFT_SLO_TARGET``                good-event objective (default 0.99)
``TORCHFT_SLO_FAST_S``                fast burn window (default 60)
``TORCHFT_SLO_SLOW_S``                slow burn window (default 600)
``TORCHFT_SLO_BURN``                  burn-rate latch threshold (default 2)
``TORCHFT_STRAGGLER_FACTOR``          p50-over-baseline latch factor
                                      (default 1.5)
``TORCHFT_STRAGGLER_K``               consecutive observations to latch /
                                      unlatch (default 5)
``TORCHFT_STRAGGLER_MONITOR``         1 = the Manager runs a FleetMonitor
                                      thread against its lighthouse
                                      (default 0)
``TORCHFT_STRAGGLER_POLL_S``          FleetMonitor poll interval (default 2)
====================================  =====================================
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from statistics import median
from typing import Any, Deque, Dict, List, Optional, Tuple

__all__ = [
    "BurnRateSlo",
    "SloManager",
    "StragglerDetector",
    "FleetMonitor",
]


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


class BurnRateSlo:
    """One SLO with fast/slow-window burn-rate evaluation (see module
    docstring for the math). Thread-compat: call from one thread (the
    Manager's main thread / a test)."""

    def __init__(
        self,
        name: str,
        threshold_s: float,
        target: Optional[float] = None,
        fast_s: Optional[float] = None,
        slow_s: Optional[float] = None,
        burn: Optional[float] = None,
        min_events: int = 1,
    ) -> None:
        self.name = name
        self.threshold_s = float(threshold_s)
        self.target = target if target is not None else _env_float(
            "TORCHFT_SLO_TARGET", 0.99
        )
        self.fast_s = fast_s if fast_s is not None else _env_float(
            "TORCHFT_SLO_FAST_S", 60.0
        )
        self.slow_s = slow_s if slow_s is not None else _env_float(
            "TORCHFT_SLO_SLOW_S", 600.0
        )
        self.burn = burn if burn is not None else _env_float(
            "TORCHFT_SLO_BURN", 2.0
        )
        # a breach needs at least this many events in the fast window —
        # rare-event SLOs (rejoin) use 1, the step SLO a small handful so
        # a cold start's first slow step can't alarm on a sample of one
        self.min_events = min_events
        self._events: Deque[Tuple[float, bool]] = deque()
        self.breached = False
        self.breaches = 0

    def _budget(self) -> float:
        return max(1e-9, 1.0 - self.target)

    def observe(self, value_s: float, now: Optional[float] = None) -> bool:
        """Record one event (good iff ``value_s <= threshold_s``) and
        re-evaluate; returns the latch state."""
        now = time.monotonic() if now is None else now
        self._events.append((now, value_s <= self.threshold_s))
        # prune past the slow window (nothing older can matter)
        horizon = now - self.slow_s
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()
        return self.evaluate(now)

    def _burn_rate(self, window_s: float, now: float) -> Optional[float]:
        """Bad fraction over the window divided by the error budget; None
        when the window holds fewer than ``min_events`` events."""
        lo = now - window_s
        total = bad = 0
        for ts, good in self._events:
            if ts < lo:
                continue
            total += 1
            if not good:
                bad += 1
        if total < self.min_events:
            return None
        return (bad / total) / self._budget()

    def evaluate(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        fast = self._burn_rate(self.fast_s, now)
        slow = self._burn_rate(self.slow_s, now)
        if (
            not self.breached
            and fast is not None
            and slow is not None
            and fast > self.burn
            and slow > self.burn
        ):
            self.breached = True
            self.breaches += 1
            try:
                from torchft_tpu import telemetry

                telemetry.SLO_BREACH_TOTAL.labels(slo=self.name).inc()
                telemetry.emit(
                    "slo_breach",
                    slo=self.name,
                    threshold_s=self.threshold_s,
                    fast_burn=round(fast, 3),
                    slow_burn=round(slow, 3),
                )
            except Exception:  # noqa: BLE001 — never fail the step path
                pass
        elif self.breached and fast is not None and fast < 1.0:
            # spending slower than budget again: clear the latch
            self.breached = False
            try:
                from torchft_tpu import telemetry

                telemetry.emit(
                    "slo_recovered", slo=self.name, fast_burn=round(fast, 3)
                )
            except Exception:  # noqa: BLE001
                pass
        return self.breached


class SloManager:
    """The Manager-side pair of SLOs (step time, rejoin-to-commit), both
    env-gated: a threshold of 0 disables the evaluator entirely, so the
    default deployment pays nothing."""

    def __init__(self) -> None:
        step_thr = _env_float("TORCHFT_SLO_STEP_S", 0.0)
        rejoin_thr = _env_float("TORCHFT_SLO_REJOIN_S", 0.0)
        self.step: Optional[BurnRateSlo] = (
            BurnRateSlo("step_time", step_thr, min_events=8)
            if step_thr > 0
            else None
        )
        self.rejoin: Optional[BurnRateSlo] = (
            BurnRateSlo("rejoin_commit", rejoin_thr, min_events=1)
            if rejoin_thr > 0
            else None
        )

    def observe_step(self, wall_s: float) -> None:
        if self.step is not None:
            self.step.observe(wall_s)

    def observe_rejoin(self, duration_s: float) -> None:
        if self.rejoin is not None:
            self.rejoin.observe(duration_s)

    def breached(self) -> bool:
        return bool(
            (self.step is not None and self.step.breached)
            or (self.rejoin is not None and self.rejoin.breached)
        )


class StragglerDetector:
    """Latched per-group straggler detection over local-step p50s.

    Call :meth:`update` with one fresh observation per group (the
    FleetMonitor only calls when the fleet's max step advanced, so
    repeated identical reports don't inflate the consecutive counters).
    Hysteresis: latch at ``factor``, unlatch at ``unlatch_factor``
    (default ``0.8 * factor``), both requiring K consecutive
    observations — a group oscillating around the threshold neither
    flaps nor silently clears."""

    def __init__(
        self,
        factor: Optional[float] = None,
        k: Optional[int] = None,
        unlatch_factor: Optional[float] = None,
        min_groups: int = 2,
    ) -> None:
        self.factor = factor if factor is not None else _env_float(
            "TORCHFT_STRAGGLER_FACTOR", 1.5
        )
        self.k = int(k if k is not None else _env_float(
            "TORCHFT_STRAGGLER_K", 5
        ))
        self.unlatch_factor = (
            unlatch_factor
            if unlatch_factor is not None
            else 0.8 * self.factor
        )
        self.min_groups = min_groups
        self._over: Dict[str, int] = {}
        self._under: Dict[str, int] = {}
        self._latched: Dict[str, bool] = {}

    def stragglers(self) -> List[str]:
        """Currently latched groups, sorted."""
        return sorted(g for g, v in self._latched.items() if v)

    def update(self, p50s: Dict[str, float]) -> List[Dict[str, Any]]:
        """One detection round over ``{group: local_step_p50_s}``; returns
        the events emitted (latch/clear records)."""
        events: List[Dict[str, Any]] = []
        live = {g: v for g, v in p50s.items() if v and v > 0}
        if len(live) < self.min_groups:
            # no detection round happened: every streak breaks ("K
            # consecutive" must mean consecutive detection rounds, never
            # K jittery samples separated by a fleet-too-small gap)
            self._over.clear()
            self._under.clear()
            return events
        # a group absent from this round (manager restart, no report yet)
        # breaks ITS streaks the same way; the latch itself persists —
        # absence is not evidence of recovery
        for group in list(self._over):
            if group not in live:
                self._over[group] = 0
        for group in list(self._under):
            if group not in live:
                self._under[group] = 0
        for group, p50 in live.items():
            others = [v for g, v in live.items() if g != group]
            baseline = median(others)
            if baseline <= 0:
                continue
            over = p50 > self.factor * baseline
            under = p50 < self.unlatch_factor * baseline
            if over:
                self._over[group] = self._over.get(group, 0) + 1
                self._under[group] = 0
            else:
                self._over[group] = 0
                if under:
                    self._under[group] = self._under.get(group, 0) + 1
                else:
                    self._under[group] = 0
            if not self._latched.get(group) and self._over[group] >= self.k:
                self._latched[group] = True
                ev = {
                    "group": group,
                    "p50_s": round(p50, 6),
                    "baseline_s": round(baseline, 6),
                    "factor": self.factor,
                }
                events.append({"event": "straggler_detected", **ev})
                try:
                    from torchft_tpu import telemetry

                    telemetry.STRAGGLER_DETECTED.labels(group=group).inc()
                    telemetry.STRAGGLERS.set(len(self.stragglers()))
                    telemetry.emit("straggler_detected", **ev)
                except Exception:  # noqa: BLE001
                    pass
            elif self._latched.get(group) and self._under[group] >= self.k:
                self._latched[group] = False
                ev = {
                    "group": group,
                    "p50_s": round(p50, 6),
                    "baseline_s": round(baseline, 6),
                }
                events.append({"event": "straggler_cleared", **ev})
                try:
                    from torchft_tpu import telemetry

                    telemetry.STRAGGLERS.set(len(self.stragglers()))
                    telemetry.emit("straggler_cleared", **ev)
                except Exception:  # noqa: BLE001
                    pass
        return events


class FleetMonitor:
    """Polls the lighthouse's ``/cluster.json`` aggregation and feeds the
    per-replica ``local_step_p50_s`` scalars into a
    :class:`StragglerDetector` — the fleet-side consumer of the anatomy
    piggyback. Run one per fleet (the faultmatrix runner runs one; a
    Manager starts one when ``TORCHFT_STRAGGLER_MONITOR=1``)."""

    def __init__(
        self,
        lighthouse_addr: str,
        detector: Optional[StragglerDetector] = None,
        poll_s: Optional[float] = None,
    ) -> None:
        self.addr = lighthouse_addr
        self.detector = detector or StragglerDetector()
        self.poll_s = poll_s if poll_s is not None else _env_float(
            "TORCHFT_STRAGGLER_POLL_S", 2.0
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # guarded-by: _lock
        self._max_step = -1
        self._lock = threading.Lock()

    def poll_once(self) -> List[Dict[str, Any]]:
        """One poll + detection round (also the testable core). Only runs
        the detector when the fleet's max reported step advanced, so a
        stalled scrape target can't inflate the consecutive counters."""
        from torchft_tpu.telemetry.native import poll_cluster

        cluster = poll_cluster(self.addr)
        if not cluster:
            return []
        replicas = cluster.get("replicas") or {}
        p50s: Dict[str, float] = {}
        max_step = -1
        for rid, rec in replicas.items():
            try:
                p50s[rid] = float(rec.get("local_step_p50_s") or 0.0)
                max_step = max(max_step, int(rec.get("step", -1)))
            except (TypeError, ValueError):
                continue
        with self._lock:
            if max_step <= self._max_step:
                return []
            self._max_step = max_step
        return self.detector.update(p50s)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — monitoring must not die
                pass

    def start(self) -> "FleetMonitor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="tft_fleet_monitor"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_s + 2.0)
            self._thread = None
