"""Collective flight recorder + step watchdog — hang forensics.

A silent multihost hang is the worst FT failure mode: every process is
alive, nothing errors, and the only observable fact is "step N never
committed". PyTorch distributed grew the NCCL flight recorder for exactly
this; here the analogue is a fixed-size ring of the last N cross-group
collective ops (op, plane, bytes, issue/complete wall timestamps,
status), recorded by both data-plane backends. When something wedges, the
per-rank dumps answer the two questions that localize a hang: *what was
the last op this rank completed* and *what is the first op it is stuck
in* — diffing those across ranks names the rank (and usually the op) that
stalled the ring.

Dumps are triggered three ways:

* **SIGUSR2** — operator-initiated (``kill -USR2 <pid>`` on a wedged
  worker); handler installed by the Manager (main thread only);
* **deadline expiry** — the futures timeout manager dumps when it fails
  a future (rate-limited);
* **step watchdog** — :class:`StepWatchdog` fires when the step a
  Manager armed exceeds ``TORCHFT_WATCHDOG_MULT`` × the steady-step p99
  (floor ``TORCHFT_WATCHDOG_MIN_S``), i.e. the step is an extreme outlier
  against this process's own recorded history.

Dump files are JSON at ``TORCHFT_FLIGHT_DIR`` (default: the system temp
dir), named ``tft_flight_<pid>_<seq>.json``. Stdlib-only; recording an op
is one lock + a few dict stores.
"""

from __future__ import annotations

import json
import os
import signal
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "FlightRecorder",
    "FLIGHT",
    "StepWatchdog",
    "install_sigusr2",
    "ENV_FLIGHT_DIR",
    "ENV_FLIGHT_SIZE",
]

ENV_FLIGHT_DIR = "TORCHFT_FLIGHT_DIR"
ENV_FLIGHT_SIZE = "TORCHFT_FLIGHT_SIZE"
ENV_WATCHDOG_MULT = "TORCHFT_WATCHDOG_MULT"
ENV_WATCHDOG_MIN_S = "TORCHFT_WATCHDOG_MIN_S"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


class FlightRecorder:
    """Fixed-size ring of collective-op records.

    ``record_issue`` returns a sequence id; ``record_complete(seq)`` marks
    it completed/failed if it is still in the ring (wraparound of a long
    ring while an op is in flight simply loses the record — acceptable,
    the recorder is forensic, not accounting)."""

    def __init__(self, size: Optional[int] = None) -> None:
        if size is None:
            try:
                size = max(16, int(os.environ.get(ENV_FLIGHT_SIZE, "256")))
            except ValueError:
                size = 256
        self._size = size
        self._lock = threading.Lock()
        self._ring: List[Optional[Dict[str, Any]]] = [None] * size
        self._seq = 0
        self._dump_seq = 0
        self._last_dump: Dict[str, float] = {}  # reason -> monotonic ts
        self.min_dump_interval_s = 5.0

    # -- producer side ---------------------------------------------------

    def record_issue(
        self,
        op: str,
        plane: str,
        nbytes: int = 0,
        tag: int = 0,
        rank: int = -1,
    ) -> int:
        rec = {
            "seq": 0,
            "op": op,
            "plane": plane,
            "bytes": int(nbytes),
            "tag": tag,
            "rank": rank,
            "issue_ts": time.time(),
            "complete_ts": None,
            "status": "issued",
        }
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring[self._seq % self._size] = rec
            seq = self._seq
        # crash-durable mirror: the in-memory ring dies with the process,
        # so the black box records every issue/complete — a SIGKILLed
        # worker's "last in-flight op" is recoverable from disk alone
        from torchft_tpu.telemetry.blackbox import BLACKBOX

        BLACKBOX.record(
            "op_issue", op=op, plane=plane, fseq=seq,
            bytes=int(nbytes), tag=tag, rank=rank,
        )
        return seq

    def record_complete(self, seq: int, error: Optional[BaseException] = None) -> None:
        with self._lock:
            rec = self._ring[seq % self._size]
            if rec is None or rec["seq"] != seq:
                return  # overwritten by wraparound
            rec["complete_ts"] = time.time()
            rec["status"] = "completed" if error is None else "failed"
            if error is not None:
                rec["error"] = repr(error)
        from torchft_tpu.telemetry.blackbox import BLACKBOX

        BLACKBOX.record(
            "op_complete", fseq=seq,
            status="completed" if error is None else "failed",
            **({"error": repr(error)} if error is not None else {}),
        )

    # -- consumer side ---------------------------------------------------

    def snapshot(self) -> List[Dict[str, Any]]:
        """Records oldest→newest (deep-enough copies for JSON dumping)."""
        with self._lock:
            recs = [dict(r) for r in self._ring if r is not None]
        recs.sort(key=lambda r: r["seq"])
        return recs

    @staticmethod
    def analyze(entries: List[Dict[str, Any]]) -> Dict[str, Any]:
        """The hang-localization digest: the newest completed op and the
        oldest still-issued one."""
        last_completed = None
        first_stuck = None
        for r in entries:
            if r["status"] == "completed":
                last_completed = r
            elif r["status"] == "issued" and first_stuck is None:
                first_stuck = r
        return {"last_completed": last_completed, "first_stuck": first_stuck}

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self._size

    # -- dumping ---------------------------------------------------------

    def dump_dir(self) -> str:
        return os.environ.get(ENV_FLIGHT_DIR) or tempfile.gettempdir()

    def dump(self, reason: str, force: bool = False) -> Optional[str]:
        """Write the ring to disk; returns the path (None when rate-limited
        or the write failed). ``force`` skips the per-reason rate limit
        (the SIGUSR2 path — an explicit operator ask always dumps)."""
        now = time.monotonic()
        with self._lock:
            if not force:
                last = self._last_dump.get(reason, 0.0)
                if now - last < self.min_dump_interval_s:
                    return None
            self._last_dump[reason] = now
            self._dump_seq += 1
            seq = self._dump_seq
        entries = self.snapshot()
        payload = {
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "host": _hostname(),
            "entries": entries,
            **self.analyze(entries),
        }
        # the step-anatomy ledger rides every dump (ISSUE 8 satellite:
        # one handler, one evidence dir — the SIGUSR2 / watchdog /
        # deadline dump now answers "where did the wedged step's time go"
        # next to "which op is stuck"), tagged-outlier digest included
        try:
            from torchft_tpu.telemetry.anatomy import LEDGER

            payload["anatomy"] = LEDGER.dump()
        except Exception:  # noqa: BLE001 — never fail the dump path
            pass
        # every live Python thread's stack rides along too (ISSUE 12
        # satellite: one handler, one evidence dir — a hung fleet used to
        # dump collective state but not WHERE each thread is parked,
        # which is the first question a wedge postmortem asks)
        try:
            payload["py_stacks"] = _thread_stacks()
        except Exception:  # noqa: BLE001
            pass
        path = os.path.join(
            self.dump_dir(), f"tft_flight_{os.getpid()}_{seq}.json"
        )
        try:
            with open(path, "w", encoding="utf-8") as f:
                json.dump(payload, f, default=str)
        except OSError:
            return None
        try:
            from torchft_tpu import telemetry

            telemetry.FLIGHT_DUMPS.labels(reason=reason).inc()
            telemetry.emit("flight_dump", reason=reason, path=path)
        except Exception:  # noqa: BLE001 — never fail the trigger path
            pass
        return path


def _hostname() -> str:
    import socket

    try:
        return socket.gethostname()
    except OSError:
        return "?"


def _thread_stacks() -> List[Dict[str, Any]]:
    """Every live Python thread's current stack (root-first
    ``file:line:function`` frames), named via threading.enumerate — the
    wedge-localization snapshot the SIGUSR2 / deadline / watchdog dumps
    carry."""
    import sys
    import traceback

    names = {
        t.ident: t.name for t in threading.enumerate() if t.ident is not None
    }
    out: List[Dict[str, Any]] = []
    for tid, frame in sys._current_frames().items():
        frames = [
            f"{fs.filename.rsplit('/', 1)[-1]}:{fs.lineno}:{fs.name}"
            for fs in traceback.extract_stack(frame)
        ]
        out.append(
            {
                "thread": names.get(tid, f"tid{tid}"),
                "tid": tid,
                "frames": frames,  # root-first
            }
        )
    return out


FLIGHT = FlightRecorder()

_SIGUSR2_INSTALLED = False
_SIGUSR2_LOCK = threading.Lock()


def install_sigusr2() -> bool:
    """Install the SIGUSR2 → flight dump handler (idempotent; main thread
    only — returns False when installation was impossible, e.g. called
    from a worker thread or a non-Unix platform)."""
    global _SIGUSR2_INSTALLED
    with _SIGUSR2_LOCK:
        if _SIGUSR2_INSTALLED:
            return True
        try:
            prev = signal.getsignal(signal.SIGUSR2)

            def _handler(signum, frame):  # noqa: ARG001
                # dump on a thread: json/file IO is not async-signal-safe
                # enough to run inline in an arbitrary interrupted frame
                threading.Thread(
                    target=FLIGHT.dump,
                    args=("signal",),
                    kwargs={"force": True},
                    daemon=True,
                    name="tft_flight_dump",
                ).start()
                if callable(prev) and prev not in (
                    signal.SIG_IGN,
                    signal.SIG_DFL,
                ):
                    prev(signum, frame)

            signal.signal(signal.SIGUSR2, _handler)
        except (ValueError, OSError, AttributeError):
            return False
        _SIGUSR2_INSTALLED = True
        return True


class StepWatchdog:
    """Per-Manager stall detector driven by the step-duration histogram.

    ``arm(step)`` at each ``start_quorum``; ``disarm()`` at the commit
    boundary. A monitor thread compares the armed step's elapsed wall time
    against ``mult × p99(steady step duration)`` (floor ``min_s``); past
    the threshold it fires ``on_stall`` once for that step, dumps the
    flight recorder, and latches :attr:`stalled` until the next disarm —
    the Manager piggybacks that flag to the lighthouse so the cluster
    dashboard shows a stuck-collective marker for the replica.

    Knobs (env): ``TORCHFT_WATCHDOG_MULT`` (default 10; <=0 disables) and
    ``TORCHFT_WATCHDOG_MIN_S`` (default 60)."""

    WARMUP_SAMPLES = 8

    def __init__(
        self,
        mult: Optional[float] = None,
        min_s: Optional[float] = None,
        on_stall: Optional[Callable[[int, float, float], None]] = None,
        recorder: Optional[FlightRecorder] = None,
    ) -> None:
        self.mult = mult if mult is not None else _env_float(ENV_WATCHDOG_MULT, 10.0)
        self.min_s = min_s if min_s is not None else _env_float(ENV_WATCHDOG_MIN_S, 60.0)
        self._on_stall = on_stall
        self._recorder = recorder or FLIGHT
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._armed_step: Optional[int] = None
        self._armed_at = 0.0
        self._fired_step: Optional[int] = None
        self._thread: Optional[threading.Thread] = None
        self._running = True
        self.stalled = False  # guarded-by: _cond
        self.stalls = 0

    @property
    def enabled(self) -> bool:
        return self.mult > 0

    def threshold_s(self) -> float:
        """Current stall threshold; the histogram p99 only engages after
        WARMUP_SAMPLES steady steps so cold starts never false-positive."""
        p99 = None
        try:
            from torchft_tpu import telemetry

            steady = telemetry.STEP_DURATION.labels(kind="steady")
            if steady.count >= self.WARMUP_SAMPLES:
                p99 = steady.quantile(0.99)
        except Exception:  # noqa: BLE001
            p99 = None
        if not p99:
            return self.min_s
        return max(self.min_s, self.mult * p99)

    def arm(self, step: int) -> None:
        if not self.enabled:
            return
        with self._cond:
            self._armed_step = step
            self._armed_at = time.monotonic()
            if self._fired_step != step:
                self.stalled = False
            if self._thread is None and self._running:
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="tft_step_watchdog"
                )
                self._thread.start()
            self._cond.notify()

    def disarm(self) -> None:
        with self._cond:
            self._armed_step = None
            self.stalled = False

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                if not self._running:
                    return
                if self._armed_step is None:
                    self._cond.wait(timeout=1.0)
                    continue
                step = self._armed_step
                elapsed = time.monotonic() - self._armed_at
            thr = self.threshold_s()
            if elapsed >= thr and step is not None:
                fire = False
                with self._cond:
                    if self._armed_step == step and self._fired_step != step:
                        self._fired_step = step
                        self.stalled = True
                        self.stalls += 1
                        fire = True
                if fire:
                    self._fire(step, elapsed, thr)
                wait_s = max(1.0, thr / 4)
            else:
                wait_s = min(max(0.05, thr - elapsed), max(1.0, thr / 4))
            with self._cond:
                if self._running:
                    self._cond.wait(timeout=wait_s)

    def _fire(self, step: int, elapsed: float, thr: float) -> None:
        try:
            from torchft_tpu import telemetry

            telemetry.WATCHDOG_STALLS.inc()
            telemetry.emit(
                "watchdog_stall",
                step=step,
                elapsed_s=round(elapsed, 3),
                threshold_s=round(thr, 3),
            )
        except Exception:  # noqa: BLE001
            pass
        try:
            self._recorder.dump("watchdog")
        except Exception:  # noqa: BLE001
            pass
        if self._on_stall is not None:
            try:
                self._on_stall(step, elapsed, thr)
            except Exception:  # noqa: BLE001
                pass
