"""Step-anatomy ledger — per-step wall-clock attribution into named phases.

The paper's per-step fault tolerance means every step pays a quorum, an
averaging collective and a commit vote; until ISSUE 8 only the wire plane's
four codec stages (PR 6) were attributable, and only as process-cumulative
totals. The ledger closes the lens: each step's wall clock is decomposed
into the phases

    compute / host_copy / quantize / wire / dequant_reduce /
    quorum_wait / commit_barrier / heal / idle

assembled from instrumentation that already existed piecemeal —
``collectives.record_wire_stage`` (now a thin shim over this ledger),
the Manager's quorum-wait/commit-barrier timing, ``StepTimer``'s
quorum/heal outlier tagging — plus explicit ``compute`` records from
``TrainStep``. ``idle`` is the residual, so the row always sums to the
measured wall clock **exactly** (the bench ``step_anatomy`` acceptance
reconciles p50 sums to within 5%, which the residual makes structural).

Two accounting views, one mechanism:

* **step rows** decompose the MAIN thread's wall clock: only records made
  on the main thread (or explicitly step-attributable, like the heal
  apply) enter the row — an op-thread socket pump overlaps the main
  thread and cannot be part of a wall-clock decomposition;
* **wire-stage totals** keep PR 6's semantics byte-for-byte: every
  ``record_wire_stage`` call (either thread) accumulates into the
  process-cumulative per-stage totals the crossgroup bench reads via
  ``collectives.wire_stage_snapshot`` — the shim's old private dict is
  gone; this ledger is the one source of truth.

The ledger also derives the **local step time** — wall minus the
peer-wait phases (``wire``/``quorum_wait``/``commit_barrier``/``heal``)
— whose rolling p50 is the straggler-discriminating signal: in a
synchronous fleet one slow group stretches *everyone's* wall clock, but
only the straggler's local time grows (the victims' extra time lands in
their barrier phases). That p50 is piggybacked to the lighthouse and fed
to :class:`torchft_tpu.telemetry.slo.StragglerDetector`.

Histograms use the fixed log2 bucket grid ``LOG2_BUCKETS`` (2^-20 s ..
2^6 s), the same bounds as the native plane's latency histograms
(``native/lathist.h``), so cross-process and cross-plane merges are exact
count additions — see :func:`merge_lathist`.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

__all__ = [
    "PHASES",
    "WIRE_STAGES",
    "BARRIER_PHASES",
    "LOG2_BUCKETS",
    "StepLedger",
    "LEDGER",
    "merge_lathist",
    "lathist_quantile",
]

# The named phases of one step's wall clock (docs/observability.md
# "Step anatomy"). `idle` is the residual — rows sum to wall by
# construction.
PHASES = (
    "compute",
    "host_copy",
    "quantize",
    "wire",
    "dequant_reduce",
    "quorum_wait",
    "commit_barrier",
    "heal",
    "telemetry",
    "idle",
)

# PR 6's wire-plane stage vocabulary (authoritative here since the shim
# moved; collectives.py re-exports it).
WIRE_STAGES = ("host_copy", "quantize", "wire", "dequant_reduce")

# Phases that absorb *peer* skew in a synchronous fleet: a slow group
# shows up in everyone ELSE's barrier phases, so excluding them from the
# local-time signal is what lets the straggler detector name the right
# group instead of flagging the whole fleet.
BARRIER_PHASES = ("wire", "quorum_wait", "commit_barrier", "heal")

# One bucket per binary order of magnitude, ~1 µs .. 64 s — identical to
# native/lathist.h's grid (_native.LATHIST_BOUNDS_S), so bucket counts
# from the Python and native planes merge exactly.
LOG2_BUCKETS = tuple(2.0 ** e for e in range(-20, 7))


def _lathist_sum_ns(h: Dict[str, Any]) -> int:
    # two on-the-wire shapes carry the same histogram: the ctypes
    # snapshot (sum_ns, exact integer) and the lighthouse /status.json
    # "latency" entries (sum_s, rendered seconds) — accept both so the
    # documented "merge anything on the fixed grid" contract holds
    if "sum_ns" in h:
        return int(h["sum_ns"])
    return int(round(float(h.get("sum_s", 0.0)) * 1e9))


def merge_lathist(
    a: Dict[str, Dict[str, Any]], b: Dict[str, Dict[str, Any]]
) -> Dict[str, Dict[str, Any]]:
    """Merge two native-latency histogram dicts — the
    ``_native.lathist_snapshot`` format or the lighthouse
    ``/status.json`` ``"latency"`` entries (``sum_s`` instead of
    ``sum_ns``). Exact by construction: every process records on the
    same fixed bucket grid, so the merge is elementwise integer
    addition — no re-binning, no precision loss (a ``sum_s`` input
    round-trips through its rendered seconds, still exact to the ns)."""
    out: Dict[str, Dict[str, Any]] = {}
    for op in set(a) | set(b):
        ha, hb = a.get(op), b.get(op)
        if ha is None or hb is None:
            src = ha or hb
            assert src is not None
            out[op] = {
                "counts": list(src["counts"]),
                "count": int(src["count"]),
                "sum_ns": _lathist_sum_ns(src),
            }
            continue
        if len(ha["counts"]) != len(hb["counts"]):
            raise ValueError(
                f"lathist merge: bucket count mismatch for {op} "
                f"({len(ha['counts'])} vs {len(hb['counts'])})"
            )
        out[op] = {
            "counts": [
                int(x) + int(y) for x, y in zip(ha["counts"], hb["counts"])
            ],
            "count": int(ha["count"]) + int(hb["count"]),
            "sum_ns": _lathist_sum_ns(ha) + _lathist_sum_ns(hb),
        }
    return out


def lathist_quantile(hist: Dict[str, Any], q: float) -> float:
    """Interpolated quantile of one native-latency histogram (the
    ``_native.lathist_snapshot`` / merged format) over the LOG2_BUCKETS
    grid; 0.0 when empty. Same estimate the C++ side serves in
    /status.json, so the two agree."""
    counts = [int(c) for c in hist["counts"]]
    total = sum(counts)
    if not total:
        return 0.0
    target = q * total
    acc = 0.0
    lo = 0.0
    for i, b in enumerate(LOG2_BUCKETS):
        nxt = acc + counts[i]
        if nxt >= target and counts[i]:
            frac = min(1.0, max(0.0, (target - acc) / counts[i]))
            return lo + (b - lo) * frac
        acc = nxt
        lo = b
    return LOG2_BUCKETS[-1]


class StepLedger:
    """Thread-safe per-step phase accounting (see module docstring).

    Producers call :meth:`record` as phases complete; the Manager calls
    :meth:`tick` at each commit boundary, which assembles the interval's
    records into one step row, computes the ``idle`` residual and the
    local (peer-wait-excluded) time, and feeds the per-phase histograms.
    """

    def __init__(self, window: int = 128) -> None:
        self._lock = threading.Lock()
        self._last: Optional[float] = None
        self._interval: Dict[str, float] = {}
        self._totals: Dict[str, float] = {}        # row-eligible cumulative
        self._wire_totals: Dict[str, float] = {}   # record_wire_stage view
        self._wire_marks: Dict[str, float] = {}    # wire_stage_snapshot(reset)
        self._heal_stages: Dict[str, float] = {}   # record_heal_stage view
        self._rows: Deque[Dict[str, Any]] = deque(maxlen=window)
        self.steps = 0
        self._timer = None  # profiling.StepTimer for the outlier digest

    # -- producer side ---------------------------------------------------

    def record(
        self, phase: str, seconds: float, wire_total: bool = False
    ) -> None:
        """Accumulate ``seconds`` into ``phase``.

        ``wire_total=True`` marks a ``record_wire_stage`` call: it always
        feeds the cumulative wire-stage totals (PR 6 bench semantics,
        either thread) and the ``tft_wire_stage_seconds_total`` mirror,
        but joins the current STEP ROW only when made on the main thread
        — an op-thread pump overlaps the main thread's wall clock and
        would break the row's sum-to-wall invariant."""
        if seconds <= 0.0:
            return
        on_main = threading.current_thread() is threading.main_thread()
        row_eligible = not wire_total or on_main
        with self._lock:
            if wire_total:
                self._wire_totals[phase] = (
                    self._wire_totals.get(phase, 0.0) + seconds
                )
            if row_eligible:
                self._interval[phase] = (
                    self._interval.get(phase, 0.0) + seconds
                )
                self._totals[phase] = self._totals.get(phase, 0.0) + seconds
        if wire_total:
            from torchft_tpu import telemetry

            telemetry.WIRE_STAGE_SECONDS.labels(stage=phase).inc(seconds)

    def record_heal_stage(self, stage: str, seconds: float) -> None:
        """Accumulate a heal sub-stage (``meta``/``recv``/``decode``/
        ``device_put`` — docs/heal_plane.md) into the cumulative heal-stage
        view. Heals are rare, mostly ride the quorum thread, and span step
        boundaries, so these do NOT enter step rows (the row's ``heal``
        phase stays the main-thread apply, PR 8 semantics) — they exist so
        a rejoin-to-commit regression is attributable to a stage instead
        of a single opaque ``heal_end`` duration."""
        if seconds <= 0.0:
            return
        with self._lock:
            self._heal_stages[stage] = (
                self._heal_stages.get(stage, 0.0) + seconds
            )
        try:
            from torchft_tpu import telemetry

            telemetry.HEAL_STAGE_SECONDS.labels(stage=stage).inc(seconds)
        except Exception:  # noqa: BLE001 — observability never fails a heal
            pass

    def heal_stage_snapshot(self) -> Dict[str, float]:
        """Process-cumulative seconds per heal sub-stage."""
        with self._lock:
            return {k: v for k, v in self._heal_stages.items() if v > 0.0}

    def attach_timer(self, timer: Any) -> None:
        """Attach the Manager's :class:`~torchft_tpu.profiling.StepTimer`
        so anatomy summaries/dumps carry its tagged-outlier digest (the
        quorum/heal outlier list PR 1 computed but never exported)."""
        self._timer = timer

    def tick(self, step: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Step boundary: assemble the interval's records into one row.

        Returns the row (None on the first call — no previous boundary to
        measure from). The row's phases sum to the measured wall clock
        exactly: ``idle`` is the residual (clamped at 0 when explicitly
        recorded phases overlap the boundary, e.g. a quorum-thread heal
        racing the tick)."""
        now = time.perf_counter()
        with self._lock:
            interval = self._interval
            self._interval = {}
            last = self._last
            self._last = now
            if last is None:
                return None
            self.steps += 1
        wall = now - last
        attributed = sum(interval.values())
        interval["idle"] = max(0.0, wall - attributed)
        local = max(
            0.0,
            wall - sum(interval.get(p, 0.0) for p in BARRIER_PHASES),
        )
        row = {
            "step": step,
            "wall_s": wall,
            "local_s": local,
            "phases": {k: v for k, v in interval.items() if v > 0.0},
        }
        with self._lock:
            self._totals["idle"] = self._totals.get("idle", 0.0) + interval["idle"]
            self._rows.append(row)
        # crash-durable mirror: one compact tick per step row, so the
        # postmortem can place a death between two step boundaries even
        # with every in-memory surface gone
        from torchft_tpu.telemetry.blackbox import BLACKBOX

        BLACKBOX.record(
            "anatomy_tick", step=step, wall_s=round(wall, 6),
            local_s=round(local, 6),
        )
        try:
            from torchft_tpu import telemetry

            # EVERY phase is observed EVERY step (zero when inactive):
            # a phase's p50 then reads "typical per-step cost" — and the
            # per-phase p50s compose to a typical step, which is what
            # lets the bench step_anatomy row reconcile its p50 sum
            # against the measured wall p50 (rare phases like heal keep
            # their cost visible in the p99)
            for phase in PHASES:
                telemetry.STEP_PHASE_SECONDS.labels(phase=phase).observe(
                    interval.get(phase, 0.0)
                )
            telemetry.STEP_WALL_SECONDS.observe(wall)
            telemetry.STEP_LOCAL_SECONDS.observe(local)
        except Exception:  # noqa: BLE001 — observability never fails a step
            pass
        return row

    # -- wire-stage view (the collectives.record_wire_stage shim) --------

    def wire_stage_snapshot(self, reset: bool = False) -> Dict[str, float]:
        """Process-cumulative seconds per wire-plane stage since the last
        ``reset=True`` mark. Resetting moves the mark; the ledger's own
        cumulative totals (and the telemetry counters) stay monotonic."""
        with self._lock:
            out = {
                k: v - self._wire_marks.get(k, 0.0)
                for k, v in self._wire_totals.items()
            }
            if reset:
                self._wire_marks = dict(self._wire_totals)
        return {k: v for k, v in out.items() if v > 0.0}

    # -- consumer side ---------------------------------------------------

    @staticmethod
    def _percentile(values: List[float], q: float) -> float:
        """Exact interpolated percentile of a value list (the summary's
        quantiles come from the retained step rows, not the log2-bucket
        histograms — one bucket per octave is fine for Prometheus but its
        ±50% quantile resolution would swamp the bench row's 5%
        phase-sum-vs-wall reconciliation)."""
        if not values:
            return 0.0
        vs = sorted(values)
        if len(vs) == 1:
            return vs[0]
        pos = q * (len(vs) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(vs) - 1)
        return vs[lo] + (vs[hi] - vs[lo]) * (pos - lo)

    def last_row(self) -> Optional[Dict[str, Any]]:
        """The most recent step row (step / wall_s / local_s / phases) or
        None — the per-step sample the time-series piggyback publishes
        (telemetry/timeseries.py): percentiles smooth exactly the level
        shifts the regression sentinel exists to catch, so the retained
        series carries raw per-step values."""
        with self._lock:
            if not self._rows:
                return None
            r = self._rows[-1]
            return {
                "step": r["step"],
                "wall_s": r["wall_s"],
                "local_s": r["local_s"],
                "phases": dict(r["phases"]),
            }

    def local_p50(self) -> Optional[float]:
        """Rolling p50 of the local (peer-wait-excluded) step time over
        the retained row window — the scalar piggybacked to the
        lighthouse for straggler detection."""
        with self._lock:
            vals = [r["local_s"] for r in self._rows]
        if not vals:
            return None
        return self._percentile(vals, 0.5)

    def outlier_digest(self) -> List[Dict[str, Any]]:
        """The attached StepTimer's tagged outliers (quorum/heal steps) as
        JSON-safe records; empty when no timer is attached."""
        if self._timer is None:
            return []
        try:
            return self._timer.outlier_digest()
        except Exception:  # noqa: BLE001
            return []

    def summary(self) -> Dict[str, Any]:
        """Compact per-phase digest for piggybacks and bench rows:
        per-phase p50/p99/cumulative seconds, wall/local p50s, step count
        and the tagged-outlier digest. Quantiles are EXACT percentiles
        over the retained row window (see :meth:`_percentile`); every
        phase contributes zero on steps it was inactive, so the per-phase
        p50s compose to a typical step."""
        with self._lock:
            rows = list(self._rows)
            totals = dict(self._totals)
            steps = self.steps
        last = rows[-1] if rows else None
        phases: Dict[str, Any] = {}
        for phase in PHASES:
            vals = [r["phases"].get(phase, 0.0) for r in rows]
            total = totals.get(phase, 0.0)
            if not any(vals) and total <= 0.0:
                continue
            phases[phase] = {
                "p50_s": round(self._percentile(vals, 0.5), 6),
                "p99_s": round(self._percentile(vals, 0.99), 6),
                "total_s": round(total, 4),
            }
        out: Dict[str, Any] = {
            "steps": steps,
            "phases": phases,
            "wall_p50_s": round(
                self._percentile([r["wall_s"] for r in rows], 0.5), 6
            ),
            "wall_p99_s": round(
                self._percentile([r["wall_s"] for r in rows], 0.99), 6
            ),
            "local_p50_s": round(
                self._percentile([r["local_s"] for r in rows], 0.5), 6
            ),
        }
        if last is not None:
            out["last"] = {
                "step": last["step"],
                "wall_s": round(last["wall_s"], 6),
                "phases": {
                    k: round(v, 6) for k, v in last["phases"].items()
                },
            }
        heal_stages = self.heal_stage_snapshot()
        if heal_stages:
            out["heal_stages"] = {
                k: round(v, 6) for k, v in heal_stages.items()
            }
        outliers = self.outlier_digest()
        if outliers:
            out["outliers"] = outliers[-8:]  # recent tail keeps it compact
        return out

    def dump(self) -> Dict[str, Any]:
        """Full ledger state for evidence dumps (flight recorder /
        SIGUSR2): every retained step row + the summary digest."""
        with self._lock:
            rows = [
                {
                    "step": r["step"],
                    "wall_s": round(r["wall_s"], 6),
                    "local_s": round(r["local_s"], 6),
                    "phases": {
                        k: round(v, 6) for k, v in r["phases"].items()
                    },
                }
                for r in self._rows
            ]
        return {"rows": rows, "summary": self.summary()}

    def reset(self) -> None:
        """Clear rows/intervals/totals/marks (tests). The registry
        histograms are zeroed separately by ``telemetry.reset()``."""
        with self._lock:
            self._last = None
            self._interval = {}
            self._totals = {}
            self._wire_totals = {}
            self._wire_marks = {}
            self._heal_stages = {}
            self._rows.clear()
            self.steps = 0


# Process-wide ledger: the data plane shims and the Manager all feed one
# instance (one Manager per process in production; in-process multi-
# manager tests interleave ticks, which is fine for telemetry).
LEDGER = StepLedger()
