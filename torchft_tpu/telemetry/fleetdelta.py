"""Delta-encoded telemetry piggybacks + fleet rollup client (ISSUE 16).

Before this module, every replica re-shipped its FULL JSON telemetry
digest (summary + anatomy + series) on every quorum RPC — ~4-10 KB per
step per replica, all of it landing on the one lighthouse whose quorum
fan-out is already superlinear at 256 groups (the ``quorum_scale``
evidence). Steady state is almost entirely redundant: between two steps
a handful of counters increment and one or two histogram buckets move.
This module makes the piggyback proportional to what CHANGED, not to
what EXISTS:

* :func:`flatten` / :func:`unflatten` — the nested report dict becomes a
  flat ``{path: leaf}`` map (path segments joined by the ``\\x1f`` unit
  separator, list indices as ``\\x1e<i>`` segments so telemetry key
  names — which legitimately contain dots, e.g. ``dp.hop`` — never
  collide with the path syntax).
* :class:`DeltaEncoder` — the replica side. Emits a versioned binary
  blob: dictionary-interned keys (a key's UTF-8 bytes travel ONCE per
  incarnation, then it is a one-varint reference) and only the fields
  that changed since the last blob. A fresh process (new random
  8-byte incarnation) or a lighthouse-requested resync re-sends FULL
  state, so a respawned pid can never alias the dead incarnation's
  interning dictionary or delta base.
* :class:`DeltaDecoder` — the symmetric receiver, used by tests as the
  oracle for the C++ decoder (``native/telemetry_delta.h``) and by any
  Python-side consumer of raw blobs.
* :func:`poll_fleet` — one ``GET /fleet.json`` against the lighthouse:
  the O(#series)-not-O(fleet) rollup scrape (fleet-folded log2
  histograms with p50/p95/p99, reporting/stuck/breach counts).

Wire format v1 (all integers unsigned LEB128 varints unless noted)::

    byte  0      magic 0xD7
    byte  1      format version (1)
    byte  2      flags (bit0 = FULL: receiver resets dictionary + state)
    bytes 3..10  incarnation (8 random bytes, fixed per encoder lifetime)
    varint       version       (this blob's state version, starts at 1)
    varint       base_version  (version this delta applies on top of;
                                0 and ignored when FULL)
    varint       entry count
    entries:
      varint     keyref = (id << 1) | define
                 define=1: varint key byte length + UTF-8 key bytes
                 (registers ``id``; ids are assigned densely from 0)
      byte       type: 0 DEL, 1 F64 (8 bytes LE), 2 I64 (zigzag
                 varint), 3 BOOL (1 byte), 4 STR (varint len + UTF-8),
                 5 BYTES (varint len + raw)
      value      per type; DEL carries none

A receiver applies a delta only when ``(incarnation, base_version)``
matches its current state exactly; any mismatch is dropped and answered
with a resync request in the quorum-reply ack (``tack``), which makes
the next blob FULL. Loss is therefore self-healing within one round
trip and never silently merges skewed states.

Degradation under the 64 KiB piggyback cap is FIELD-BY-FIELD in a
documented priority order (the old path dropped the whole anatomy
digest for an opaque marker): latches and health scalars (tier 0) >
summary counters / series samples (tier 1) > anatomy + histogram
digests (tier 2) > spans (tier 3 — spans ride outside the blob and are
dropped first by the Manager). Entries that do not fit stay DIRTY in
the encoder (the shadow state is only advanced for what was actually
sent), so a truncated field ships on a later, smaller step instead of
being lost.

Knob registry (documented in docs/observability.md "Telemetry at
scale", enforced both directions by the ``obs-env-drift`` rule):
``TORCHFT_TELEMETRY_MAX_BYTES`` (encoder blob cap, default 65536) and
``TORCHFT_TELEMETRY_ROLLUP_S`` (lighthouse fleet-rollup cadence into
the TSDB's ``_fleet`` pseudo-replica; parsed natively by coord.cc, this
module's :func:`rollup_interval_s` is the client's shared constant).
"""

from __future__ import annotations

import json
import os
import struct
import urllib.request
from typing import Any, Dict, List, Optional, Set, Tuple

__all__ = [
    "SEP",
    "IDX",
    "MAGIC",
    "FMT_VERSION",
    "DEFAULT_MAX_BYTES",
    "T_DEL",
    "T_F64",
    "T_I64",
    "T_BOOL",
    "T_STR",
    "T_BYTES",
    "delta_enabled",
    "max_blob_bytes",
    "rollup_interval_s",
    "flatten",
    "unflatten",
    "tier_of",
    "DeltaEncoder",
    "DeltaDecoder",
    "collect_hists",
    "poll_fleet",
]

SEP = "\x1f"  # path-segment joiner (unit separator: never in key names)
IDX = "\x1e"  # list-index segment prefix; IDX + "#" is the length marker

MAGIC = 0xD7
FMT_VERSION = 1
FLAG_FULL = 0x01

T_DEL = 0
T_F64 = 1
T_I64 = 2
T_BOOL = 3
T_STR = 4
T_BYTES = 5

DEFAULT_MAX_BYTES = 1 << 16  # the lighthouse's piggyback cap

_I64_MIN = -(1 << 63)
_I64_MAX = (1 << 63) - 1


def delta_enabled() -> bool:
    """``TORCHFT_TELEMETRY_DELTA=0`` falls back to the legacy full-JSON
    piggyback (also the ``quorum_scale`` contrast leg)."""
    return os.environ.get("TORCHFT_TELEMETRY_DELTA", "1") != "0"


def max_blob_bytes() -> int:
    try:
        return int(
            os.environ.get("TORCHFT_TELEMETRY_MAX_BYTES",
                           str(DEFAULT_MAX_BYTES))
        )
    except ValueError:
        return DEFAULT_MAX_BYTES


def rollup_interval_s() -> float:
    """The lighthouse's fleet-rollup cadence (native getenv in coord.cc;
    this is the Python side's shared constant, same idiom as
    ``timeseries.retain``)."""
    try:
        return float(os.environ.get("TORCHFT_TELEMETRY_ROLLUP_S", "1.0"))
    except ValueError:
        return 1.0


# ---------------------------------------------------------------- flatten

def flatten(obj: Any, _prefix: str = "", _out: Optional[Dict[str, Any]] = None
            ) -> Dict[str, Any]:
    """Nested dict/list → flat ``{path: leaf}``. Leaves are bool / int /
    float / str / bytes; ``None`` leaves are skipped (absence IS the
    encoding); anything else degrades to ``str(v)`` (the same contract
    as the legacy path's ``json.dumps(default=str)``)."""
    if _out is None:
        _out = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            key = str(k)
            _flatten_child(v, _prefix + key if not _prefix
                           else _prefix + SEP + key, _out)
    elif isinstance(obj, (list, tuple)):
        _out[(_prefix + SEP if _prefix else "") + IDX + "#"] = len(obj)
        for i, v in enumerate(obj):
            _flatten_child(v, (_prefix + SEP if _prefix else "")
                           + IDX + str(i), _out)
    else:
        _flatten_child(obj, _prefix, _out)
    return _out


def _flatten_child(v: Any, path: str, out: Dict[str, Any]) -> None:
    if v is None:
        return
    if isinstance(v, (dict, list, tuple)):
        flatten(v, path, out)
    elif isinstance(v, bool):
        out[path] = v
    elif isinstance(v, int):
        out[path] = v if _I64_MIN <= v <= _I64_MAX else float(v)
    elif isinstance(v, (float, str, bytes)):
        out[path] = v
    else:
        out[path] = str(v)


def unflatten(flat: Dict[str, Any]) -> Any:
    """Inverse of :func:`flatten` (modulo ``None`` leaves and non-JSON
    types, which flatten degrades by design)."""
    root: Dict[str, Any] = {}
    for path, leaf in flat.items():
        segs = path.split(SEP)
        node = root
        for seg in segs[:-1]:
            node = node.setdefault(seg, {})
            if not isinstance(node, dict):  # leaf/subtree collision
                break
        else:
            if segs[-1] == IDX + "#":
                node.setdefault(IDX + "#", leaf)
            else:
                node[segs[-1]] = leaf
    return _rebuild(root)


def _rebuild(node: Any) -> Any:
    if not isinstance(node, dict):
        return node
    if any(k.startswith(IDX) for k in node):
        n = node.get(IDX + "#")
        if not isinstance(n, int):
            n = 1 + max(
                (int(k[len(IDX):]) for k in node
                 if k.startswith(IDX) and k != IDX + "#"),
                default=-1,
            )
        out_list: List[Any] = [None] * int(n)
        for k, v in node.items():
            if not k.startswith(IDX) or k == IDX + "#":
                continue
            i = int(k[len(IDX):])
            if 0 <= i < len(out_list):
                out_list[i] = _rebuild(v)
        return out_list
    return {k: _rebuild(v) for k, v in node.items()}


# ------------------------------------------------------------ varint core

def _wv(out: bytearray, n: int) -> None:  # unsigned LEB128
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _rv(buf: bytes, off: int) -> Tuple[int, int]:
    n = 0
    shift = 0
    while True:
        if off >= len(buf):
            raise ValueError("truncated varint")
        b = buf[off]
        off += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, off
        shift += 7
        if shift > 63:
            raise ValueError("varint overflow")


def _zz(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n >= 0 else ((-n) << 1) - 1


def _unzz(n: int) -> int:
    return (n >> 1) if not n & 1 else -((n + 1) >> 1)


# ------------------------------------------------------------------ tiers

def tier_of(path: str) -> int:
    """Degradation tier under the byte cap (lower survives longer):
    0 = latches + health scalars, 1 = summary counters / series /
    diagnosis pointers, 2 = anatomy + histogram digests. (Spans are
    tier 3 but ride outside the blob — the Manager drops them first.)"""
    top = path.split(SEP, 1)[0]
    if top in ("step", "epoch", "stuck", "slo_breach",
               "local_step_p50_s", "last_heal_ts"):
        return 0
    if path.startswith("series" + SEP + "flag."):
        return 0  # detector latches as 0/1 series
    if top in ("anatomy", "hist"):
        return 2
    return 1


def _leaf_differs(a: Any, b: Any) -> bool:
    # type-sensitive: 1 and 1.0 and True compare equal in Python but
    # decode to different wire types on the far side
    return type(a) is not type(b) or a != b


def _encode_leaf(out: bytearray, v: Any) -> None:
    if isinstance(v, bool):
        out.append(T_BOOL)
        out.append(1 if v else 0)
    elif isinstance(v, int):
        out.append(T_I64)
        _wv(out, _zz(v))
    elif isinstance(v, float):
        out.append(T_F64)
        out += struct.pack("<d", v)
    elif isinstance(v, str):
        b = v.encode("utf-8")
        out.append(T_STR)
        _wv(out, len(b))
        out += b
    elif isinstance(v, bytes):
        out.append(T_BYTES)
        _wv(out, len(v))
        out += v
    else:  # pragma: no cover — flatten never emits other leaves
        raise TypeError(f"unencodable leaf: {type(v)}")


class DeltaEncoder:
    """Replica-side stateful encoder. One instance per process telemetry
    chain; the incarnation is fixed at construction so a respawn is a
    NEW chain by construction. Thread-compatible, not thread-safe — the
    Manager calls it from the quorum path only."""

    # a chain whose acks lag this many versions has lost its reply
    # channel (e.g. a lighthouse failover that kept state_ but not our
    # RPC replies) — resync defensively rather than delta forever
    MAX_UNACKED = 32

    def __init__(self, max_bytes: Optional[int] = None) -> None:
        self.incarnation: bytes = os.urandom(8)
        self.version = 0  # version of the last emitted blob
        self.acked_version = 0
        self._need_full = True
        self._key_ids: Dict[str, int] = {}
        self._shadow: Dict[str, Any] = {}
        self._max_bytes = max_bytes
        self.truncated_total = 0  # entries skipped under the cap, ever
        self.last_truncated = 0   # ... by the most recent encode
        self.fulls_total = 0
        self.blobs_total = 0
        self.bytes_total = 0

    @property
    def cap(self) -> int:
        return self._max_bytes if self._max_bytes is not None \
            else max_blob_bytes()

    def on_ack(self, ack: Optional[Dict[str, Any]]) -> None:
        """Feed the ``tack`` map from a quorum reply:
        ``{incarnation_hex: {"ver": int, "resync": bool}}``. Entries for
        other incarnations (other local ranks, or our own previous life
        relayed late) are ignored."""
        if not isinstance(ack, dict):
            return
        mine = ack.get(self.incarnation.hex())
        if not isinstance(mine, dict):
            return
        if mine.get("resync"):
            self._need_full = True
        try:
            self.acked_version = max(self.acked_version,
                                     int(mine.get("ver", 0)))
        except (TypeError, ValueError):
            pass

    def force_full(self) -> None:
        """Next blob re-sends full state — the recovery lever for any
        caller that knows the receiver lost the chain (e.g. a respawn
        re-basing after a parked resync)."""
        self._need_full = True

    def encode(self, report: Dict[str, Any]) -> bytes:
        """One blob for this step's report. Always succeeds; under the
        byte cap lower-priority entries are deferred (see module doc)."""
        if (self.version - self.acked_version) > self.MAX_UNACKED:
            self._need_full = True
        flat = flatten(report)
        full = self._need_full
        if full:
            self._key_ids = {}
            self._shadow = {}
        # the changed set, most-critical tier first, stable within a tier
        changed: List[Tuple[int, str, Any]] = [
            (tier_of(k), k, v) for k, v in flat.items()
            if full or k not in self._shadow
            or _leaf_differs(self._shadow[k], v)
        ]
        deleted: Set[str] = set(self._shadow) - set(flat)
        changed += [(tier_of(k), k, None) for k in deleted]
        changed.sort(key=lambda t: (t[0], t[1]))

        out = bytearray()
        out.append(MAGIC)
        out.append(FMT_VERSION)
        out.append(FLAG_FULL if full else 0)
        out += self.incarnation
        version = self.version + 1
        _wv(out, version)
        _wv(out, 0 if full else self.version)
        cap = self.cap
        entries = bytearray()
        n_entries = 0
        skipped = 0
        # header + worst-case count varint headroom
        budget = cap - len(out) - 5
        for _tier, key, val in changed:
            e = bytearray()
            kid = self._key_ids.get(key)
            if kid is None:
                kid = len(self._key_ids)
                kb = key.encode("utf-8")
                _wv(e, (kid << 1) | 1)
                _wv(e, len(kb))
                e += kb
                new_key = True
            else:
                _wv(e, kid << 1)
                new_key = False
            if val is None:
                e.append(T_DEL)
            else:
                _encode_leaf(e, val)
            if len(entries) + len(e) > budget:
                skipped += 1
                continue  # stays dirty: shadow not advanced for it
            if new_key:
                self._key_ids[key] = kid
            entries += e
            n_entries += 1
            if val is None:
                self._shadow.pop(key, None)
            else:
                self._shadow[key] = val
        _wv(out, n_entries)  # landed in the headroom reserved above
        out += entries
        self.version = version
        self._need_full = False
        self.last_truncated = skipped
        self.truncated_total += skipped
        self.fulls_total += 1 if full else 0
        self.blobs_total += 1
        self.bytes_total += len(out)
        return bytes(out)


class DeltaDecoder:
    """Receiver-side state for ONE incarnation chain — the Python oracle
    for ``native/telemetry_delta.h`` and the unit under round-trip
    tests. ``apply`` returns an outcome dict instead of raising: the
    real receiver must degrade (request resync), never fail a quorum."""

    def __init__(self) -> None:
        self.incarnation: Optional[bytes] = None
        self.version = 0
        self.keys: List[str] = []
        self.flat: Dict[str, Any] = {}
        self.resync = False

    def state(self) -> Any:
        """The current nested view (tests compare against the sender's
        report)."""
        return unflatten(self.flat)

    def apply(self, blob: bytes) -> Dict[str, Any]:
        out = {"ok": False, "full": False, "resync_wanted": False,
               "changed": [], "error": ""}
        try:
            if len(blob) < 11 or blob[0] != MAGIC:
                raise ValueError("bad magic")
            if blob[1] != FMT_VERSION:
                raise ValueError(f"format version {blob[1]} != "
                                 f"{FMT_VERSION}")
            full = bool(blob[2] & FLAG_FULL)
            inc = blob[3:11]
            off = 11
            version, off = _rv(blob, off)
            base, off = _rv(blob, off)
            if not full:
                if self.incarnation != inc or self.version != base:
                    self.resync = True
                    out["resync_wanted"] = True
                    out["error"] = "incarnation/base mismatch"
                    return out
            n, off = _rv(blob, off)
            if full:
                self.incarnation = inc
                self.keys = []
                self.flat = {}
            changed: List[str] = []
            for _ in range(n):
                ref, off = _rv(blob, off)
                if ref & 1:
                    klen, off = _rv(blob, off)
                    key = blob[off:off + klen].decode("utf-8")
                    off += klen
                    if (ref >> 1) != len(self.keys):
                        raise ValueError("non-dense key id")
                    self.keys.append(key)
                else:
                    key = self.keys[ref >> 1]
                if off >= len(blob):
                    raise ValueError("truncated entry")
                t = blob[off]
                off += 1
                if t == T_DEL:
                    self.flat.pop(key, None)
                elif t == T_F64:
                    (self.flat[key],) = struct.unpack_from("<d", blob, off)
                    off += 8
                elif t == T_I64:
                    zz, off = _rv(blob, off)
                    self.flat[key] = _unzz(zz)
                elif t == T_BOOL:
                    self.flat[key] = bool(blob[off])
                    off += 1
                elif t in (T_STR, T_BYTES):
                    slen, off = _rv(blob, off)
                    raw = blob[off:off + slen]
                    off += slen
                    self.flat[key] = (raw.decode("utf-8") if t == T_STR
                                      else bytes(raw))
                else:
                    raise ValueError(f"unknown leaf type {t}")
                changed.append(key)
            self.version = version
            self.resync = False
            out.update(ok=True, full=full, changed=changed)
            return out
        except (ValueError, IndexError, UnicodeDecodeError,
                struct.error) as e:
            self.resync = True
            out["resync_wanted"] = True
            out["error"] = str(e)
            return out


# -------------------------------------------------------- hist collection

def collect_hists() -> Dict[str, Dict[str, int]]:
    """This replica's mergeable log2 histograms for the fleet rollup:
    raw (non-cumulative) per-bucket counts on the shared 28-bucket grid
    (``LOG2_BUCKETS`` == ``native/lathist.h``), keyed by bucket index as
    a string so only the 1-2 buckets a step actually moves ride the
    delta. Sources: the step wall/local/per-phase registry histograms
    and the native lathist ops. Zero buckets are omitted (the fold
    treats absence as zero). Never raises."""
    out: Dict[str, Dict[str, int]] = {}
    try:
        from torchft_tpu import telemetry as T

        def sparse(counts: List[int]) -> Dict[str, int]:
            return {str(i): int(c) for i, c in enumerate(counts) if c}

        for name, hist in (("wall", T.STEP_WALL_SECONDS),
                           ("local", T.STEP_LOCAL_SECONDS)):
            s = sparse(hist.raw_counts())
            if s:
                out[name] = s
        from torchft_tpu.telemetry.anatomy import PHASES

        for phase in PHASES:
            s = sparse(T.STEP_PHASE_SECONDS.labels(phase=phase)
                       .raw_counts())
            if s:
                out[f"phase.{phase}"] = s
        try:
            from torchft_tpu.telemetry.native import native_latency_snapshot

            for op, h in (native_latency_snapshot() or {}).items():
                s = sparse(list(h.get("counts") or ()))
                if s:
                    out[f"lat.{op}"] = s
        except Exception:  # noqa: BLE001 — native plane optional
            pass
    except Exception:  # noqa: BLE001 — observability must not fail quorum
        return {}
    return out


# ------------------------------------------------------------ fleet client

def _base_url(addr: str) -> str:
    if "://" not in addr:
        addr = "http://" + addr
    return addr.rstrip("/")


def poll_fleet(addr: str, group: str = "", timeout: float = 3.0
               ) -> Optional[Dict[str, Any]]:
    """One ``GET /fleet.json`` rollup scrape: fleet-folded histogram
    percentiles + reporting/stuck/breach counts, size-independent of
    fleet width. ``group`` adds one group's own percentile block.
    Returns the parsed reply or None — observability degrades, never
    raises."""
    url = f"{_base_url(addr)}/fleet.json"
    if group:
        url += f"?group={group}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except Exception:  # noqa: BLE001
        return None
