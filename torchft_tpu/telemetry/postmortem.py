"""Fleet postmortem reconstruction — ``python -m torchft_tpu.telemetry.postmortem <dir>``.

Merges every replica's crash-durable black boxes (Python rings + native
breadcrumb rings — ``telemetry/blackbox.py``), FT event trails
(``*.jsonl``) and fault-injection evidence (``tft_fault_*``) found under
one directory into a single causal timeline, ordered by the
clock-sync-free ``(quorum_epoch, step, seq)`` coordinates every record
carries (wall clock is only the within-coordinate tiebreak — replicas
never needed synchronized clocks to agree on epoch and step, which is
the whole point of using them).

The incident report answers the four questions a 3 a.m. page actually
asks:

* **first anomaly** — the earliest abort / heal failure / peer death /
  watchdog stall / divergence latch on the merged timeline;
* **victim** — the replica the survivors' ``peer_death`` records accuse
  (corroborated by a box that ends with an in-flight op / torn tail);
* **in-flight ops** — per replica, the last collective issued but never
  completed (the flight-recorder mirror survives SIGKILL in the box);
* **classification** — ``injected`` (fault-plane evidence exists),
  ``environmental`` (the documented churn-corruption signatures —
  ``conftest.known_corruption_signature``), ``divergence`` (the
  commit-time sentinel latched), or ``new-bug`` (anomalies nothing
  explains: the red that means *investigate*).

Stdlib-only and safe to run against a live directory (readers never
write the rings).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from torchft_tpu.telemetry.blackbox import (
    read_blackbox,
    read_native_blackbox,
)

__all__ = [
    "collect_boxes", "analyze", "classify", "render_text",
    "perf_windows", "render_perf_text", "main",
]

# record kinds that mark "something went wrong here" on the timeline
ANOMALY_KINDS = (
    "abort",
    "heal_failed",
    "peer_death",
    "eviction",
    "watchdog_stall",
    "flight_dump",
    "fault_injected",
    "divergence_detected",
    "slo_breach",
)


def _read_trail_file(path: str) -> List[Dict[str, Any]]:
    records: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail of a SIGKILLed writer
                if isinstance(rec, dict) and "event" in rec:
                    records.append(rec)
    except OSError:
        pass
    return records


def collect_boxes(root: str) -> List[Dict[str, Any]]:
    """Every black box under ``root`` (recursive), each as
    ``{"path", "pid", "replica", "native", "torn", "records"}``."""
    out: List[Dict[str, Any]] = []
    for base, _dirs, files in os.walk(root):
        for fn in sorted(files):
            if not fn.endswith(".bb"):
                continue
            path = os.path.join(base, fn)
            try:
                if fn.endswith("_native.bb"):
                    records, meta = read_native_blackbox(path)
                else:
                    records, meta = read_blackbox(path)
            except OSError:
                continue
            out.append(
                {
                    "path": path,
                    "pid": meta.get("pid"),
                    "replica": meta.get("replica") or "",
                    "native": bool(meta.get("native")),
                    "torn": int(meta.get("torn", 0)),
                    "records": records,
                }
            )
    return out


def _inflight_op(records: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The last op issued but never completed in one box's records —
    "what was this process doing when it died"."""
    completed = {
        r.get("fseq") for r in records if r.get("k") == "op_complete"
    }
    last = None
    for r in records:
        if r.get("k") == "op_issue" and r.get("fseq") not in completed:
            last = r
    return last


def _sort_key(rec: Dict[str, Any]) -> Tuple:
    # (epoch, step) are the causal coordinates; seq orders within one
    # process; ts is only the cross-process tiebreak inside a coordinate
    ep = rec.get("ep", -1)
    st = rec.get("st", -1)
    return (
        ep if isinstance(ep, int) else -1,
        st if isinstance(st, int) else -1,
        float(rec.get("ts", 0.0) or 0.0),
        int(rec.get("q", 0) or 0),
    )


def classify(
    report: Dict[str, Any], log_text: Optional[str] = None
) -> str:
    """Attribution verdict for the incident (see module docstring)."""
    if report.get("injected_evidence"):
        return "injected"
    from torchft_tpu.faultinject.core import ENV_CORRUPTION_SIGNATURES

    texts: List[str] = []
    if log_text:
        texts.append(log_text)
    for rec in report.get("timeline", []):
        err = rec.get("error") or rec.get("errored")
        if err:
            texts.append(str(err))
    for text in texts:
        for sig in ENV_CORRUPTION_SIGNATURES:
            if sig in text:
                return "environmental"
    if any(
        r.get("k") == "divergence_detected" or r.get("k") == "divergence"
        for r in report.get("timeline", [])
    ):
        return "divergence"
    if report.get("first_anomaly") or report.get("victim"):
        return "new-bug"
    return "clean"


def collect_bundles(paths: List[Optional[str]]) -> List[Dict[str, Any]]:
    """Every diagnosis bundle (``bundle.json``) under the given roots,
    deduplicated and ordered by capture time — the ``--bundles`` input
    (ISSUE 12). Torn/malformed bundles are skipped, same contract as the
    black-box reader."""
    from torchft_tpu.telemetry.diagnosis import load_bundle_meta

    metas: List[Dict[str, Any]] = []
    seen: set = set()
    for p in paths:
        if not p or not os.path.isdir(p):
            continue
        for base, _dirs, files in os.walk(p):
            if "bundle.json" not in files:
                continue
            real = os.path.realpath(base)
            if real in seen:
                continue
            seen.add(real)
            meta = load_bundle_meta(base)
            if meta is not None:
                metas.append(meta)
    metas.sort(key=lambda m: m.get("ts", 0.0))
    return metas


def analyze(
    root: str,
    log_text: Optional[str] = None,
    timeline_cap: int = 2000,
    bundles_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Reconstruct the incident under ``root``; returns the report dict
    (JSON-safe). ``log_text`` optionally feeds worker-log text into the
    environmental-signature classification. ``bundles_dir`` (the
    ``--bundles`` flag; ``""`` = discover under ``root``) folds captured
    diagnosis bundles into the causal timeline, so the report reads
    latch → capture → evidence even after every process died."""
    boxes = collect_boxes(root)
    evidence: List[Dict[str, Any]] = []
    trails: List[Dict[str, Any]] = []
    for base, _dirs, files in os.walk(root):
        for fn in sorted(files):
            path = os.path.join(base, fn)
            if fn.startswith("tft_fault_"):
                from torchft_tpu.faultinject.core import read_evidence

                evidence.extend(read_evidence(base))
                break  # read_evidence consumed the whole directory
        for fn in sorted(files):
            if fn.endswith(".jsonl"):
                trails.extend(_read_trail_file(os.path.join(base, fn)))

    # normalize everything onto one record shape and merge
    timeline: List[Dict[str, Any]] = []
    replicas: Dict[str, Dict[str, Any]] = {}
    for box in boxes:
        src = box["replica"] or f"pid:{box['pid']}"
        info = replicas.setdefault(
            src,
            {"replica": box["replica"], "pids": [], "records": 0,
             "torn": 0, "inflight": None, "last_epoch": -1,
             "last_step": -1},
        )
        info["pids"].append(box["pid"])
        info["records"] += len(box["records"])
        info["torn"] += box["torn"]
        inflight = _inflight_op(box["records"])
        if inflight is not None:
            info["inflight"] = inflight
        for rec in box["records"]:
            info["last_epoch"] = max(
                info["last_epoch"], int(rec.get("ep", -1) or -1)
            )
            info["last_step"] = max(
                info["last_step"], int(rec.get("st", -1) or -1)
            )
            timeline.append({**rec, "src": src})
    # The black box MIRRORS every event-trail emit (events.py), so when
    # boxes were recovered the trail files are duplicates: merging both
    # would double every peer_death/abort on the timeline and double the
    # victim-accusation counts. Trails only fill in when no box spoke
    # (pre-arm workers, or a directory with trails alone).
    trails_mirrored = any(box["records"] for box in boxes)
    if not trails_mirrored:
        for rec in trails:
            timeline.append(
                {
                    "k": rec.get("event"),
                    "ep": rec.get("quorum_id", -1),
                    "st": rec.get("step", -1),
                    "ts": rec.get("ts", 0.0),
                    "src": "trail",
                    **{
                        k: v
                        for k, v in rec.items()
                        if k not in ("event", "ts", "step")
                    },
                }
            )
    # diagnosis bundles fold in as first-class timeline records at their
    # stamped (epoch, step, seq) coordinates: the latch event (mirrored
    # by the trigger replica's box) is followed by its capture, and the
    # record carries the on-disk evidence paths (ISSUE 12)
    bundles: List[Dict[str, Any]] = []
    if bundles_dir is not None:
        bundles = collect_bundles([root, bundles_dir or None])
        for meta in bundles:
            trig = meta.get("trigger") or {}
            timeline.append(
                {
                    "k": "diagnosis_captured",
                    "ep": meta.get("epoch", -1),
                    "st": meta.get("step", -1),
                    "q": meta.get("seq", 0),
                    "ts": meta.get("ts", 0.0),
                    "src": meta.get("replica_id") or "diagnosis",
                    "bundle": meta.get("bundle"),
                    "trigger": trig.get("event"),
                    "path": meta.get("_dir"),
                }
            )
    timeline.sort(key=_sort_key)

    # victim attribution: the replica the survivors' peer_death records
    # accuse — readable from black boxes alone (the event-trail mirror
    # rides the box), corroborated by that replica's own torn/in-flight
    # tail
    accusations: Dict[str, int] = {}
    for rec in timeline:
        if rec.get("k") == "peer_death" and rec.get("replica"):
            accusations[str(rec["replica"])] = (
                accusations.get(str(rec["replica"]), 0) + 1
            )
    victim = max(accusations, key=accusations.get) if accusations else None
    victim_info = replicas.get(victim) if victim else None
    if victim is None:
        # no accuser survived (or a single-replica incident): fall back
        # to the box that ends torn / with an op still in flight
        for src, info in replicas.items():
            if info["torn"] or info["inflight"] is not None:
                victim = src
                victim_info = info
                break

    first_anomaly = next(
        (r for r in timeline if r.get("k") in ANOMALY_KINDS), None
    )
    injected = [
        r
        for r in evidence
        if r.get("action") in ("kill", "torn", "drop", "corrupt")
    ]

    report: Dict[str, Any] = {
        "root": root,
        "boxes": [
            {k: v for k, v in b.items() if k != "records"} for b in boxes
        ],
        "replicas": replicas,
        "victim": victim,
        "victim_inflight_op": (
            victim_info.get("inflight") if victim_info else None
        ),
        "victim_epoch": (
            victim_info.get("last_epoch") if victim_info else None
        ),
        "survivor_inflight": {
            src: info["inflight"]
            for src, info in replicas.items()
            if src != victim and info["inflight"] is not None
        },
        "first_anomaly": first_anomaly,
        "injected_evidence": injected,
        "bundles": [
            {k: v for k, v in m.items() if k not in ("lathist",)}
            for m in bundles
        ],
        "trails_mirrored_by_boxes": trails_mirrored,
        "timeline": timeline[:timeline_cap],
        "timeline_truncated": max(0, len(timeline) - timeline_cap),
    }
    report["classification"] = classify(report, log_text=log_text)

    # recovery accounting: reading a crashed process's box IS the event
    # the live plane could never emit — record it on THIS process's
    # trail so forensic tooling use shows up in telemetry too
    try:
        from torchft_tpu import telemetry

        telemetry.emit(
            "blackbox_recovered",
            boxes=len(boxes),
            records=sum(len(b["records"]) for b in boxes),
            torn=sum(b["torn"] for b in boxes),
            classification=report["classification"],
        )
    except Exception:  # noqa: BLE001 — reporting must not fail the report
        pass
    return report


def perf_windows(
    root: str,
    window: int = 0,
    delta: Optional[float] = None,
    lam: Optional[float] = None,
    min_n: Optional[int] = None,
) -> Dict[str, Any]:
    """``--perf`` window mode (ISSUE 11): reconstruct each replica's
    per-step wall/local series from the crash-durable ``anatomy_tick``
    black-box records — the SAME series the lighthouse time-series store
    retains live, read post-hoc from disk — and run the perf-regression
    sentinel (:mod:`torchft_tpu.telemetry.regression` Page-Hinkley)
    offline over them. Answers "when did this fleet get slow" from the
    boxes ALONE, after every live surface died with its processes.

    ``window`` keeps only the last N steps per replica (0 = all).
    Returns per-replica: the step range, first/last-window means, and
    every latched shift with its onset step."""
    from torchft_tpu.telemetry.regression import RegressionDetector

    boxes = collect_boxes(root)
    # replica -> [(step, wall_s, local_s)] in recorded order
    series: Dict[str, List[Tuple[int, float, float]]] = {}
    for box in boxes:
        src = box["replica"] or f"pid:{box['pid']}"
        for rec in box["records"]:
            if rec.get("k") != "anatomy_tick":
                continue
            try:
                step = int(rec.get("step", rec.get("st", -1)))
                wall = float(rec.get("wall_s", 0.0))
                local = float(rec.get("local_s", 0.0))
            except (TypeError, ValueError):
                continue
            if step >= 0 and wall > 0:
                series.setdefault(src, []).append((step, wall, local))
    kwargs: Dict[str, Any] = {}
    if delta is not None:
        kwargs["delta"] = delta
    if lam is not None:
        kwargs["lam"] = lam
    if min_n is not None:
        kwargs["min_n"] = min_n
    # unlike the live monitor (which excludes wall_s — the straggler/
    # critical-path planes already own cross-replica wall analysis),
    # this offline window feeds BOTH reconstructed series, so watch both:
    # a barrier-dominated degradation shows in wall while local stays
    # flat, and 'no level shift latched' would be a lie
    detector = RegressionDetector(
        prefixes=("local_s", "wall_s", "phase."), **kwargs
    )
    out: Dict[str, Any] = {"root": root, "replicas": {}}
    for src, samples in sorted(series.items()):
        samples.sort(key=lambda t: t[0])
        if window > 0:
            samples = samples[-window:]
        shifts: List[Dict[str, Any]] = []
        for step, wall, local in samples:
            for name, value in (("wall_s", wall), ("local_s", local)):
                ev = detector.observe(src, name, step, value)
                if ev is not None:
                    shifts.append(ev)
        locals_ = [s[2] for s in samples]
        head = locals_[: max(1, len(locals_) // 4)]
        tail = locals_[-max(1, len(locals_) // 4):]
        out["replicas"][src] = {
            "steps": len(samples),
            "step_range": [samples[0][0], samples[-1][0]] if samples else [],
            "local_head_mean_s": (
                round(sum(head) / len(head), 6) if head else None
            ),
            "local_tail_mean_s": (
                round(sum(tail) / len(tail), 6) if tail else None
            ),
            "shifts": shifts,
        }
    out["regressed"] = [
        {"replica": r, "series": s} for r, s in detector.regressed()
    ]
    return out


def render_perf_text(report: Dict[str, Any]) -> str:
    lines = [f"perf window of {report['root']}"]
    for src, info in sorted(report.get("replicas", {}).items()):
        lines.append(
            f"  {src}: {info['steps']} steps {info['step_range']} "
            f"local mean {info['local_head_mean_s']}s -> "
            f"{info['local_tail_mean_s']}s"
        )
        for ev in info.get("shifts", []):
            lines.append(
                f"    {ev['event']}: {ev['series']} at step {ev['step']}"
                + (
                    f" (baseline {ev['baseline_s']}s -> {ev['value_s']}s)"
                    if "baseline_s" in ev
                    else ""
                )
            )
    if not report.get("regressed"):
        lines.append("  no level shift latched")
    return "\n".join(lines)


def render_text(report: Dict[str, Any]) -> str:
    """Human-readable incident summary (the JSON report is the machine
    surface; this is the triage page)."""
    lines = [f"postmortem of {report['root']}"]
    lines.append(
        f"  boxes: {len(report['boxes'])} "
        f"({sum(b['torn'] for b in report['boxes'])} torn region(s) "
        "skipped — CRC-invalid tails, never trusted)"
    )
    lines.append(f"  classification: {report['classification']}")
    if report.get("victim"):
        lines.append(f"  victim: {report['victim']}")
        op = report.get("victim_inflight_op")
        if op:
            lines.append(
                f"    in-flight at death: {op.get('op', op.get('k'))} "
                f"(plane={op.get('plane', '?')}, step={op.get('st')}, "
                f"epoch={op.get('ep')})"
            )
        if report.get("victim_epoch") is not None:
            lines.append(f"    quorum epoch: {report['victim_epoch']}")
    fa = report.get("first_anomaly")
    if fa:
        lines.append(
            f"  first anomaly: {fa.get('k')} at epoch={fa.get('ep')} "
            f"step={fa.get('st')} (src={fa.get('src')})"
        )
    for src, op in sorted(report.get("survivor_inflight", {}).items()):
        lines.append(
            f"  survivor {src}: in-flight {op.get('op', op.get('k'))} "
            f"at step={op.get('st')}"
        )
    if report.get("injected_evidence"):
        sites = sorted(
            {r.get("site", "?") for r in report["injected_evidence"]}
        )
        lines.append(
            f"  injection evidence: {len(report['injected_evidence'])} "
            f"record(s) at {sites}"
        )
    for m in report.get("bundles") or []:
        trig = (m.get("trigger") or {}).get("event", "?")
        lines.append(
            f"  diagnosis bundle: {m.get('bundle')} (trigger={trig}, "
            f"replica={m.get('replica_id')}, step={m.get('step')}, "
            f"epoch={m.get('epoch')}) -> {m.get('_dir')}"
        )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m torchft_tpu.telemetry.postmortem",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("dir", help="directory holding black boxes / trails / "
                    "fault evidence (searched recursively)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="also write the full report JSON here")
    ap.add_argument("--timeline", type=int, default=0,
                    help="print the last N merged timeline records")
    ap.add_argument("--perf", action="store_true",
                    help="perf window mode: reconstruct per-replica "
                    "wall/local step series from the boxes' anatomy "
                    "ticks and run the perf-regression sentinel offline")
    ap.add_argument("--window", type=int, default=0,
                    help="--perf: analyze only the last N steps per "
                    "replica (0 = all)")
    ap.add_argument("--bundles", nargs="?", const="", default=None,
                    metavar="DIR",
                    help="fold diagnosis bundles (bundle.json dirs) into "
                    "the causal timeline; with no DIR, discover them "
                    "under the evidence dir itself")
    ap.add_argument("--conformance", action="store_true",
                    help="replay every trail/black box under the "
                    "evidence dir against the FT-protocol spec "
                    "(analysis/protocol) and flag illegal transitions; "
                    "exit 2 on any finding")
    args = ap.parse_args(argv)

    if args.perf:
        perf = perf_windows(args.dir, window=args.window)
        print(render_perf_text(perf))
        if args.json_out:
            with open(args.json_out, "w", encoding="utf-8") as f:
                json.dump(perf, f, indent=1, default=str)
            print(f"report: {args.json_out}")
        return 0

    report = analyze(args.dir, bundles_dir=args.bundles)
    print(render_text(report))
    if args.timeline:
        for rec in report["timeline"][-args.timeline:]:
            print(
                f"  [ep={rec.get('ep')} st={rec.get('st')} "
                f"q={rec.get('q', '-')}] {rec.get('src')}: {rec.get('k')}"
            )
    conformance_ok = True
    if args.conformance:
        # spec replay (ISSUE 15): every recorded lifecycle transition is
        # checked against the executable protocol spec, so a postmortem
        # doubles as a conformance proof — an incident whose records are
        # protocol-legal is an environment/injection story; an illegal
        # transition is a protocol bug with the exact record named
        from torchft_tpu.analysis.protocol import check_tree

        conf = check_tree(args.dir)
        print(conf.render())
        report["conformance"] = {
            "sources": conf.sources,
            "lifecycle_records": conf.lifecycle_records,
            "findings": [f.__dict__ for f in conf.findings],
        }
        conformance_ok = conf.ok
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1, default=str)
        print(f"report: {args.json_out}")
    if not conformance_ok:
        return 2
    return 0 if report["classification"] in ("clean", "injected") else 2


if __name__ == "__main__":
    sys.exit(main())
