"""Perf-regression sentinel — Page-Hinkley level-shift detection over the
retained time series (ISSUE 11).

The fleet already has three detectors, and each has a blind spot this one
covers:

* the **SLO** evaluator needs an absolute threshold configured
  (``TORCHFT_SLO_STEP_S``) — a fleet that drifts from 180 ms to 300 ms
  steps under a 500 ms SLO never alarms;
* the **straggler** detector compares replicas against each other — when
  the WHOLE fleet slows down together (a bad rollout, a shared-storage
  regression, thermal throttling across a rack) the leave-one-out median
  moves with it and nothing latches;
* the **watchdog** only fires on order-of-magnitude stalls.

The sentinel is threshold-free and per-replica-per-series: for each
``(replica, series)`` stream retained by the time-series store it runs a
one-sided (slower-is-bad) Page-Hinkley test — the classic sequential
level-shift statistic: ``m_t = Σ (x_i − loc_i − δ)`` with alarm when
``m_t − min(m_t) > λ``, where ``loc`` is a running MEDIAN (robust — see
:class:`PageHinkley`) and δ (the drift allowance) and λ (the
cumulative-excess latch) scale RELATIVE to that location, so one
configuration covers a 50 ms compute phase and a 2 s step wall clock
alike. A latch emits ONE ``perf_regression`` event naming the
shifted ``(replica, series)`` — for ``phase.*`` series that IS "which
replica's which phase" — bumps
``tft_perf_regression_total{replica,series}``, and clears
(``perf_regression_cleared``) only after K consecutive samples back at
the pre-shift baseline.

Knob registry (docs/observability.md "Perf regression"; enforced both
directions by the ``obs-env-drift`` analysis rule):

====================================  =====================================
``TORCHFT_REGRESSION_DELTA``          drift allowance δ as a fraction of
                                      the stream's running-median
                                      location (default 0.05)
``TORCHFT_REGRESSION_LAMBDA``        latch threshold λ as a multiple of
                                      the running-median location —
                                      cumulative excess seconds beyond δ
                                      before latching (default 3.0)
``TORCHFT_REGRESSION_MIN_N``          samples to establish a baseline
                                      before the statistic arms
                                      (default 8)
``TORCHFT_REGRESSION_K``              consecutive at-baseline samples to
                                      clear a latch (default 5)
``TORCHFT_REGRESSION_FLOOR_S``        absolute arming floor: the test
                                      stays disarmed while the stream's
                                      mean is under this many seconds —
                                      a RELATIVE detector on a 1 ms
                                      series latches on scheduler noise
                                      (default 0.02)
``TORCHFT_REGRESSION_SERIES``         comma list of series-name prefixes
                                      to watch (default
                                      ``local_s,phase.``; the barrier
                                      phases — wire / quorum_wait /
                                      commit_barrier / heal — are always
                                      excluded unless listed by exact
                                      name: they measure PEER waits, the
                                      symptom, never this replica's
                                      cause)
``TORCHFT_REGRESSION_MONITOR``        ``1`` = the Manager (rank 0) hosts a
                                      RegressionMonitor + CriticalPath
                                      monitor against its lighthouse
                                      (default 0)
``TORCHFT_REGRESSION_POLL_S``         monitor poll interval (default 2)
====================================  =====================================
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "PageHinkley",
    "RegressionDetector",
    "RegressionMonitor",
]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


class PageHinkley:
    """One-sided Page-Hinkley test for an UPWARD level shift (durations:
    up = slower = bad), with relative δ/λ, a ROBUST location estimate
    and latch/clear hysteresis.

    Two robustness choices, both learned from real traces:

    * the location estimate is a running **median** over a bounded
      window, not a mean — the first jax steps of a real trainer are
      30–40× the steady state (compile), and a mean poisoned by two
      warm-up samples sits above the shifted level for the whole run
      (observed: steady 0.09 s, warm-up 4.0 s, +0.15 s shift never
      latched against the 0.5 s running mean);
    * positive deviations are **winsorized** at 2× the location — one
      10× spike (a re-jit, a GC pause) must contribute a bounded step to
      the statistic, not an instant latch; a real level shift persists
      and accumulates past λ anyway.

    States: warming (n < min_n, or location under the floor) → armed →
    latched. While latched the pre-shift baseline is frozen (an adapting
    location would chase the shift and declare the new level normal);
    K consecutive samples back under ``baseline × (1 + δ)`` clear the
    latch and re-arm fresh."""

    WINDOW = 256  # samples kept for the running median
    CLIP = 2.0    # positive-deviation winsor, multiples of the location

    def __init__(
        self,
        delta: Optional[float] = None,
        lam: Optional[float] = None,
        min_n: Optional[int] = None,
        k: Optional[int] = None,
        floor: Optional[float] = None,
    ) -> None:
        self.delta = delta if delta is not None else _env_float(
            "TORCHFT_REGRESSION_DELTA", 0.05
        )
        self.lam = lam if lam is not None else _env_float(
            "TORCHFT_REGRESSION_LAMBDA", 3.0
        )
        self.min_n = int(min_n if min_n is not None else _env_int(
            "TORCHFT_REGRESSION_MIN_N", 8
        ))
        self.k = int(k if k is not None else _env_int(
            "TORCHFT_REGRESSION_K", 5
        ))
        self.floor = floor if floor is not None else _env_float(
            "TORCHFT_REGRESSION_FLOOR_S", 0.02
        )
        from collections import deque

        self._window: Any = deque(maxlen=self.WINDOW)
        self.n = 0
        self.location = 0.0  # running median of the window
        self._mh = 0.0
        self._mh_min = 0.0
        self.latched = False
        self.latches = 0
        self.baseline = 0.0  # frozen pre-shift location while latched
        self._under = 0

    def observe(self, x: float) -> Optional[str]:
        """Feed one sample; returns ``"latched"`` / ``"cleared"`` on a
        transition, else None."""
        from statistics import median

        if self.latched:
            # frozen baseline: recovery means returning to where the
            # stream WAS, not to wherever the shift dragged the location
            if x <= self.baseline * (1.0 + self.delta):
                self._under += 1
                if self._under >= self.k:
                    self.latched = False
                    self._under = 0
                    # re-arm fresh: the episode is over
                    self._window.clear()
                    self._window.append(x)
                    self.n = 1
                    self.location = x
                    self._mh = 0.0
                    self._mh_min = 0.0
                    return "cleared"
            else:
                self._under = 0
            return None
        self.n += 1
        self._window.append(x)
        self.location = median(self._window)
        if self.n < self.min_n:
            return None  # baseline warm-up: nothing to deviate from yet
        scale = abs(self.location)
        if scale < self.floor:
            # a relative test on a microsecond-scale stream measures
            # scheduler noise, not performance — stay disarmed (found the
            # hard way: the 1 ms commit_barrier phase false-latched the
            # control soak before this floor existed)
            self._mh = 0.0
            self._mh_min = 0.0
            return None
        dev = x - self.location - self.delta * scale
        if dev > self.CLIP * scale:
            dev = self.CLIP * scale  # winsorize: one spike, bounded step
        self._mh += dev
        self._mh_min = min(self._mh_min, self._mh)
        if (self._mh - self._mh_min) > self.lam * scale:
            self.latched = True
            self.latches += 1
            self.baseline = self.location
            self._under = 0
            return "latched"
        return None


def _watched_prefixes() -> Tuple[str, ...]:
    raw = os.environ.get("TORCHFT_REGRESSION_SERIES", "local_s,phase.")
    return tuple(p for p in (s.strip() for s in raw.split(",")) if p)


# Peer-wait phases are the SYMPTOM side of a slowdown (a slow peer
# inflates everyone else's barriers) — watching them would blame victims.
# Same reasoning as critical_path's non-barrier blame split; excluded
# from the watch unless a deployment lists one by exact name.
def _barrier_series() -> Tuple[str, ...]:
    from torchft_tpu.telemetry.anatomy import BARRIER_PHASES

    return tuple(f"phase.{p}" for p in BARRIER_PHASES)


class RegressionDetector:
    """Per-(replica, series) Page-Hinkley bank over the watched series
    prefixes. Feed with :meth:`observe`; emits ``perf_regression`` /
    ``perf_regression_cleared`` events and bumps
    ``tft_perf_regression_total{replica,series}`` on transitions."""

    def __init__(
        self,
        prefixes: Optional[Tuple[str, ...]] = None,
        **ph_kwargs: Any,
    ) -> None:
        self._ph_kwargs = ph_kwargs
        self.prefixes = (
            tuple(prefixes) if prefixes is not None else _watched_prefixes()
        )
        self._tests: Dict[Tuple[str, str], PageHinkley] = {}

    def watched(self, series: str) -> bool:
        if series in _barrier_series() and series not in self.prefixes:
            return False
        return any(series.startswith(p) for p in self.prefixes)

    def regressed(self) -> List[Tuple[str, str]]:
        """Currently latched (replica, series) pairs, sorted."""
        return sorted(
            key for key, ph in self._tests.items() if ph.latched
        )

    def observe(
        self, replica: str, series: str, step: int, value: float
    ) -> Optional[Dict[str, Any]]:
        """One sample; returns the emitted event record on a latch/clear
        transition, else None."""
        if not self.watched(series):
            return None
        key = (replica, series)
        ph = self._tests.get(key)
        if ph is None:
            ph = self._tests[key] = PageHinkley(**self._ph_kwargs)
        transition = ph.observe(value)
        if transition is None:
            return None
        # phase.<name> series name the anatomy phase directly; the rest
        # (local_s, wall_s, lat.*) name themselves
        phase = (
            series[len("phase."):] if series.startswith("phase.") else series
        )
        if transition == "latched":
            ev = {
                "event": "perf_regression",
                "replica": replica,
                "series": series,
                "phase": phase,
                "step": step,
                "baseline_s": round(ph.baseline, 6),
                "value_s": round(value, 6),
            }
            try:
                from torchft_tpu import telemetry

                telemetry.PERF_REGRESSION_TOTAL.labels(
                    replica=replica, series=series
                ).inc()
                telemetry.emit(
                    "perf_regression",
                    **{k: v for k, v in ev.items() if k != "event"},
                )
            except Exception:  # noqa: BLE001 — never fail the monitor
                pass
            return ev
        ev = {
            "event": "perf_regression_cleared",
            "replica": replica,
            "series": series,
            "phase": phase,
            "step": step,
            "value_s": round(value, 6),
        }
        try:
            from torchft_tpu import telemetry

            telemetry.emit(
                "perf_regression_cleared",
                **{k: v for k, v in ev.items() if k != "event"},
            )
        except Exception:  # noqa: BLE001
            pass
        return ev


class RegressionMonitor:
    """Fleet-side host: polls the lighthouse's ``/timeseries.json`` and
    feeds every new sample of the watched series to a
    :class:`RegressionDetector`, in step order per stream. Run one per
    fleet (the faultmatrix runner hosts one; a Manager hosts one under
    ``TORCHFT_REGRESSION_MONITOR=1``)."""

    def __init__(
        self,
        lighthouse_addr: str,
        detector: Optional[RegressionDetector] = None,
        poll_s: Optional[float] = None,
    ) -> None:
        self.addr = lighthouse_addr
        self.detector = detector or RegressionDetector()
        self.poll_s = poll_s if poll_s is not None else _env_float(
            "TORCHFT_REGRESSION_POLL_S", 2.0
        )
        self._cursor: Dict[Tuple[str, str], int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(
        self, reply: Optional[Dict[str, Any]] = None
    ) -> List[Dict[str, Any]]:
        """One poll + detection round; returns the transition events
        emitted (also the testable core). Pass ``reply`` to reuse a
        /timeseries.json fetch another consumer already paid for (the
        Manager's history thread feeds this monitor and the critical-path
        monitor from ONE poll — the full-ring reply can be megabytes)."""
        from torchft_tpu.telemetry.timeseries import (
            iter_new_samples,
            poll_timeseries,
        )

        if reply is None:
            reply = poll_timeseries(self.addr)
        if not reply:
            return []
        events: List[Dict[str, Any]] = []
        for rid, name, _epoch, step, value in iter_new_samples(
            reply, self._cursor
        ):
            ev = self.detector.observe(rid, name, step, value)
            if ev is not None:
                events.append(ev)
        return events

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — monitoring must not die
                pass

    def start(self) -> "RegressionMonitor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="tft_regression_monitor"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_s + 2.0)
            self._thread = None
