"""Latch-triggered deep-capture engine — the detection→diagnosis bridge
(ISSUE 12).

The fleet already *detects* well: straggler latches, burn-rate SLOs, the
step watchdog, the divergence sentinel and the perf-regression sentinel
all fire precise, debounced events. But each one bottoms out at phase
granularity — "train_bytes_1 lost 150 ms/step in compute" — and nothing
can say *which code*. This module closes that gap the way production
fleets do (Google-Wide-Profiler-style): the profilers are ALWAYS ON at
low Hz (:mod:`torchft_tpu.telemetry.profiler`, ``native/profiler.h``),
and a latch event triggers a **bounded deep capture** instead of a human
attaching a profiler after the fact.

One :class:`DiagnosisEngine` per process (the Manager hosts it whenever
``TORCHFT_DIAG_DIR`` is set). It subscribes to the live event trail and,
on any of the five latch events —

    ``straggler_detected``, ``perf_regression``, ``slo_breach``,
    ``watchdog_stall``, ``divergence_detected``

— debounced **once per episode** (re-armed by the matching ``*_cleared``
event, or after ``TORCHFT_DIAG_REARM_S`` for latches that never clear),
writes a **diagnosis bundle** under ``TORCHFT_DIAG_DIR``:

``bundle.json``
    trigger record, (epoch, step, seq) coordinates, capture window,
    lathist p50/p99 deltas over the window, the flight-recorder
    hang-localization digest, and (when a lighthouse is known) the
    tsdb window around onset;
``native.folded`` / ``python.folded``
    collapsed stacks captured DURING the window with both samplers
    boosted to ``TORCHFT_PROF_BURST_HZ`` (exact snapshot diffs — see
    ``subtract_folded``), flamegraph-ready;
``flight.json``
    the full flight-recorder ring at capture time;
``jax_trace/``
    a bounded ``jax.profiler.trace`` of the compute phase
    (``TORCHFT_DIAG_JAX=1`` only).

Events that name a *different* replica (a fleet monitor latching some
other group) are ignored — the victim captures its own evidence, which
is the only process whose stacks answer the question. Each capture emits
``diagnosis_captured`` + ``tft_diagnosis_bundles_total`` and is announced
on the quorum piggyback (``diag_bundles``/``diag_last``) so the
lighthouse's ``GET /diagnosis.json`` indexes the fleet's evidence.

Knob registry (docs/observability.md "Profiling & diagnosis bundles"):
``TORCHFT_DIAG_DIR``, ``TORCHFT_DIAG_WINDOW_S``, ``TORCHFT_DIAG_REARM_S``,
``TORCHFT_DIAG_JAX``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "TRIGGER_EVENTS",
    "DiagnosisEngine",
    "diag_dir",
    "read_bundles",
]

# trigger → the event that ends its episode (None = never clears on its
# own; the engine re-arms after TORCHFT_DIAG_REARM_S instead)
TRIGGER_EVENTS: Dict[str, Optional[str]] = {
    "straggler_detected": "straggler_cleared",
    "perf_regression": "perf_regression_cleared",
    "slo_breach": "slo_recovered",
    "watchdog_stall": None,
    "divergence_detected": None,
}

_CLEAR_TO_TRIGGER = {
    clear: trig for trig, clear in TRIGGER_EVENTS.items() if clear
}

DEFAULT_WINDOW_S = 3.0
DEFAULT_REARM_S = 600.0

# One capture in flight per PROCESS, not per engine: the burst boost
# mutates the shared global samplers (PROFILER / the native plane), so
# two engines racing a subject-less latch (divergence_detected triggers
# every installed engine) would each save the OTHER's burst rate as its
# "pre-burst" value — leaving the fleet sampling at burst Hz forever —
# and write duplicate bundles for one incident. Non-blocking: a loser
# stays latched (debounced) and the in-flight bundle carries the
# window's evidence.
_CAPTURE_MU = threading.Lock()


def diag_dir() -> Optional[str]:
    """The bundle directory; None disarms the whole plane (the default
    deployment pays nothing)."""
    return os.environ.get("TORCHFT_DIAG_DIR") or None


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _subject(record: Dict[str, Any]) -> Optional[str]:
    """The replica/group a latch event names (None = process-local
    event like watchdog_stall / slo_breach / divergence_detected)."""
    s = record.get("group") or record.get("replica")
    return str(s) if s else None


def _episode_key(kind: str, record: Dict[str, Any]) -> tuple:
    """The debounce key: one episode per (trigger, subject, stream).
    The stream discriminator keeps DISTINCT latches independent — the
    two SLOs (step_time / rejoin_commit) share one event kind, and a
    perf_regression on wall_s is a different episode than one on
    phase.compute; without it, a rejoin breach would be swallowed by a
    live step_time episode and its recovery would re-arm the wrong
    latch."""
    return (
        kind,
        _subject(record),
        record.get("slo") or record.get("series"),
    )


def _lathist_delta_quantiles(
    after: Dict[str, Any], before: Dict[str, Any]
) -> Dict[str, Any]:
    """Per-op p50/p99 of ONLY the window's observations: both snapshots
    are cumulative on the shared log2 grid, so the window's histogram is
    an exact per-bucket subtraction."""
    from torchft_tpu.telemetry.anatomy import lathist_quantile

    out: Dict[str, Any] = {}
    for op, h1 in (after or {}).items():
        h0 = (before or {}).get(op) or {}
        c1 = list(h1.get("counts") or [])
        c0 = list(h0.get("counts") or [0] * len(c1))
        if len(c0) != len(c1):
            continue
        window = [max(0, a - b) for a, b in zip(c1, c0)]
        count = sum(window)
        entry: Dict[str, Any] = {
            "count_window": int(count),
            "p50_s_total": round(lathist_quantile(h1, 0.5), 6),
            "p99_s_total": round(lathist_quantile(h1, 0.99), 6),
        }
        if count:
            wh = {"counts": window, "count": count}
            entry["p50_s_window"] = round(lathist_quantile(wh, 0.5), 6)
            entry["p99_s_window"] = round(lathist_quantile(wh, 0.99), 6)
        out[op] = entry
    return out


class DiagnosisEngine:
    """See the module docstring. ``synchronous=True`` runs captures
    inline on the emitting thread (tests); production captures run on a
    daemon thread so a latch never blocks the step path."""

    def __init__(
        self,
        directory: Optional[str] = None,
        replica_id: str = "",
        lighthouse_addr: Optional[str] = None,
        window_s: Optional[float] = None,
        burst_hz: Optional[float] = None,
        rearm_s: Optional[float] = None,
        synchronous: bool = False,
        clock=time.monotonic,
    ) -> None:
        from torchft_tpu.telemetry.profiler import burst_hz as _burst

        self.directory = directory or diag_dir()
        self.replica_id = replica_id
        self.lighthouse_addr = lighthouse_addr
        self.window_s = (
            window_s
            if window_s is not None
            else _env_float("TORCHFT_DIAG_WINDOW_S", DEFAULT_WINDOW_S)
        )
        self.burst_hz = burst_hz if burst_hz is not None else _burst()
        self.rearm_s = (
            rearm_s
            if rearm_s is not None
            else _env_float("TORCHFT_DIAG_REARM_S", DEFAULT_REARM_S)
        )
        self.synchronous = synchronous
        self._clock = clock
        self._lock = threading.Lock()
        # (trigger, subject) → latch monotonic ts. guarded-by: _lock
        self._episodes: Dict[Any, float] = {}
        self._seq = 0  # guarded-by: _lock
        self.bundles: List[str] = []  # bundle names, oldest first
        self.last_bundle: Optional[str] = None
        self._installed = False

    # -- wiring ----------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return bool(self.directory)

    def install(self) -> "DiagnosisEngine":
        """Subscribe to the live event trail (idempotent)."""
        if not self._installed and self.enabled:
            from torchft_tpu.telemetry import EVENTS

            EVENTS.subscribe(self.on_event)
            self._installed = True
        return self

    def remove(self) -> None:
        if self._installed:
            from torchft_tpu.telemetry import EVENTS

            EVENTS.unsubscribe(self.on_event)
            self._installed = False

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    # -- trigger path (runs on the emitting thread: keep it cheap) ------

    def on_event(self, record: Dict[str, Any]) -> None:
        kind = record.get("event")
        if kind in _CLEAR_TO_TRIGGER:
            # the episode is over: re-arm this trigger for its subject
            # (+ stream — the *_cleared events carry the same slo/series
            # fields their latches do)
            key = _episode_key(_CLEAR_TO_TRIGGER[kind], record)
            with self._lock:
                self._episodes.pop(key, None)
            return
        if kind not in TRIGGER_EVENTS or not self.enabled:
            return
        subject = _subject(record)
        if subject is not None and self.replica_id:
            # a fleet monitor here may latch SOME OTHER group — only the
            # named victim captures (its stacks are the evidence). Match
            # prefix both ways: detector subjects come from /cluster.json
            # ids, which carry the same example-chosen prefix.
            if not (
                subject.startswith(self.replica_id)
                or self.replica_id.startswith(subject)
            ):
                return
        now = self._clock()
        key = _episode_key(kind, record)
        with self._lock:
            latched_at = self._episodes.get(key)
            if latched_at is not None:
                rearm = (
                    TRIGGER_EVENTS[kind] is None
                    and now - latched_at >= self.rearm_s
                )
                if not rearm:
                    return  # once per episode
            self._episodes[key] = now
        if not _CAPTURE_MU.acquire(blocking=False):
            # a capture is already running (this engine or another in
            # the process) for another latch; this episode stays latched
            # (debounced) and the in-flight bundle carries the fleet's
            # evidence for the window
            return
        with self._lock:
            self._seq += 1
            seq = self._seq
        if self.synchronous:
            self._capture(dict(record), seq)
        else:
            try:
                threading.Thread(
                    target=self._capture,
                    args=(dict(record), seq),
                    daemon=True,
                    name="tft_diagnosis_capture",
                ).start()
            except Exception:  # noqa: BLE001 — thread exhaustion is
                # exactly the distressed-fleet state diagnosis targets:
                # a failed start must release the in-flight guard, or
                # every future latch is silently ignored forever
                _CAPTURE_MU.release()

    # -- capture ---------------------------------------------------------

    def _capture(self, trigger: Dict[str, Any], seq: int) -> None:
        try:
            self._capture_inner(trigger, seq)
        except Exception:  # noqa: BLE001 — diagnosis must never crash
            pass           # the process it is diagnosing
        finally:
            _CAPTURE_MU.release()

    def _capture_inner(self, trigger: Dict[str, Any], seq: int) -> None:
        from torchft_tpu.telemetry import BLACKBOX, FLIGHT
        from torchft_tpu.telemetry import profiler as prof

        t_wall = time.time()
        coords = BLACKBOX.context()
        # pid in the name: a process-local event (e.g. divergence) can
        # capture on EVERY replica sharing one fleet TORCHFT_DIAG_DIR in
        # the same wall-clock second — same-named dirs would silently
        # merge (makedirs exist_ok) and overwrite each other's evidence
        name = "diag_{:.0f}_{}_{}_{}".format(
            t_wall, trigger.get("event", "manual"), os.getpid(), seq
        )
        bundle_dir = os.path.join(self.directory, name)
        os.makedirs(bundle_dir, exist_ok=True)

        lat_before = self._lathist()
        native_before = prof.native_folded()
        py_before = prof.PROFILER.folded()

        # boost both samplers for the window, restore after — to their
        # PRE-burst rates, not the env default: a rate someone set live
        # (including a deliberate disarm) must survive a capture
        restore_py = prof.PROFILER.hz
        restore_native = prof.native_hz()
        prof.PROFILER.set_hz(self.burst_hz)
        native_armed = prof.native_set_hz(self.burst_hz)
        jax_dir = None
        try:
            jax_dir = prof.capture_jax_trace(
                os.path.join(bundle_dir, "jax_trace"), self.window_s
            )
            if jax_dir is None:
                time.sleep(self.window_s)
        finally:
            prof.PROFILER.set_hz(restore_py)
            if native_armed:
                prof.native_set_hz(
                    restore_native
                    if restore_native is not None
                    else prof.env_hz()
                )

        native_folded = prof.subtract_folded(
            prof.native_folded(), native_before
        )
        py_folded = prof.subtract_folded(prof.PROFILER.folded(), py_before)
        lat_after = self._lathist()
        prof.poll_native_samples()

        flight_entries = FLIGHT.snapshot()
        tsdb_window = None
        if self.lighthouse_addr:
            from torchft_tpu.telemetry.timeseries import poll_timeseries

            tsdb_window = poll_timeseries(
                self.lighthouse_addr, max_points=256
            )

        self._write(bundle_dir, "native.folded", native_folded)
        self._write(bundle_dir, "python.folded", py_folded)
        self._write(
            bundle_dir,
            "flight.json",
            json.dumps(
                {"entries": flight_entries, **FLIGHT.analyze(flight_entries)},
                default=str,
            ),
        )
        meta = {
            "schema": 1,
            "bundle": name,
            "ts": round(t_wall, 3),
            "replica_id": self.replica_id or coords.get("replica_id"),
            # the same clock-sync-free coordinates every other forensic
            # surface orders by — postmortem --bundles merges on these
            "epoch": coords.get("epoch"),
            "step": trigger.get("step", coords.get("step")),
            "seq": coords.get("seq"),
            "trigger": trigger,
            "window_s": self.window_s,
            "burst_hz": self.burst_hz,
            "native_armed": native_armed,
            "jax_trace": bool(jax_dir),
            "lathist": _lathist_delta_quantiles(lat_after, lat_before),
            "files": {
                "native_folded": "native.folded",
                "python_folded": "python.folded",
                "flight": "flight.json",
                "jax_trace": "jax_trace" if jax_dir else None,
            },
        }
        if tsdb_window is not None:
            self._write(
                bundle_dir, "tsdb.json", json.dumps(tsdb_window, default=str)
            )
            meta["files"]["tsdb"] = "tsdb.json"
        self._write(bundle_dir, "bundle.json", json.dumps(meta, default=str))

        self.bundles.append(name)
        self.last_bundle = name
        try:
            from torchft_tpu import telemetry

            telemetry.DIAGNOSIS_BUNDLES.labels(
                trigger=trigger.get("event", "manual")
            ).inc()
            telemetry.emit(
                "diagnosis_captured",
                trigger=trigger.get("event"),
                bundle=name,
                path=bundle_dir,
                step=meta["step"],
                epoch=meta["epoch"],
                window_s=self.window_s,
            )
        except Exception:  # noqa: BLE001
            pass

    @staticmethod
    def _lathist() -> Dict[str, Any]:
        try:
            from torchft_tpu import _native

            return _native.lathist_snapshot()
        except Exception:  # noqa: BLE001 — native plane optional
            return {}

    @staticmethod
    def _write(bundle_dir: str, fname: str, text: str) -> None:
        try:
            with open(
                os.path.join(bundle_dir, fname), "w", encoding="utf-8"
            ) as f:
                f.write(text)
        except OSError:
            pass  # a full disk must not fail the capture thread


def load_bundle_meta(bundle_dir: str) -> Optional[Dict[str, Any]]:
    """Load ONE bundle directory's ``bundle.json`` (stamped with
    ``_dir``); None for torn/malformed/absent bundles. The single
    reader behind :func:`read_bundles` and the postmortem ``--bundles``
    collector — one place to evolve when the schema does."""
    path = os.path.join(bundle_dir, "bundle.json")
    if not os.path.isfile(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return None
    meta["_dir"] = bundle_dir
    return meta


def read_bundles(directory: str) -> List[Dict[str, Any]]:
    """Load every bundle's ``bundle.json`` under ``directory`` (the
    one-level layout the engine writes), ordered by capture time. Torn
    or malformed bundles are skipped; the faultmatrix assertions read
    through this."""
    out: List[Dict[str, Any]] = []
    if not directory or not os.path.isdir(directory):
        return out
    for entry in sorted(os.listdir(directory)):
        meta = load_bundle_meta(os.path.join(directory, entry))
        if meta is not None:
            out.append(meta)
    out.sort(key=lambda m: m.get("ts", 0.0))
    return out
