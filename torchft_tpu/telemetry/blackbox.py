"""Crash-durable per-process black box — the forensic substrate.

Everything the live telemetry plane records dies with the process: the
event trail's file sink survives, but the in-memory flight ring, the
anatomy rows and the native breadcrumbs are gone the instant a worker is
SIGKILLed — and the ROADMAP's churn-corruption item plus PR 2's open
checksum-divergence mode are exactly the failures whose only witness IS
the dead process. This module mirrors the live planes into an **mmap'd
ring file**: pages dirtied through an mmap survive any process death
(SIGKILL, SIGSEGV, a glibc abort — the kernel owns the page cache), so a
post-mortem reader recovers everything written up to the torn tail with
zero cooperation from the victim. That is the flight-data-recorder
discipline production FT systems pair with per-step fault tolerance, and
the only forensic channel available under the jaxlib-can't-be-ASan'd
constraint (docs/fault_injection.md).

**File layout** (``TORCHFT_BLACKBOX_DIR/tft_bb_<pid>.bb``)::

    header (64 B): b"TFTBBPY1" | u32 size | u32 pid | u64 created_ns | pad
    ring   (size - 64 B): 4-byte-aligned frames, written circularly

    frame: u32 magic (0x42425446 "TFBB") | u32 payload_len |
           u32 crc32(payload) | payload (JSON, padded to 4 B)

Each payload is a compact JSON object carrying the clock-sync-free
coordinates ``{"q": seq, "ep": quorum_epoch, "st": step, "ts": wall,
"k": kind, ...fields}`` — ``q`` is this process's monotone record
counter, so a reader can order records exactly even after the ring
wrapped. Recovery scans the whole ring: a frame whose CRC fails (the
torn tail of a mid-write death, or a half-overwritten older lap) is
skipped, never trusted — the reader resynchronizes on the next aligned
magic and keeps going, so one torn record costs one record.

The native plane writes its own sibling ring
(``tft_bb_<pid>_native.bb``, fixed 64-byte binary records — see
``native/blackbox.h``); :func:`read_native_blackbox` parses it here.
Both are merged by ``python -m torchft_tpu.telemetry.postmortem``.

Armed by ``TORCHFT_BLACKBOX_DIR`` (or :meth:`BlackBox.configure`);
disarmed, :meth:`BlackBox.record` is one cached attribute check. Ring
bytes: ``TORCHFT_BLACKBOX_SIZE`` (default 1 MiB, shared with the native
ring's sizing). Stdlib-only; never raises on the record path.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "ENV_BLACKBOX_DIR",
    "ENV_BLACKBOX_SIZE",
    "BlackBox",
    "BLACKBOX",
    "blackbox_dir",
    "read_blackbox",
    "read_native_blackbox",
    "NATIVE_SITES_BB",
]

ENV_BLACKBOX_DIR = "TORCHFT_BLACKBOX_DIR"
ENV_BLACKBOX_SIZE = "TORCHFT_BLACKBOX_SIZE"

_HEADER_MAGIC = b"TFTBBPY1"
_HEADER_SIZE = 64
_FRAME_MAGIC = 0x42425446  # "TFBB" little-endian
_FRAME = struct.Struct("<III")  # magic, payload_len, crc32(payload)
_DEFAULT_SIZE = 1 << 20
_MAX_PAYLOAD = 1 << 16  # one record must never eat the whole ring

# native/blackbox.h record layout (64 B, crc32 over the first 56 B) —
# keep in byte-for-byte lockstep with struct Rec there
_NATIVE_HEADER_MAGIC = b"TFTBBNA1"
_NATIVE_REC = struct.Struct("<IHHQQqqqqII")
_NATIVE_REC_SIZE = 64
assert _NATIVE_REC.size == _NATIVE_REC_SIZE

# native site ids (native/blackbox.h Site enum) -> names; the postmortem
# timeline uses these as record kinds
NATIVE_SITES_BB = {
    1: "dp.hop",
    2: "dp.stripe",
    3: "rpc.serve",
    4: "quorum.publish",
    5: "quorum.deliver",
    6: "commit.decision",
    7: "divergence",
}


def blackbox_dir() -> Optional[str]:
    """The armed black-box directory, or None when the plane is off."""
    return os.environ.get(ENV_BLACKBOX_DIR) or None


def _ring_size() -> int:
    try:
        size = int(os.environ.get(ENV_BLACKBOX_SIZE, str(_DEFAULT_SIZE)))
    except ValueError:
        size = _DEFAULT_SIZE
    return max(4096, size)


class BlackBox:
    """Crash-durable mmap'd record ring (see module docstring).

    One process-wide instance (:data:`BLACKBOX`) mirrors the event
    trail, the flight recorder and the anatomy ledger; the Manager keeps
    its ``(replica_id, step, quorum_epoch)`` context current via
    :meth:`set_context` so every record carries the coordinates the
    postmortem merge orders by."""

    def __init__(self, path: Optional[str] = None) -> None:
        self._lock = threading.Lock()
        self._mm: Optional[mmap.mmap] = None
        self._size = 0
        self._off = _HEADER_SIZE
        self._seq = 0
        self._env_checked = False
        self._replica_id = ""
        self._step = -1
        self._epoch = -1
        self.path: Optional[str] = None
        if path:
            self.configure(path)

    # -- arming ----------------------------------------------------------

    def configure(self, path: Optional[str]) -> bool:
        """Open (or reopen) the ring at ``path``; ``None`` disarms.
        Returns whether the box is armed afterwards."""
        with self._lock:
            self._close_locked()
            self._env_checked = True  # explicit config wins over env
            if path is None:
                return False
            return self._open_locked(path)

    def _maybe_open_from_env(self) -> None:
        # called under self._lock
        if self._env_checked:
            return
        self._env_checked = True
        d = blackbox_dir()
        if not d:
            return
        self._open_locked(os.path.join(d, f"tft_bb_{os.getpid()}.bb"))

    def _open_locked(self, path: str) -> bool:
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            size = _ring_size()
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
            try:
                os.ftruncate(fd, size)
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
            header = _HEADER_MAGIC + struct.pack(
                "<IIQ", size, os.getpid(), time.time_ns()
            )
            self._mm[0:_HEADER_SIZE] = header.ljust(_HEADER_SIZE, b"\0")
            self._size = size
            self._off = _HEADER_SIZE
            self.path = path
            return True
        except (OSError, ValueError):
            # forensics must never take down training
            self._mm = None
            self.path = None
            return False

    def enabled(self) -> bool:
        with self._lock:
            self._maybe_open_from_env()
            return self._mm is not None

    # -- context ---------------------------------------------------------

    def set_context(
        self,
        replica_id: Optional[str] = None,
        step: Optional[int] = None,
        quorum_epoch: Optional[int] = None,
    ) -> None:
        """Update the coordinates stamped on subsequent records; a
        replica change additionally writes a ``ctx`` record so the
        postmortem reader can attribute the box to a replica."""
        emit_ctx = False
        with self._lock:
            if replica_id is not None and replica_id != self._replica_id:
                self._replica_id = replica_id
                emit_ctx = True
            if step is not None:
                self._step = int(step)
            if quorum_epoch is not None:
                self._epoch = int(quorum_epoch)
        if emit_ctx:
            self.record("ctx", replica=replica_id)

    def context(self) -> Dict[str, Any]:
        """The current clock-sync-free coordinates (replica, epoch, step,
        seq) — the diagnosis engine stamps bundles with these so capture
        evidence merges onto the same timeline as everything else."""
        with self._lock:
            return {
                "replica_id": self._replica_id,
                "epoch": self._epoch,
                "step": self._step,
                "seq": self._seq,
            }

    # -- producer --------------------------------------------------------

    def record(self, kind: str, **fields: Any) -> None:
        """Append one record; silently drops on any failure (a full disk
        or a serialization surprise must never fail a step)."""
        # disarmed fast path, no lock: this rides every collective-op
        # record. The unsynchronized read is safe — worst case a racing
        # configure() costs one early record, never corruption (all real
        # state changes happen under the lock below).
        if self._mm is None and self._env_checked:
            return
        try:
            with self._lock:
                self._maybe_open_from_env()
                mm = self._mm
                if mm is None:
                    return
                self._seq += 1
                payload = json.dumps(
                    {
                        "q": self._seq,
                        "ep": self._epoch,
                        "st": self._step,
                        "ts": round(time.time(), 6),
                        "k": kind,
                        **fields,
                    },
                    separators=(",", ":"),
                    default=str,
                ).encode()
                if len(payload) > _MAX_PAYLOAD:
                    payload = payload[:_MAX_PAYLOAD]  # torn JSON: reader skips
                pad = (-len(payload)) % 4
                frame_len = _FRAME.size + len(payload) + pad
                if frame_len > self._size - _HEADER_SIZE:
                    return
                if self._off + frame_len > self._size:
                    # wrap: zero the stub so the reader's magic scan can't
                    # resurrect a stale frame header at the old offset
                    mm[self._off : self._size] = b"\0" * (
                        self._size - self._off
                    )
                    self._off = _HEADER_SIZE
                off = self._off
                # payload first, CRC+magic last: a death mid-write leaves
                # a frame whose CRC cannot validate — torn-tail tolerance
                # is by construction, not by luck
                mm[off + _FRAME.size : off + _FRAME.size + len(payload)] = (
                    payload
                )
                if pad:
                    mm[
                        off + _FRAME.size + len(payload) :
                        off + _FRAME.size + len(payload) + pad
                    ] = b"\0" * pad
                _FRAME.pack_into(
                    mm, off, _FRAME_MAGIC, len(payload),
                    zlib.crc32(payload) & 0xFFFFFFFF,
                )
                self._off = off + frame_len
        except Exception:  # noqa: BLE001 — never fail the caller
            pass

    def close(self) -> None:
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        if self._mm is not None:
            try:
                self._mm.close()
            except (OSError, ValueError):
                pass
        self._mm = None
        self.path = None


# Process-wide box: events.py, flight.py and anatomy.py mirror into it.
BLACKBOX = BlackBox()


def read_blackbox(path: str) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Recover a Python black box: returns ``(records, meta)``.

    Records are CRC-valid payloads in ``q`` order (the ring may have
    wrapped, so file order is not record order). ``meta`` carries
    ``pid``, ``torn`` (number of invalid/garbage regions skipped — a
    SIGKILL mid-write shows up here, never as a corrupt record) and
    ``replica`` (from the latest ``ctx`` record)."""
    with open(path, "rb") as f:
        raw = f.read()
    meta: Dict[str, Any] = {"path": path, "pid": None, "torn": 0,
                            "replica": ""}
    records: List[Dict[str, Any]] = []
    if len(raw) < _HEADER_SIZE or raw[:8] != _HEADER_MAGIC:
        meta["torn"] = 1
        return records, meta
    size, pid, _created = struct.unpack_from("<IIQ", raw, 8)
    meta["pid"] = pid
    size = min(size, len(raw))
    off = _HEADER_SIZE
    in_garbage = False
    while off + _FRAME.size <= size:
        magic, plen, crc = _FRAME.unpack_from(raw, off)
        if (
            magic == _FRAME_MAGIC
            and 0 < plen <= _MAX_PAYLOAD
            and off + _FRAME.size + plen <= size
        ):
            payload = raw[off + _FRAME.size : off + _FRAME.size + plen]
            if zlib.crc32(payload) & 0xFFFFFFFF == crc:
                try:
                    rec = json.loads(payload.decode())
                except ValueError:
                    rec = None
                if isinstance(rec, dict):
                    records.append(rec)
                    off += _FRAME.size + plen + ((-plen) % 4)
                    in_garbage = False
                    continue
        # invalid frame: count one torn region per contiguous run and
        # resynchronize on the next aligned candidate magic
        if not in_garbage and magic != 0:
            meta["torn"] += 1
        in_garbage = magic != 0
        off += 4
    records.sort(key=lambda r: r.get("q", 0))
    for rec in records:
        if rec.get("k") == "ctx" and rec.get("replica"):
            meta["replica"] = rec["replica"]
    return records, meta


def read_native_blackbox(
    path: str,
) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Recover a native breadcrumb ring (``native/blackbox.h`` format):
    fixed 64-byte records, CRC32 over the first 56 bytes, ordered by the
    lock-free global ``seq``. Same ``(records, meta)`` contract as
    :func:`read_blackbox`; each record gets a ``k`` from the native site
    id so the postmortem merge treats both formats uniformly."""
    with open(path, "rb") as f:
        raw = f.read()
    meta: Dict[str, Any] = {"path": path, "pid": None, "torn": 0,
                            "replica": "", "native": True}
    records: List[Dict[str, Any]] = []
    if len(raw) < _HEADER_SIZE or raw[:8] != _NATIVE_HEADER_MAGIC:
        meta["torn"] = 1
        return records, meta
    _cap, pid = struct.unpack_from("<II", raw, 8)
    meta["pid"] = pid
    off = _HEADER_SIZE
    while off + _NATIVE_REC_SIZE <= len(raw):
        (magic, site, _flags, seq, ts_ns, epoch, step, a, b, crc,
         _pad) = _NATIVE_REC.unpack_from(raw, off)
        if magic == 0x4242544E:  # "NTBB"
            if zlib.crc32(raw[off : off + 56]) & 0xFFFFFFFF == crc:
                records.append(
                    {
                        "q": seq,
                        "ep": epoch,
                        "st": step,
                        "ts": ts_ns / 1e9,
                        "k": NATIVE_SITES_BB.get(site, f"native.{site}"),
                        "a": a,
                        "b": b,
                        "native": True,
                    }
                )
            else:
                meta["torn"] += 1
        off += _NATIVE_REC_SIZE
    records.sort(key=lambda r: r.get("q", 0))
    return records, meta
