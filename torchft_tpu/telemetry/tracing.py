"""Distributed spans for the FT runtime — the cross-replica timeline.

PR 1's metrics/event-trail answer "how many, how long"; spans answer
"what overlapped what, across which replicas". Every quorum RPC, heal
send/recv, checkpoint transfer and commit barrier is a span carrying a
``trace_id`` of the form ``replica_id:step:quorum_epoch`` — because the
step counter and quorum epoch are *globally agreed* values, spans emitted
by different replicas for the same step/epoch correlate with no clock
sync beyond wall-clock timestamps. Context propagates between replicas
through RPC metadata (:meth:`Tracer.inject` / carrier dicts), so e.g. a
checkpoint GET served for a healing peer records the healer's span as
its parent.

Spans export two ways:

* JSONL (one span per line, ``TORCHFT_TRACE_PATH`` env or
  :meth:`Tracer.configure`) — grep/jq-friendly, merge-friendly;
* Chrome trace-event JSON (:meth:`Tracer.chrome_events` /
  :func:`chrome_trace`) — open in Perfetto (https://ui.perfetto.dev) or
  ``chrome://tracing``; the lighthouse's ``GET /trace`` serves the whole
  cluster merged on one timeline (replicas piggyback recent span batches
  on their quorum traffic — see ``docs/observability.md``).

Design constraints match the rest of the package: stdlib-only, no jax
import, exception-free on the hot path (a tracing bug must never fail a
step), and cheap when idle (span entry/exit is a couple of dict ops).
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from collections import deque
from typing import Any, Deque, Dict, List, Optional

__all__ = [
    "Span",
    "Tracer",
    "TRACER",
    "chrome_trace",
    "ENV_TRACE_PATH",
]

ENV_TRACE_PATH = "TORCHFT_TRACE_PATH"
ENV_TRACE_RING = "TORCHFT_TRACE_RING"


def _ring_size() -> int:
    try:
        return max(16, int(os.environ.get(ENV_TRACE_RING, "4096")))
    except ValueError:
        return 4096


def _stable_pid(replica_id: str) -> int:
    """Deterministic Chrome-trace pid for a replica: the merged cluster
    trace groups each replica's spans into its own process lane even
    though the events were recorded on different hosts."""
    if not replica_id:
        return os.getpid()
    return zlib.crc32(replica_id.encode()) & 0x7FFFFFFF


class Span:
    """One recorded operation: name, trace identity, parent link, wall
    timestamps. Created via :meth:`Tracer.span`; attributes set inside the
    ``with`` block land in ``attrs``."""

    __slots__ = (
        "name",
        "trace_id",
        "span_id",
        "parent_id",
        "replica_id",
        "ts",
        "dur_s",
        "tid",
        "attrs",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str],
        replica_id: str,
    ) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.replica_id = replica_id
        self.ts = time.time()
        self.dur_s = 0.0
        self.tid = threading.get_ident() & 0x7FFFFFFF
        self.attrs: Dict[str, Any] = {}

    def set(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "ts": self.ts,
            "dur_s": round(self.dur_s, 6),
            "replica_id": self.replica_id,
            "tid": self.tid,
        }
        if self.parent_id:
            d["parent_id"] = self.parent_id
        if self.attrs:
            d["attrs"] = self.attrs
        return d

    def to_chrome(self) -> Dict[str, Any]:
        """Chrome trace-event ("X" complete event, microsecond clock)."""
        return _chrome_event(self.to_dict())


def _chrome_event(d: Dict[str, Any]) -> Dict[str, Any]:
    """One span dict -> one Chrome trace "X" event — the single place the
    event shape is defined (Span.to_chrome, chrome_events and the
    piggyback fragments all go through here)."""
    args = dict(d.get("attrs", {}))
    args["trace_id"] = d.get("trace_id", "")
    args["span_id"] = d.get("span_id", "")
    if d.get("parent_id"):
        args["parent_id"] = d["parent_id"]
    return {
        "name": d.get("name", "?"),
        "cat": "tft",
        "ph": "X",
        "ts": float(d.get("ts", 0.0)) * 1e6,
        "dur": max(float(d.get("dur_s", 0.0)), 0.0) * 1e6,
        "pid": _stable_pid(d.get("replica_id", "")),
        "tid": int(d.get("tid", 0)),
        "args": args,
    }


def _chrome_process_name(replica_id: str) -> Dict[str, Any]:
    """Metadata event naming a replica's process lane."""
    return {
        "name": "process_name",
        "ph": "M",
        "pid": _stable_pid(replica_id),
        "tid": 0,
        "args": {"name": replica_id},
    }


class _SpanCtx:
    """Context manager produced by :meth:`Tracer.span`."""

    def __init__(self, tracer: "Tracer", span: Span) -> None:
        self._tracer = tracer
        self.span = span
        self._t0 = time.perf_counter()

    def __enter__(self) -> Span:
        self._tracer._push(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> None:
        self.span.dur_s = time.perf_counter() - self._t0
        if exc is not None:
            self.span.attrs.setdefault("error", repr(exc))
        self._tracer._pop(self.span)
        self._tracer._record(self.span)
        return None  # never swallow exceptions


class Tracer:
    """Process-wide span recorder with carrier-based context propagation.

    The process context (``replica_id``, ``step``, ``quorum_epoch``) is set
    by the Manager at each step boundary; spans created without an explicit
    ``trace_id`` inherit it. Thread-local span stacks give implicit
    parent/child nesting; cross-process links use :meth:`inject` (producer)
    and the ``parent=`` carrier argument (consumer)."""

    def __init__(self, maxlen: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        n = maxlen or _ring_size()
        self._ring: Deque[Dict[str, Any]] = deque(maxlen=n)
        # spans not yet shipped to the lighthouse (piggyback batches)
        self._pending: Deque[Span] = deque(maxlen=n)
        self._last_batch: List[Span] = []
        self._tls = threading.local()
        self._seq = 0
        self._file = None
        self._path: Optional[str] = None
        self._env_checked = False
        self._ctx: Dict[str, Any] = {
            "replica_id": "",
            "step": -1,
            "quorum_epoch": -1,
        }

    # -- context ---------------------------------------------------------

    def set_context(
        self,
        replica_id: Optional[str] = None,
        step: Optional[int] = None,
        quorum_epoch: Optional[int] = None,
    ) -> None:
        """Update the process trace context (Manager calls this at quorum
        start and whenever the epoch changes)."""
        with self._lock:
            if replica_id is not None:
                self._ctx["replica_id"] = replica_id
            if step is not None:
                self._ctx["step"] = int(step)
            if quorum_epoch is not None:
                self._ctx["quorum_epoch"] = int(quorum_epoch)

    def context(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._ctx)

    def current_trace_id(self) -> str:
        with self._lock:
            c = self._ctx
            return f"{c['replica_id']}:{c['step']}:{c['quorum_epoch']}"

    def _next_span_id(self) -> str:
        with self._lock:
            self._seq += 1
            return f"{os.getpid():x}-{self._seq:x}"

    # -- producing spans -------------------------------------------------

    def span(
        self,
        name: str,
        parent: Optional[Dict[str, Any]] = None,
        trace_id: Optional[str] = None,
        replica_id: Optional[str] = None,
        **attrs: Any,
    ) -> _SpanCtx:
        """Open a span. ``parent`` is a carrier dict (from :meth:`inject`,
        possibly received over an RPC) that both links the parent span and
        adopts its trace_id; otherwise the innermost open span on this
        thread is the parent and the process context names the trace."""
        parent_id: Optional[str] = None
        if parent:
            parent_id = parent.get("span_id") or None
            if trace_id is None:
                trace_id = parent.get("trace_id") or None
        if parent_id is None:
            cur = self._current()
            if cur is not None:
                parent_id = cur.span_id
                if trace_id is None:
                    trace_id = cur.trace_id
        if trace_id is None:
            trace_id = self.current_trace_id()
        if replica_id is None:
            replica_id = trace_id.split(":", 1)[0] or self.context()["replica_id"]
        s = Span(name, trace_id, self._next_span_id(), parent_id, replica_id)
        if attrs:
            s.attrs.update(attrs)
        return _SpanCtx(self, s)

    def inject(self) -> Dict[str, str]:
        """Carrier for RPC metadata: the current span (or bare context) as
        ``{"trace_id", "span_id"}`` — attach it to an outgoing request and
        pass it as ``parent=`` on the serving side."""
        cur = self._current()
        if cur is not None:
            return {"trace_id": cur.trace_id, "span_id": cur.span_id}
        return {"trace_id": self.current_trace_id(), "span_id": ""}

    @staticmethod
    def parse_carrier(raw: str) -> Optional[Dict[str, str]]:
        """Parse the ``trace_id|span_id`` header form used by the HTTP
        transports back into a carrier dict."""
        if not raw:
            return None
        trace_id, _, span_id = raw.partition("|")
        return {"trace_id": trace_id, "span_id": span_id}

    @staticmethod
    def format_carrier(carrier: Dict[str, str]) -> str:
        return f"{carrier.get('trace_id', '')}|{carrier.get('span_id', '')}"

    # -- thread-local stack ----------------------------------------------

    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def _current(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        st = self._stack()
        if st and st[-1] is span:
            st.pop()
        elif span in st:  # tolerate mismatched exits
            st.remove(span)

    # -- recording -------------------------------------------------------

    def configure(self, path: Optional[str]) -> None:
        """Point the JSONL sink at ``path`` (append), or detach with None."""
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
            self._path = path
            self._env_checked = True
            if path:
                d = os.path.dirname(path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._file = open(path, "a", encoding="utf-8")

    def _maybe_open_from_env(self) -> None:
        # called under self._lock
        if self._env_checked:
            return
        self._env_checked = True
        path = os.environ.get(ENV_TRACE_PATH)
        if not path:
            return
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._file = open(path, "a", encoding="utf-8")
            self._path = path
        except OSError:
            self._file = None
            self._path = None

    def _record(self, span: Span) -> None:
        try:
            d = span.to_dict()
            with self._lock:
                self._maybe_open_from_env()
                self._ring.append(d)
                self._pending.append(span)
                if self._file is not None:
                    try:
                        self._file.write(json.dumps(d, default=str) + "\n")
                        self._file.flush()
                    except (OSError, ValueError):
                        pass
            from torchft_tpu import telemetry

            telemetry.TRACE_SPANS.labels(span=span.name).inc()
        except Exception:  # noqa: BLE001 — tracing must never fail a step
            pass

    # -- consuming -------------------------------------------------------

    def recent(
        self, name: Optional[str] = None, limit: Optional[int] = None
    ) -> List[Dict[str, Any]]:
        """Most recent span dicts, oldest first, optionally by name."""
        with self._lock:
            spans = list(self._ring)
        if name is not None:
            spans = [s for s in spans if s.get("name") == name]
        if limit is not None:
            spans = spans[-limit:]
        return spans

    def drain_chrome_fragment(
        self, max_events: int = 64, max_bytes: int = 32768
    ) -> str:
        """Pop up-to-``max_events`` not-yet-shipped spans as a comma-joined
        Chrome trace-event fragment (no enclosing brackets) — the compact
        batch replicas piggyback on their quorum traffic. Includes a
        ``process_name`` metadata event per distinct replica so the merged
        timeline labels its lanes; duplicates across batches are harmless."""
        spans: List[Span] = []
        with self._lock:
            while self._pending and len(spans) < max_events:
                spans.append(self._pending.popleft())
        if not spans:
            return ""
        parts: List[str] = []
        named: set = set()
        total = 0
        consumed = 0
        for s in spans:
            try:
                frag = json.dumps(s.to_chrome(), separators=(",", ":"), default=str)
            except (TypeError, ValueError):
                consumed += 1
                continue  # unserializable span: drop it, keep draining
            if total + len(frag) > max_bytes and parts:
                break  # over budget: later spans stay pending (below)
            if s.replica_id and s.replica_id not in named:
                named.add(s.replica_id)
                parts.append(
                    json.dumps(
                        _chrome_process_name(s.replica_id),
                        separators=(",", ":"),
                    )
                )
            total += len(frag)
            parts.append(frag)
            consumed += 1
        if consumed < len(spans):
            # push the unshipped tail back (in order) for the next batch —
            # busy incident windows must not lose their spans to the cap
            with self._lock:
                for s in reversed(spans[consumed:]):
                    self._pending.appendleft(s)
        self._last_batch = spans[:consumed]
        return ",".join(parts)

    def requeue_last_batch(self) -> None:
        """Re-queue the spans returned by the most recent
        :meth:`drain_chrome_fragment` (callers that failed to ship a
        piggyback batch use this so an outage window keeps its spans; a
        rare double-requeue only duplicates events, which the merged
        trace tolerates)."""
        with self._lock:
            batch = getattr(self, "_last_batch", None)
            self._last_batch = []
            if batch:
                for s in reversed(batch):
                    self._pending.appendleft(s)

    def chrome_events(
        self, spans: Optional[List[Dict[str, Any]]] = None
    ) -> List[Dict[str, Any]]:
        """Chrome trace events for ``spans`` (default: the recent ring),
        with a ``process_name`` metadata event per replica."""
        if spans is None:
            spans = self.recent()
        out: List[Dict[str, Any]] = []
        named: set = set()
        for d in spans:
            rid = d.get("replica_id", "")
            if rid and rid not in named:
                named.add(rid)
                out.append(_chrome_process_name(rid))
            out.append(_chrome_event(d))
        return out

    def clear(self) -> None:
        """Empty the ring and pending batches (tests)."""
        with self._lock:
            self._ring.clear()
            self._pending.clear()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


TRACER = Tracer()


def chrome_trace(path: str, spans: Optional[List[Dict[str, Any]]] = None) -> str:
    """Write the recent spans (or ``spans``) as a Chrome trace-event JSON
    file loadable in Perfetto; returns the path."""
    events = TRACER.chrome_events(spans)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"displayTimeUnit": "ms", "traceEvents": events}, f)
    return path


def read_spans(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL span file back into dicts (skips torn tails)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except FileNotFoundError:
        pass
    return out
