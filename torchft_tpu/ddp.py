"""Cross-replica-group gradient averaging — the DDP comm-hook analogue.

The reference registers a DDP communication hook that routes each gradient
bucket through ``manager.allreduce`` (torchft/ddp.py:32-71). JAX has no
backward hooks: gradients arrive as one pytree from ``jax.grad``, already
reduced *within* the replica group by XLA's ICI collectives. This module
averages them *across* replica groups on host buffers (the managed axis
that can resize without recompiling the train step).

Bucketing mirrors DDP's reducer: leaves are packed into ~25 MB flat
buffers so each quorum-managed allreduce moves a large contiguous span
(fewer ring rounds, full-bandwidth frames) instead of one op per leaf.
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import numpy as np

__all__ = ["flatten_buckets", "unflatten_buckets", "allreduce_gradients"]

_DEFAULT_BUCKET_BYTES = 25 * 1024 * 1024


def _leaves(tree: Any) -> Tuple[List[Any], Any]:
    import jax

    return jax.tree_util.tree_flatten(tree)


def flatten_buckets(
    leaves: Sequence[np.ndarray], bucket_bytes: int = _DEFAULT_BUCKET_BYTES
) -> List[Tuple[np.ndarray, List[int]]]:
    """Pack host arrays into flat float buffers of ~``bucket_bytes``.

    Returns ``[(buffer, leaf_indices), ...]``; same-dtype leaves are packed
    together in input order (a dtype change forces a new bucket, as packing
    requires a uniform element type)."""
    buckets: List[Tuple[np.ndarray, List[int]]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_dtype = None

    def flush() -> None:
        nonlocal cur, cur_bytes, cur_dtype
        if not cur:
            return
        buf = np.concatenate([leaves[i].reshape(-1) for i in cur])
        buckets.append((buf, cur))
        cur, cur_bytes, cur_dtype = [], 0, None

    for i, leaf in enumerate(leaves):
        if cur and (leaf.dtype != cur_dtype or cur_bytes + leaf.nbytes > bucket_bytes):
            flush()
        cur.append(i)
        cur_bytes += leaf.nbytes
        cur_dtype = leaf.dtype
    flush()
    return buckets


def unflatten_buckets(
    buckets: Sequence[Tuple[np.ndarray, List[int]]],
    leaves: Sequence[np.ndarray],
) -> List[np.ndarray]:
    """Scatter reduced buffers back into leaf-shaped arrays."""
    out: List[np.ndarray] = list(leaves)
    for buf, idxs in buckets:
        offset = 0
        for i in idxs:
            n = leaves[i].size
            out[i] = buf[offset : offset + n].reshape(leaves[i].shape)
            offset += n
    return out


def allreduce_gradients(
    manager,
    grads: Any,
    bucket_bytes: int = _DEFAULT_BUCKET_BYTES,
) -> Any:
    """Average a gradient pytree across replica groups through the Manager.

    Two paths, chosen by the Manager's configured data plane:

    * **device path** (``CollectivesDevice`` — groups sharing one JAX
      runtime): the ``jax.Array`` leaves go straight into
      ``manager.allreduce_many``; the averaging is one jitted psum over the
      'ft' mesh axis riding ICI and the gradients never touch the host.
    * **host path** (``CollectivesTcp`` — groups in separate processes,
      DCN): device arrays are pulled to host (async per-leaf D2H overlaps
      the transfers), bucketed into ~25 MB flat buffers, ring-allreduced,
      and returned as numpy — feed them straight into the jitted optimizer
      update, XLA transfers them back to device.

    Both scale by ``1/num_participants()`` and swallow errors into the
    Manager's latched state.
    """
    import jax

    leaves, treedef = _leaves(grads)

    if getattr(manager, "device_data_plane", lambda: False)():
        out = manager.allreduce_many(leaves).wait()
        return jax.tree_util.tree_unflatten(treedef, out)

    # host path. A leaf sharded across processes (multi-host group) cannot
    # be gathered: this process averages only its addressable shards —
    # correct because same-rank peers across groups hold the same shard
    # indices (congruent meshes), and replicas within the process are
    # averaged once and re-placed to every holder.
    from torchft_tpu.checkpointing.serialization import _index_desc

    # overlap D2H across leaves before the first blocking np.asarray —
    # for process-spanning leaves, prefetch each local shard
    try:
        for leaf in leaves:
            if not isinstance(leaf, jax.Array):
                continue
            if leaf.is_fully_addressable:
                leaf.copy_to_host_async()
            else:
                for s in leaf.addressable_shards:
                    s.data.copy_to_host_async()
    except Exception:  # noqa: BLE001 — prefetch is best-effort
        pass

    host: List[np.ndarray] = []
    rebuild: List[Tuple] = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            seen = {}
            for s in leaf.addressable_shards:
                idx = _index_desc(s.index, leaf.shape)
                if idx not in seen:
                    seen[idx] = np.ascontiguousarray(np.asarray(s.data))
            rebuild.append(("shards", leaf, list(seen.keys())))
            host.extend(seen.values())
        else:
            rebuild.append(("dense",))
            host.append(np.ascontiguousarray(np.asarray(leaf)))

    buckets = flatten_buckets(host, bucket_bytes)
    # one managed op for all buckets (in-place on the numpy buffers):
    # same bytes, a single SPMD slot instead of per-bucket dispatch
    manager.allreduce_many([buf for buf, _ in buckets]).wait()
    averaged = unflatten_buckets(buckets, host)

    out: List[Any] = []
    it = iter(averaged)
    for item, leaf in zip(rebuild, leaves):
        if item[0] == "dense":
            out.append(next(it))
        else:
            _, template, idxs = item
            by_idx = {idx: next(it) for idx in idxs}
            arrays = [
                jax.device_put(by_idx[_index_desc(index, template.shape)], dev)
                for dev, index in template.sharding.addressable_devices_indices_map(
                    template.shape
                ).items()
            ]
            out.append(
                jax.make_array_from_single_device_arrays(
                    template.shape, template.sharding, arrays
                )
            )
    return jax.tree_util.tree_unflatten(treedef, out)
