"""Cross-replica-group gradient averaging — the DDP comm-hook analogue.

The reference registers a DDP communication hook that routes each gradient
bucket through ``manager.allreduce`` (torchft/ddp.py:32-71). JAX has no
backward hooks: gradients arrive as one pytree from ``jax.grad``, already
reduced *within* the replica group by XLA's ICI collectives. This module
averages them *across* replica groups on host buffers (the managed axis
that can resize without recompiling the train step).

Bucketing mirrors DDP's reducer: leaves are packed into ~25 MB flat
buffers so each quorum-managed allreduce moves a large contiguous span
(fewer ring rounds, full-bandwidth frames) instead of one op per leaf.

The host path is a three-stage pipeline, the role NCCL's async stream
plays in the reference (process_group.py:431-447): while bucket k rides
the TCP ring on the collectives op thread, bucket k+1's device→host
transfers complete on the main thread and bucket k−1's averaged pieces
are already being device_put back — so wire time hides behind transfer
time instead of adding to it.

Pipelined-commit note (docs/commit_pipeline.md): callers must resolve
any in-flight commit vote (``manager.resolve_pending_commit()``) before
calling :func:`allreduce_gradients` for the next step — the Manager
raises otherwise, because gradients of a speculative (possibly about to
be rolled back) state must never enter a collective. The bucket buffers
here always own their memory (``np.concatenate`` / explicit ``copy``),
so the in-place ring reduction can never corrupt the caller's retained
gradient pytree across a rollback/replay.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["flatten_buckets", "unflatten_buckets", "allreduce_gradients"]

_DEFAULT_BUCKET_BYTES = 25 * 1024 * 1024


def default_bucket_bytes() -> int:
    """Streamed-bucket size for the host wire plane — the
    ``TORCHFT_WIRE_BUCKET_BYTES`` env knob, default 25 MB
    (docs/wire_plane.md: smaller buckets start the wire earlier but pay
    more per-op overhead)."""
    raw = os.environ.get("TORCHFT_WIRE_BUCKET_BYTES")
    if raw:
        try:
            return max(1 << 16, int(raw))
        except ValueError:
            pass
    return _DEFAULT_BUCKET_BYTES


def _leaves(tree: Any) -> Tuple[List[Any], Any]:
    import jax

    return jax.tree_util.tree_flatten(tree)


def flatten_buckets(
    leaves: Sequence[np.ndarray], bucket_bytes: int = _DEFAULT_BUCKET_BYTES
) -> List[Tuple[np.ndarray, List[int]]]:
    """Pack host arrays into flat float buffers of ~``bucket_bytes``.

    Returns ``[(buffer, leaf_indices), ...]``; same-dtype leaves are packed
    together in input order (a dtype change forces a new bucket, as packing
    requires a uniform element type)."""
    buckets: List[Tuple[np.ndarray, List[int]]] = []
    for idxs in plan_buckets(
        [(l.dtype, l.nbytes) for l in leaves], bucket_bytes
    ):
        buf = np.concatenate([leaves[i].reshape(-1) for i in idxs])
        buckets.append((buf, idxs))
    return buckets


def plan_buckets(
    meta: Sequence[Tuple[np.dtype, int]], bucket_bytes: int = _DEFAULT_BUCKET_BYTES
) -> List[List[int]]:
    """Group item indices into ~``bucket_bytes`` same-dtype buckets from
    (dtype, nbytes) metadata alone — so the plan exists before any device
    buffer has been pulled to host (the pipeline needs it up front)."""
    plan: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i, (dtype, nbytes) in enumerate(meta):
        if cur and (dtype != cur_dtype or cur_bytes + nbytes > bucket_bytes):
            plan.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        cur_dtype = dtype
    if cur:
        plan.append(cur)
    return plan


def unflatten_buckets(
    buckets: Sequence[Tuple[np.ndarray, List[int]]],
    leaves: Sequence[np.ndarray],
) -> List[np.ndarray]:
    """Scatter reduced buffers back into leaf-shaped arrays."""
    out: List[np.ndarray] = list(leaves)
    for buf, idxs in buckets:
        offset = 0
        for i in idxs:
            n = leaves[i].size
            out[i] = buf[offset : offset + n].reshape(leaves[i].shape)
            offset += n
    return out


class _Item:
    """One host transfer unit: a dense leaf or a single shard of a
    process-spanning leaf. Metadata (dtype/size) is known before the
    device buffer is, which is what lets buckets be planned up front."""

    __slots__ = ("leaf_pos", "src", "dtype", "shape", "index")

    def __init__(self, leaf_pos, src, dtype, shape, index=None) -> None:
        self.leaf_pos = leaf_pos
        self.src = src  # jax.Array / shard data / numpy
        self.dtype = np.dtype(dtype)
        self.shape = tuple(shape)
        self.index = index  # shard index desc, or None for dense

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) * self.dtype.itemsize


def allreduce_gradients(
    manager,
    grads: Any,
    bucket_bytes: Optional[int] = None,
    error_feedback: Optional[Any] = None,
) -> Any:
    """Average a gradient pytree across replica groups through the Manager.

    Two paths, chosen by the Manager's configured data plane:

    * **device path** (``CollectivesDevice`` — groups sharing one JAX
      runtime): the ``jax.Array`` leaves go straight into
      ``manager.allreduce_many``; the averaging is one jitted psum over the
      'ft' mesh axis riding ICI and the gradients never touch the host.
    * **host path** (``CollectivesTcp`` — groups in separate processes,
      DCN): a per-bucket pipeline — D2H of bucket k+1 overlaps the TCP
      ring of bucket k overlaps the H2D of bucket k−1. Averaged leaves
      come back as device arrays (the H2D already happened), ready for
      the jitted optimizer update.

    Both scale by ``1/num_participants()`` and swallow errors into the
    Manager's latched state.

    ``error_feedback`` (a :class:`~torchft_tpu.wire_codec.ErrorFeedback`,
    host path only): each bucket is compensated with the committed
    residual, projected onto the wire codec's grid, and its fresh
    residual STAGED — the caller promotes or discards it with the step's
    fate (``commit()``/``rollback()``; ManagedOptimizer wires this
    automatically). ``bucket_bytes`` defaults to the
    ``TORCHFT_WIRE_BUCKET_BYTES`` knob.
    """
    import jax

    if bucket_bytes is None:
        bucket_bytes = default_bucket_bytes()
    leaves, treedef = _leaves(grads)

    if getattr(manager, "device_data_plane", lambda: False)():
        out = manager.allreduce_many(leaves).wait()
        return jax.tree_util.tree_unflatten(treedef, out)

    # host path. A leaf sharded across processes (multi-host group) cannot
    # be gathered: this process averages only its addressable shards —
    # correct because same-rank peers across groups hold the same shard
    # indices (congruent meshes), and replicas within the process are
    # averaged once and re-placed to every holder.
    from torchft_tpu.checkpointing.serialization import _index_desc

    # stage 0: kick off D2H for every leaf/shard before anything blocks
    try:
        for leaf in leaves:
            if not isinstance(leaf, jax.Array):
                continue
            if leaf.is_fully_addressable:
                leaf.copy_to_host_async()
            else:
                for s in leaf.addressable_shards:
                    s.data.copy_to_host_async()
    except Exception:  # noqa: BLE001 — prefetch is best-effort
        pass

    # item descriptors (metadata only; no blocking transfer yet)
    items: List[_Item] = []
    for li, leaf in enumerate(leaves):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            seen: Dict[Tuple, Any] = {}
            for s in leaf.addressable_shards:
                idx = _index_desc(s.index, leaf.shape)
                if idx not in seen:  # replicated copies average once
                    seen[idx] = s.data
            for idx, data in seen.items():
                items.append(_Item(li, data, data.dtype, data.shape, idx))
        else:
            dtype = getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
            shape = getattr(leaf, "shape", None)
            if shape is None:
                shape = np.asarray(leaf).shape
            items.append(_Item(li, leaf, dtype, shape))

    plan = plan_buckets([(it.dtype, it.nbytes) for it in items], bucket_bytes)

    def _run_bucket(ordinal: int, idxs: List[int]):
        import time as _time

        from torchft_tpu.collectives import record_wire_stage

        # stage 1 (main thread): materialize this bucket's host buffers —
        # blocks only on *this* bucket's D2H while earlier buckets are
        # already riding the ring on the op thread
        t0 = _time.perf_counter()
        flat = [
            np.ascontiguousarray(np.asarray(items[i].src)).reshape(-1)
            for i in idxs
        ]
        # the bucket buffer always owns its memory: the ring reduces (and
        # non-participants zero) in place, which must never write through
        # a view of the caller's arrays or a read-only XLA host buffer
        buf = np.concatenate(flat) if len(flat) > 1 else flat[0].copy()
        record_wire_stage("host_copy", _time.perf_counter() - t0)

        if error_feedback is not None:
            # compensate with the committed residual and project onto the
            # codec's bucket-level grid BEFORE the collective (exact for
            # bf16; int8's per-chunk wire scales add a finer bounded
            # component EF doesn't track — see ErrorFeedback docstring);
            # the fresh residual stays PENDING until the step's fate
            # resolves. The key is stable across steps as long as the
            # bucket plan is (same tree -> same plan).
            t0 = _time.perf_counter()
            error_feedback.apply(f"b{ordinal}_{buf.size}", buf)
            record_wire_stage("quantize", _time.perf_counter() - t0)

        # stage 2 (op thread): quorum-managed ring allreduce of the bucket
        fut = manager.allreduce_many([buf])

        # dense jax leaves carry their sharding so stage 3 can start the
        # averaged piece's H2D without waiting for the whole tree
        put_shardings = []
        for i in idxs:
            it = items[i]
            s = (
                getattr(it.src, "sharding", None)
                if it.index is None and isinstance(it.src, jax.Array)
                else None
            )
            put_shardings.append(s)
        shapes = [items[i].shape for i in idxs]

        def scatter(f):
            # stage 3 (runs on the op thread as soon as this bucket's ring
            # finishes, while the next bucket's ring occupies the wire):
            # slice the averaged buffer and dispatch H2D immediately
            res = f.value()[0]
            parts = []
            off = 0
            for shp, sharding in zip(shapes, put_shardings):
                n = int(np.prod(shp, dtype=np.int64))
                piece = res[off : off + n].reshape(shp)
                off += n
                if sharding is not None:
                    piece = jax.device_put(piece, sharding)
                parts.append(piece)
            return parts

        return fut.then(scatter)

    bucket_futs = [
        (idxs, _run_bucket(ordinal, idxs))
        for ordinal, idxs in enumerate(plan)
    ]

    # collect averaged pieces per item (in order; waits overlap the tail).
    # The blocked time is the step's main-thread cost of the cross-group
    # wire — recorded as the anatomy ledger's `wire` phase (NOT via
    # record_wire_stage: that would double it into the op-thread socket
    # totals the crossgroup bench attributes stages with). In a
    # synchronous fleet a slow peer inflates exactly this wait, which is
    # what lets the straggler detector's local-time signal exclude it.
    import time as _time

    from torchft_tpu.telemetry.anatomy import LEDGER as _ledger

    item_out: List[np.ndarray] = [None] * len(items)  # type: ignore[list-item]
    t_wait = _time.perf_counter()
    for idxs, fut in bucket_futs:
        parts = fut.wait()
        for i, piece in zip(idxs, parts):
            item_out[i] = piece
    _ledger.record("wire", _time.perf_counter() - t_wait)

    # reassemble leaves
    out: List[Any] = [None] * len(leaves)
    shard_acc: Dict[int, Dict[Tuple, np.ndarray]] = {}
    for it, averaged in zip(items, item_out):
        if it.index is None:
            out[it.leaf_pos] = averaged
        else:
            shard_acc.setdefault(it.leaf_pos, {})[it.index] = averaged
    for li, by_idx in shard_acc.items():
        template = leaves[li]
        arrays = [
            jax.device_put(by_idx[_index_desc(index, template.shape)], dev)
            for dev, index in template.sharding.addressable_devices_indices_map(
                template.shape
            ).items()
        ]
        out[li] = jax.make_array_from_single_device_arrays(
            template.shape, template.sharding, arrays
        )
    return jax.tree_util.tree_unflatten(treedef, out)
