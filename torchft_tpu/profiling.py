"""Tracing / profiling hooks.

Reference: wall-clock context managers ``_time`` / ``_timeit`` logging
checkpoint-stage durations (http_transport.py:31-36, pg_transport.py:73-78)
— no deeper profiler. The TPU build goes further: ``profile`` wraps
``jax.profiler`` traces (viewable in TensorBoard/XProf, capturing XLA ops,
HBM traffic and ICI collectives) and ``StepTimer`` keeps a rolling
steps/sec with outlier-marked quorum/heal steps, feeding the
``tft_step_duration_seconds`` histogram in :mod:`torchft_tpu.telemetry`.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import deque
from typing import Deque, Iterator, List, Optional, Tuple

logger = logging.getLogger(__name__)

__all__ = ["timed", "profile", "StepTimer"]


@contextlib.contextmanager
def timed(what: str, log: logging.Logger = logger) -> Iterator[None]:
    """Log the wall-clock duration of a block (the reference's ``_time``).

    Prefer a :class:`~torchft_tpu.telemetry.registry.Histogram` ``.time()``
    for recurring spans — this context manager only logs; it records
    nothing scrapable."""
    t0 = time.perf_counter()
    yield
    log.info("%s took %.3fs", what, time.perf_counter() - t0)


@contextlib.contextmanager
def profile(log_dir: Optional[str] = None) -> Iterator[None]:
    """jax.profiler trace around a block; no-op if log_dir is None.

    View with ``tensorboard --logdir <log_dir>`` (Profile tab) — includes
    per-op device timelines, memory viewer, and collective stats."""
    if log_dir is None:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Rolling training-step telemetry with quorum/heal outlier marking.

    Steps that absorbed an FT lifecycle event (a quorum reconfigure, a
    heal) are *outliers*: their duration is real recovery cost, not
    steady-state throughput, so they are excluded from the headline
    rolling rate and reported separately. Mark them either up front
    (:meth:`mark_quorum` / :meth:`mark_heal` any time before the boundary)
    or at the boundary (``tick(quorum=..., heal=...)``).

    Every step duration is also observed into the process-wide
    ``tft_step_duration_seconds{kind=...}`` histogram (kind ``steady``,
    ``quorum`` or ``heal`` — heal wins when both apply, since it
    dominates the cost), so the recovery envelope is readable from
    recorded telemetry: the outlier durations ARE the per-step recovery
    cost the paper's "at most one step" claim bounds.
    """

    def __init__(self, window: int = 50, record_metrics: bool = True) -> None:
        self._window: Deque[float] = deque(maxlen=window)  # steady only
        self._all_window: Deque[float] = deque(maxlen=window)
        self._last: Optional[float] = None
        self._pending: set = set()
        self._outliers: Deque[Tuple[int, float, Tuple[str, ...]]] = deque(
            maxlen=window
        )
        self._record_metrics = record_metrics
        self.steps = 0
        self.outlier_steps = 0
        self.last_tags: Tuple[str, ...] = ()

    def mark_quorum(self) -> None:
        """Flag the in-flight step as having absorbed a quorum reconfigure."""
        self._pending.add("quorum")

    def mark_heal(self) -> None:
        """Flag the in-flight step as having absorbed a heal."""
        self._pending.add("heal")

    def tick(self, quorum: bool = False, heal: bool = False) -> Optional[float]:
        """Mark a step boundary; returns this step's duration (None on the
        first call)."""
        if quorum:
            self._pending.add("quorum")
        if heal:
            self._pending.add("heal")
        now = time.perf_counter()
        if self._last is None:
            # no previous boundary to measure from — HOLD the pending
            # marks instead of discarding them: a rejoiner heals before
            # its first boundary, and the heal must tag its first
            # measurable step or the recovery never shows as an outlier
            self._last = now
            self.steps += 1
            self.last_tags = ()
            return None
        tags = tuple(sorted(self._pending))
        self._pending.clear()
        self.last_tags = tags
        dur = now - self._last
        self._all_window.append(dur)
        if tags:
            self.outlier_steps += 1
            self._outliers.append((self.steps, dur, tags))
        else:
            self._window.append(dur)
        if self._record_metrics:
            kind = "heal" if "heal" in tags else (
                "quorum" if "quorum" in tags else "steady"
            )
            from torchft_tpu import telemetry

            telemetry.STEP_DURATION.labels(kind=kind).observe(dur)
        self._last = now
        self.steps += 1
        return dur

    def steps_per_sec(self) -> Optional[float]:
        """Headline rolling rate over STEADY steps only (quorum/heal
        outliers excluded, so one recovery doesn't crater the number)."""
        if not self._window:
            return None
        return len(self._window) / sum(self._window)

    def steps_per_sec_all(self) -> Optional[float]:
        """Rolling rate over every step, outliers included — the rate a
        wall clock actually observed."""
        if not self._all_window:
            return None
        return len(self._all_window) / sum(self._all_window)

    def outliers(self) -> List[Tuple[int, float, Tuple[str, ...]]]:
        """Recent outlier steps as (step_index, duration_s, tags) — the
        recorded recovery cost per FT event."""
        return list(self._outliers)

    def outlier_digest(self) -> List[dict]:
        """JSON-safe form of :meth:`outliers` — exported through the
        step-anatomy summaries and the flight-recorder/SIGUSR2 dumps
        (``telemetry.anatomy.LEDGER.attach_timer``), so the tagged
        recovery costs finally leave the process instead of living and
        dying in this deque."""
        return [
            {"step": s, "duration_s": round(d, 4), "tags": list(tags)}
            for s, d, tags in self._outliers
        ]
