"""Tracing / profiling hooks.

Reference: wall-clock context managers ``_time`` / ``_timeit`` logging
checkpoint-stage durations (http_transport.py:31-36, pg_transport.py:73-78)
— no deeper profiler. The TPU build goes further: ``profile`` wraps
``jax.profiler`` traces (viewable in TensorBoard/XProf, capturing XLA ops,
HBM traffic and ICI collectives) and ``StepTimer`` keeps a rolling
steps/sec with outlier-marked quorum/heal steps.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import deque
from typing import Deque, Iterator, Optional

logger = logging.getLogger(__name__)

__all__ = ["timed", "profile", "StepTimer"]


@contextlib.contextmanager
def timed(what: str, log: logging.Logger = logger) -> Iterator[None]:
    """Log the wall-clock duration of a block (the reference's ``_time``)."""
    t0 = time.perf_counter()
    yield
    log.info("%s took %.3fs", what, time.perf_counter() - t0)


@contextlib.contextmanager
def profile(log_dir: Optional[str] = None) -> Iterator[None]:
    """jax.profiler trace around a block; no-op if log_dir is None.

    View with ``tensorboard --logdir <log_dir>`` (Profile tab) — includes
    per-op device timelines, memory viewer, and collective stats."""
    if log_dir is None:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Rolling training-step telemetry."""

    def __init__(self, window: int = 50) -> None:
        self._window: Deque[float] = deque(maxlen=window)
        self._last: Optional[float] = None
        self.steps = 0

    def tick(self) -> Optional[float]:
        """Mark a step boundary; returns this step's duration (None on the
        first call)."""
        now = time.perf_counter()
        dur = None
        if self._last is not None:
            dur = now - self._last
            self._window.append(dur)
        self._last = now
        self.steps += 1
        return dur

    def steps_per_sec(self) -> Optional[float]:
        if not self._window:
            return None
        return len(self._window) / sum(self._window)
