"""Job launcher — the TorchX/torchrun analogue.

Reference: torchft/torchx.py:11-76 (N replica-group roles, each under
``torchrun --max_restarts=10``) driven by .torchxconfig. TPU deployments
have no torchrun; this supervisor fills both roles for single-host runs
and documents the env contract for cluster schedulers:

    TORCHFT_LIGHTHOUSE   lighthouse host:port
    TORCHFT_STORE_ADDR   per-replica-group KV store host:port
    REPLICA_GROUP_ID     group index
    NUM_REPLICA_GROUPS   total groups
    RANK / WORLD_SIZE    rank within the group

Each replica group gets its own StoreServer and worker subprocesses; a
group whose worker dies is torn down and relaunched whole (the reference's
torchelastic restart, which its integration tests emulate with
``attempts=3``) up to ``--max-restarts`` times. The lighthouse is spawned
automatically unless an address is given.

CLI::

    python -m torchft_tpu.launcher --groups 2 --nproc 1 -- \
        python examples/train_ddp.py
"""

from __future__ import annotations

import argparse
import logging
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)

__all__ = ["launch", "launch_shared_runtime", "main"]


@dataclass
class _Group:
    gid: int
    store: object
    procs: List[subprocess.Popen] = field(default_factory=list)
    restarts: int = 0


def _free_port() -> int:
    # NOTE: bind/close races another process onto the port before rank 0's
    # jax coordinator binds it — rare, and self-healing: the group dies at
    # startup and the supervisor loop respawns it with a fresh port.
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _spawn_group(
    gid: int,
    cmd: Sequence[str],
    num_groups: int,
    nproc: int,
    lighthouse_addr: str,
    base_env: Dict[str, str],
    cohort_env: Optional[Dict[str, str]] = None,
) -> _Group:
    from torchft_tpu.store import StoreServer

    store = StoreServer()
    group = _Group(gid=gid, store=store)
    # multi-process group: hand out a fresh jax coordinator endpoint so the
    # workers form one multi-controller JAX runtime (a group-wide mesh)
    # via parallel.multihost.initialize_group. Single-host launcher →
    # localhost; a cluster scheduler sets TORCHFT_JAX_COORDINATOR to the
    # group's rank-0 host itself.
    coordinator = f"localhost:{_free_port()}" if nproc > 1 else None
    for rank in range(nproc):
        env = dict(base_env)
        env.update(
            TORCHFT_LIGHTHOUSE=lighthouse_addr,
            TORCHFT_STORE_ADDR=store.address(),
            REPLICA_GROUP_ID=str(gid),
            NUM_REPLICA_GROUPS=str(num_groups),
            RANK=str(rank),
            WORLD_SIZE=str(nproc),
        )
        if coordinator is not None:
            env["TORCHFT_JAX_COORDINATOR"] = coordinator
        if cohort_env:
            env.update(cohort_env)
        group.procs.append(subprocess.Popen(list(cmd), env=env))
    return group


def _teardown_group(group: _Group) -> None:
    for p in group.procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.monotonic() + 5
    for p in group.procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
    group.store.shutdown()


def launch_shared_runtime(
    cmd: Sequence[str],
    num_groups: int = 2,
    lighthouse_addr: Optional[str] = None,
    max_restarts: int = 10,
    restart_backoff_s: float = 6.0,
) -> int:
    """Run ``cmd`` as ``num_groups`` single-process replica groups joined
    to ONE multi-controller JAX runtime (``CollectivesDeviceDist``: the
    cross-group psum rides ICI). The cohort's membership is static —
    multi-controller JAX cannot lose a member — so failure handling is
    COHORT-grained: any worker death tears down and respawns the whole
    cohort with a fresh coordinator (the k8s Job restart pattern), up to
    ``max_restarts`` cohort restarts. Workers receive
    ``TORCHFT_COHORT_COORDINATOR`` / ``TORCHFT_COHORT_SIZE`` /
    ``TORCHFT_COHORT_ID`` and call
    ``collectives_device_dist.init_from_env()`` before first jax use."""
    lighthouse, lighthouse_addr = _maybe_spawn_lighthouse(
        lighthouse_addr, num_groups
    )
    base_env = dict(os.environ)
    groups: List[_Group] = []

    def spawn_cohort() -> None:
        # appends into the shared list so a spawn failure mid-cohort
        # leaves every already-started group visible to the finally block
        coordinator = f"localhost:{_free_port()}"
        cohort_env = {
            "TORCHFT_COHORT_COORDINATOR": coordinator,
            "TORCHFT_COHORT_SIZE": str(num_groups),
        }
        for g in range(num_groups):
            groups.append(
                _spawn_group(
                    g, cmd, num_groups, 1, lighthouse_addr, base_env,
                    {**cohort_env, "TORCHFT_COHORT_ID": str(g)},
                )
            )

    restarts = 0
    exit_code = 0
    try:
        spawn_cohort()
        while True:
            time.sleep(0.5)
            codes = [p.poll() for g in groups for p in g.procs]
            if all(c == 0 for c in codes):
                logger.info("cohort finished clean")
                break
            if any(c is not None and c != 0 for c in codes):
                logger.warning("cohort worker died (codes %s)", codes)
                for g in groups:
                    _teardown_group(g)
                groups.clear()
                if restarts >= max_restarts:
                    logger.error("cohort exhausted restarts")
                    exit_code = 1
                    break
                restarts += 1
                # let the dead incarnation's heartbeat leases lapse at the
                # lighthouse before the new cohort joins: an immediate
                # respawn forms a quorum that still contains the stale
                # replica_ids, the device-dist plane refuses the cohort
                # mismatch (quorum N+stale vs runtime N), the fresh
                # workers die, and each cycle re-arms the race — the
                # restart budget burns without ever converging. Default
                # sits just above the lighthouse's 5 s default lease.
                logger.info(
                    "restarting cohort (restart %d/%d) after %.1fs lease "
                    "backoff", restarts, max_restarts, restart_backoff_s,
                )
                time.sleep(restart_backoff_s)
                spawn_cohort()
    except KeyboardInterrupt:
        exit_code = 130
    finally:
        for g in groups:
            _teardown_group(g)
        if lighthouse is not None:
            lighthouse.shutdown()
    return exit_code


def _maybe_spawn_lighthouse(lighthouse_addr: Optional[str], min_replicas: int):
    """Launcher-owned lighthouse when no external address was given;
    returns (server_or_None, host:port)."""
    if lighthouse_addr is not None:
        return None, lighthouse_addr
    from torchft_tpu.coordination import LighthouseServer

    lighthouse = LighthouseServer(bind="[::]:0", min_replicas=min_replicas)
    # address() is http://host:port — the env var carries host:port
    addr = lighthouse.address().split("//", 1)[-1]
    logger.info("spawned lighthouse at %s", addr)
    return lighthouse, addr


def launch(
    cmd: Sequence[str],
    num_groups: int = 2,
    nproc: int = 1,
    lighthouse_addr: Optional[str] = None,
    max_restarts: int = 10,
    min_replicas: Optional[int] = None,
) -> int:
    """Run ``cmd`` as ``num_groups`` fault-tolerant replica groups of
    ``nproc`` workers. Returns the exit code (0 iff every group finished
    clean)."""
    lighthouse, lighthouse_addr = _maybe_spawn_lighthouse(
        lighthouse_addr, min_replicas or num_groups
    )

    base_env = dict(os.environ)
    groups = [
        _spawn_group(g, cmd, num_groups, nproc, lighthouse_addr, base_env)
        for g in range(num_groups)
    ]
    exit_code = 0
    min_needed = min_replicas or num_groups
    try:
        while groups:
            time.sleep(0.5)
            for group in list(groups):
                codes = [p.poll() for p in group.procs]
                if all(c == 0 for c in codes):
                    logger.info("group %d finished clean", group.gid)
                    _teardown_group(group)
                    groups.remove(group)
                elif any(c is not None and c != 0 for c in codes):
                    logger.warning(
                        "group %d worker died (codes %s)", group.gid, codes
                    )
                    _teardown_group(group)
                    groups.remove(group)
                    if lighthouse is not None and len(groups) + 1 < min_needed:
                        # this launcher owns the quorum and a respawn plus
                        # every still-running group cannot reach
                        # min_replicas (the peers finished and left): the
                        # respawn could never re-quorum and would hang to
                        # max_restarts. The peers could only finish with
                        # this group in their quorums, so the cohort's
                        # work is complete. (With an external lighthouse,
                        # other launchers' groups may keep the quorum
                        # alive — always respawn then.)
                        logger.info(
                            "group %d died with too few peers left to ever "
                            "re-quorum (%d alive < min_replicas %d); job "
                            "complete, not respawning",
                            group.gid,
                            len(groups) + 1,
                            min_needed,
                        )
                        # the peers finished clean but THIS group crashed at
                        # the tail (e.g. during its final step/checkpoint):
                        # the launcher's 0-iff-every-group-finished-clean
                        # contract still holds (round-2 advisor finding)
                        exit_code = 1
                        continue
                    if group.restarts < max_restarts:
                        fresh = _spawn_group(
                            group.gid, cmd, num_groups, nproc,
                            lighthouse_addr, base_env,
                        )
                        fresh.restarts = group.restarts + 1
                        groups.append(fresh)
                        logger.info(
                            "restarted group %d (restart %d/%d)",
                            group.gid, fresh.restarts, max_restarts,
                        )
                    else:
                        logger.error(
                            "group %d exhausted restarts", group.gid
                        )
                        exit_code = 1
    except KeyboardInterrupt:
        exit_code = 130
    finally:
        for group in groups:
            _teardown_group(group)
        if lighthouse is not None:
            lighthouse.shutdown()
    return exit_code


def k8s_worker(cmd: Sequence[str]) -> int:
    """In-cluster per-pod bootstrap (the pod command the --emit-k8s
    manifests render). Completes the env contract a scheduler can't:

    * pod index 0 hosts the replica group's KV store (and names itself
      the jax coordinator for multi-host groups);
    * every pod resolves both through the index-0 pod's stable DNS
      (``TORCHFT_GROUP_HOST0``, set by the manifest) and execs the
      training command with ``TORCHFT_STORE_ADDR`` /
      ``TORCHFT_JAX_COORDINATOR`` filled in.
    """
    import signal

    from torchft_tpu.k8s import COORD_PORT, STORE_PORT

    rank = int(os.environ.get("RANK", "0") or "0")
    world = int(os.environ.get("WORLD_SIZE", "1"))
    host0 = os.environ.get("TORCHFT_GROUP_HOST0", "localhost")
    # TORCHFT_STORE_PORT=0 → ephemeral (tests / single-pod runs only:
    # peer pods can't guess an ephemeral port)
    port = int(os.environ.get("TORCHFT_STORE_PORT", STORE_PORT))

    env = dict(os.environ)
    env["RANK"] = str(rank)
    store = None
    if rank == 0:
        from torchft_tpu.store import StoreServer

        store = StoreServer(bind=f"[::]:{port}")
        port = store.port
    env["TORCHFT_STORE_ADDR"] = f"{host0}:{port}"
    if world > 1:
        env["TORCHFT_JAX_COORDINATOR"] = f"{host0}:{COORD_PORT}"

    proc = subprocess.Popen(list(cmd), env=env)

    # this bootstrap is container PID 1: forward termination signals so the
    # trainer gets its graceful-shutdown window (checkpoint flush, clean
    # quorum leave) before kubelet's grace period expires
    def _forward(signum, frame):  # noqa: ARG001
        try:
            proc.send_signal(signum)
        except OSError:
            pass

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _forward)

    try:
        return proc.wait()
    finally:
        if store is not None:
            store.shutdown()


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(
        description="Launch N fault-tolerant replica groups of a training script"
    )
    parser.add_argument("--groups", type=int, default=2)
    parser.add_argument("--nproc", type=int, default=1, help="workers per group")
    parser.add_argument("--lighthouse", default=None, help="existing host:port")
    parser.add_argument("--max-restarts", type=int, default=10)
    parser.add_argument("--min-replicas", type=int, default=None)
    parser.add_argument(
        "--shared-runtime",
        action="store_true",
        help="join all groups to ONE multi-controller jax runtime "
        "(CollectivesDeviceDist: cross-group psum rides ICI). Cohort-"
        "grained restarts; requires --nproc 1",
    )
    parser.add_argument(
        "--emit-k8s",
        action="store_true",
        help="print Kubernetes manifests for this topology instead of "
        "launching locally (the TorchX-component analogue, "
        "reference torchx.py:11-76)",
    )
    parser.add_argument(
        "--k8s-worker",
        action="store_true",
        help="internal: in-cluster per-pod bootstrap (store/coordinator "
        "hosting + env completion); used by the emitted manifests",
    )
    parser.add_argument(
        "--k8s-apply",
        action="store_true",
        help="render the manifests and kubectl-apply them (torchx run "
        "analogue; kubectl owns auth/context)",
    )
    parser.add_argument(
        "--k8s-status",
        action="store_true",
        help="print the session's Job/lighthouse status as JSON "
        "(selects on the torchft-session label; use --name)",
    )
    parser.add_argument(
        "--k8s-down",
        action="store_true",
        help="delete every object of the session (label-selected)",
    )
    parser.add_argument(
        "--kubectl", default="kubectl", help="kubectl binary to shell to"
    )
    parser.add_argument("--image", default="IMAGE", help="--emit-k8s: container image")
    parser.add_argument("--name", default="torchft", help="--emit-k8s: resource prefix")
    parser.add_argument("--namespace", default="default")
    parser.add_argument(
        "--tpu-accelerator", default=None, help="--emit-k8s: GKE nodeSelector"
    )
    parser.add_argument(
        "--tpu-topology", default=None, help="--emit-k8s: GKE TPU topology"
    )
    parser.add_argument("cmd", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    logging.basicConfig(level=logging.INFO)
    if args.k8s_status or args.k8s_down:
        # cmd-less verbs: operate on an existing session by name
        import json as _json

        from torchft_tpu.k8s import status, teardown

        if args.k8s_status:
            print(
                _json.dumps(
                    status(
                        args.name,
                        namespace=args.namespace,
                        kubectl=args.kubectl,
                    ),
                    indent=1,
                )
            )
        if args.k8s_down:
            teardown(
                args.name, namespace=args.namespace, kubectl=args.kubectl
            )
        return
    if not cmd:
        parser.error("no command given (use: launcher [opts] -- cmd ...)")
    if args.emit_k8s or args.k8s_apply:
        if args.shared_runtime:
            parser.error("--emit-k8s/--k8s-apply do not support --shared-runtime yet: "
                         "the manifests would lack the TORCHFT_COHORT_* "
                         "wiring and workers would silently fall back to "
                         "per-group runtimes")
        from torchft_tpu.k8s import emit_manifests, submit

        manifests = emit_manifests(
            cmd,
            name=args.name,
            image=args.image,
            num_groups=args.groups,
            nproc=args.nproc,
            min_replicas=args.min_replicas,
            max_restarts=args.max_restarts,
            namespace=args.namespace,
            tpu_accelerator=args.tpu_accelerator,
            tpu_topology=args.tpu_topology,
        )
        if args.k8s_apply:
            submit(manifests, namespace=args.namespace, kubectl=args.kubectl)
        else:
            print(manifests, end="")
        return
    if args.k8s_worker:
        sys.exit(k8s_worker(cmd))
    if args.shared_runtime:
        if args.nproc != 1:
            parser.error("--shared-runtime requires --nproc 1 (one jax "
                         "runtime per process)")
        if args.min_replicas is not None:
            parser.error("--shared-runtime is cohort-grained: membership "
                         "is static, --min-replicas does not apply")
        sys.exit(
            launch_shared_runtime(
                cmd,
                num_groups=args.groups,
                lighthouse_addr=args.lighthouse,
                max_restarts=args.max_restarts,
            )
        )
    sys.exit(
        launch(
            cmd,
            num_groups=args.groups,
            nproc=args.nproc,
            lighthouse_addr=args.lighthouse,
            max_restarts=args.max_restarts,
            min_replicas=args.min_replicas,
        )
    )


if __name__ == "__main__":
    main()
