"""Cross-group device plane for separate-PROCESS groups sharing a slice.

``CollectivesDevice`` (collectives_device.py) averages gradients over an
elastic ``'ft'`` mesh axis, but its rendezvous is an in-process registry —
it requires every replica group to live in ONE Python process. The
builder's own launcher and k8s manifests put each group in its own
process, where averaging previously fell back to the host TCP/CMA plane
(round-3 review missing #1/#6).

``CollectivesDeviceDist`` closes that gap for the one-slice topology: all
replica-group processes join a single multi-controller JAX runtime
(``jax.distributed``), and cross-group averaging is ONE jitted
``shard_map``/``psum`` over a global ``'ft'`` axis spanning the
processes — the cross-process reduction rides ICI, the role
NCCL-over-NVLink plays for the reference's same-host process groups
(process_group.py:431-447). The current API takes host numpy buffers
(one D2H/H2D hop each side of the psum, like the host plane's bucket
path); a device-array fast path (``device_arrays=True``) is the natural
next step once a multi-chip box exists to measure it on.

The price of the shared runtime is STATIC membership: multi-controller
JAX cannot lose a member and live. ``configure`` therefore validates the
quorum cohort == the runtime cohort and raises on any mismatch — the
supervisor then restarts the whole cohort (the k8s Indexed-Job pattern,
launcher.py), or the caller falls back to the host plane, which is what
the elastic path is for. Plane selection table: README "Choosing a
cross-group data plane".

Runtime bootstrap: call ``jax.distributed.initialize`` before first jax
use (the launcher's ``--jax-coordinator`` wiring or
``init_distributed`` below), one process per replica group.
"""

from __future__ import annotations

from datetime import timedelta
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from torchft_tpu.collectives import Collectives, ReduceOp, Work
from torchft_tpu.futures import Future

__all__ = ["CollectivesDeviceDist", "init_distributed", "init_from_env"]


def init_distributed(
    coordinator: str, num_processes: int, process_id: int
) -> None:
    """Join the shared runtime (idempotent). Must run before first jax
    use in the process; the launcher can do this for you."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def init_from_env() -> bool:
    """Join the shared runtime from the launcher's cohort env contract
    (``torchft_tpu.launcher --shared-runtime`` exports
    TORCHFT_COHORT_COORDINATOR / _SIZE / _ID). Returns whether a cohort
    was configured; call before first jax use."""
    import os

    coordinator = os.environ.get("TORCHFT_COHORT_COORDINATOR")
    if not coordinator:
        return False
    init_distributed(
        coordinator,
        int(os.environ["TORCHFT_COHORT_SIZE"]),
        int(os.environ["TORCHFT_COHORT_ID"]),
    )
    return True


class CollectivesDeviceDist(Collectives):
    def __init__(self, timeout: timedelta = timedelta(seconds=60)) -> None:
        # Per-op deadlines cannot interrupt a compiled collective; on this
        # plane LIVENESS is the shared runtime's own job (jax.distributed
        # heartbeats kill the cohort when a member wedges, and the
        # launcher's cohort supervision respawns it). The timeout arg is
        # kept for Collectives-API symmetry only.
        self._timeout = timeout
        self._rank = -1
        self._world = 0
        self._mesh = None
        self._jit_cache: Dict[Tuple, Callable] = {}

    # -- lifecycle --

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        import jax
        from jax.sharding import Mesh

        # the cohort check applies to world_size==1 too: a quorum shrunk
        # to one on a 2-process runtime must RAISE (silently no-op
        # allreducing alone — or two partitioned singletons diverging —
        # is exactly what the contract forbids)
        if jax.process_count() != world_size or jax.process_index() != rank:
            raise RuntimeError(
                "CollectivesDeviceDist needs quorum cohort == runtime "
                f"cohort: quorum says rank {rank}/{world_size}, the shared "
                f"jax runtime says {jax.process_index()}/"
                f"{jax.process_count()}. A shrunken quorum cannot ride a "
                "multi-controller runtime — restart the cohort (launcher/"
                "k8s Job) or fall back to the host plane."
            )
        # one device per process carries the cross-group payload; the
        # group's inner mesh (if any) keeps using all local devices
        devs = np.empty(world_size, dtype=object)
        for d in jax.devices():
            if d.process_index < world_size and devs[d.process_index] is None:
                devs[d.process_index] = d
        if any(d is None for d in devs):
            raise RuntimeError("some process contributes no devices")
        self._mesh = Mesh(devs, ("ft",))
        self._rank = rank
        self._world = world_size
        self._jit_cache.clear()

    def shutdown(self) -> None:
        self._mesh = None

    def size(self) -> int:
        return self._world

    def rank(self) -> int:
        return self._rank

    # -- plumbing --

    def _reduce_jit(self, shape, dtype, op: ReduceOp) -> Callable:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (tuple(shape), str(dtype), op)
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn
        mesh = self._mesh
        world = self._world

        def block(x):  # x: local [1, *shape] block
            if op in (ReduceOp.SUM, ReduceOp.AVG):
                r = jax.lax.psum(x, "ft")
                if op == ReduceOp.AVG:
                    r = r / world
            elif op == ReduceOp.MAX:
                r = jax.lax.pmax(x, "ft")
            else:
                r = jax.lax.pmin(x, "ft")
            return r

        reduced = jax.jit(
            jax.shard_map(
                block,
                mesh=mesh,
                in_specs=P("ft"),
                out_specs=P("ft"),
            ),
            out_shardings=NamedSharding(mesh, P("ft")),
        )
        self._jit_cache[key] = reduced
        return reduced

    def _gather_jit(self, shape, dtype) -> Callable:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (tuple(shape), str(dtype), "allgather")
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn
        fn = jax.jit(
            jax.shard_map(
                lambda x: jax.lax.all_gather(x, "ft", axis=0, tiled=True),
                mesh=self._mesh,
                in_specs=P("ft"),
                out_specs=P(),
                # all_gather(tiled) IS replicated over 'ft'; the VMA
                # checker just can't infer it through the tiled form
                check_vma=False,
            ),
            out_shardings=NamedSharding(self._mesh, P()),
        )
        self._jit_cache[key] = fn
        return fn

    def _allreduce_one(self, arr: np.ndarray, op: ReduceOp) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(self._mesh, P("ft"))
        host = np.ascontiguousarray(arr)[None, ...]
        garr = jax.make_array_from_process_local_data(
            sharding, host, (self._world, *arr.shape)
        )
        out = self._reduce_jit(arr.shape, arr.dtype, op)(garr)
        shard = out.addressable_shards[0].data
        arr[...] = np.asarray(shard)[0]

    # -- collectives --

    def allreduce(self, arrays: List[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        try:
            if self._world > 1:
                for arr in arrays:
                    self._allreduce_one(arr, op)
            elif op == ReduceOp.AVG:
                pass  # world 1: average of one is identity
            return Work.completed(arrays)
        except Exception as e:  # noqa: BLE001 — surface through the future
            fut: Future = Future()
            fut.set_exception(e)
            return Work(fut)

    def allgather(self, arr: np.ndarray) -> Work:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        try:
            if self._world == 1:
                return Work.completed([arr.copy()])
            sharding = NamedSharding(self._mesh, P("ft"))
            garr = jax.make_array_from_process_local_data(
                sharding, np.ascontiguousarray(arr)[None, ...],
                (self._world, *arr.shape),
            )
            gathered = self._gather_jit(arr.shape, arr.dtype)(garr)
            local = np.asarray(gathered.addressable_shards[0].data)
            return Work.completed([local[i] for i in range(self._world)])
        except Exception as e:  # noqa: BLE001
            fut: Future = Future()
            fut.set_exception(e)
            return Work(fut)

    def broadcast(self, arr: np.ndarray, root: int = 0) -> Work:
        out = self.allgather(arr)

        def pick(f: Future):
            arr[...] = f.value()[root]
            return arr

        return Work(out.get_future().then(pick))

    def reduce_scatter(
        self, arrays: List[np.ndarray], op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        raise NotImplementedError(
            "reduce_scatter is not offered on the shared-runtime plane; "
            "use CollectivesTcp (host) for non-allreduce collectives"
        )

    def alltoall(self, arrays: List[np.ndarray]) -> Work:
        raise NotImplementedError(
            "alltoall is not offered on the shared-runtime plane"
        )

    def send(self, arr: np.ndarray, dst: int, tag: int = 0) -> Work:
        raise NotImplementedError(
            "p2p is not offered on the shared-runtime plane; checkpoint "
            "heals ride the HTTP transport"
        )

    def recv(self, arr: np.ndarray, src: int, tag: int = 0) -> Work:
        raise NotImplementedError(
            "p2p is not offered on the shared-runtime plane; checkpoint "
            "heals ride the HTTP transport"
        )

    def barrier(self) -> Work:
        one = np.ones(1, dtype=np.float32)
        return Work(
            self.allreduce([one], ReduceOp.SUM).get_future().then(lambda f: None)
        )
