"""Cross-group device plane for separate-PROCESS groups sharing a slice.

``CollectivesDevice`` (collectives_device.py) averages gradients over an
elastic ``'ft'`` mesh axis, but its rendezvous is an in-process registry —
it requires every replica group to live in ONE Python process. The
builder's own launcher and k8s manifests put each group in its own
process, where averaging previously fell back to the host TCP/CMA plane
(round-3 review missing #1/#6).

``CollectivesDeviceDist`` closes that gap for the one-slice topology: all
replica-group processes join a single multi-controller JAX runtime
(``jax.distributed``), and cross-group averaging is ONE jitted
``shard_map``/``psum`` over a global ``'ft'`` axis spanning the
processes — the cross-process reduction rides ICI, the role
NCCL-over-NVLink plays for the reference's same-host process groups
(process_group.py:431-447). The current API takes host numpy buffers
(one D2H/H2D hop each side of the psum, like the host plane's bucket
path); a device-array fast path (``device_arrays=True``) is the natural
next step once a multi-chip box exists to measure it on.

The price of the shared runtime is STATIC membership: multi-controller
JAX cannot lose a member and live. ``configure`` therefore validates the
quorum cohort == the runtime cohort and raises on any mismatch — the
supervisor then restarts the whole cohort (the k8s Indexed-Job pattern,
launcher.py), or the caller falls back to the host plane, which is what
the elastic path is for. Plane selection table: README "Choosing a
cross-group data plane".

Op surface (round-4 review missing #2 closed): the symmetric
collectives — allreduce, allgather, broadcast, reduce_scatter,
alltoall, barrier — ride the device mesh (psum / all_gather /
psum_scatter / all_to_all over the global ``'ft'`` axis). Point-to-point
``send``/``recv`` cannot ride a multi-controller runtime (a compiled
collective needs every process in the same program; p2p involves two),
so they ride a host TCP side-channel — an embedded
:class:`~torchft_tpu.collectives.CollectivesTcp` configured on the same
epoch store — which is also what makes
:class:`~torchft_tpu.checkpointing.collectives_transport.CollectivesTransport`
(live heals) work on this plane. This mirrors how NCCL separates
collective rings from p2p channels. Non-uniform input lists for
reduce_scatter/alltoall (per-slot shapes/dtypes) take the side-channel
too; the device path requires a stackable list.

Runtime bootstrap: call ``jax.distributed.initialize`` before first jax
use (the launcher's ``--jax-coordinator`` wiring or
``init_distributed`` below), one process per replica group.
"""

from __future__ import annotations

from datetime import timedelta
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from torchft_tpu.collectives import Collectives, ReduceOp, Work
from torchft_tpu.futures import Future

__all__ = ["CollectivesDeviceDist", "init_distributed", "init_from_env"]


def init_distributed(
    coordinator: str, num_processes: int, process_id: int
) -> None:
    """Join the shared runtime (idempotent). Must run before first jax
    use in the process; the launcher can do this for you."""
    import jax

    from torchft_tpu.utils.jax_compat import enable_cpu_gloo_collectives

    enable_cpu_gloo_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def init_from_env() -> bool:
    """Join the shared runtime from the launcher's cohort env contract
    (``torchft_tpu.launcher --shared-runtime`` exports
    TORCHFT_COHORT_COORDINATOR / _SIZE / _ID). Returns whether a cohort
    was configured; call before first jax use."""
    import os

    coordinator = os.environ.get("TORCHFT_COHORT_COORDINATOR")
    if not coordinator:
        return False
    init_distributed(
        coordinator,
        int(os.environ["TORCHFT_COHORT_SIZE"]),
        int(os.environ["TORCHFT_COHORT_ID"]),
    )
    return True


class CollectivesDeviceDist(Collectives):
    def __init__(self, timeout: timedelta = timedelta(seconds=60)) -> None:
        # Per-op deadlines cannot interrupt a compiled collective; on this
        # plane LIVENESS is the shared runtime's own job (jax.distributed
        # heartbeats kill the cohort when a member wedges, and the
        # launcher's cohort supervision respawns it). The timeout arg is
        # kept for Collectives-API symmetry only.
        self._timeout = timeout
        self._rank = -1
        self._world = 0
        self._mesh = None
        self._jit_cache: Dict[Tuple, Callable] = {}
        # host TCP side-channel for p2p (and ragged reduce_scatter/
        # alltoall): created at first configure, reconfigured per epoch
        self._p2p: Optional[Any] = None

    # -- lifecycle --

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        import jax
        from jax.sharding import Mesh

        # the cohort check applies to world_size==1 too: a quorum shrunk
        # to one on a 2-process runtime must RAISE (silently no-op
        # allreducing alone — or two partitioned singletons diverging —
        # is exactly what the contract forbids)
        if jax.process_count() != world_size or jax.process_index() != rank:
            raise RuntimeError(
                "CollectivesDeviceDist needs quorum cohort == runtime "
                f"cohort: quorum says rank {rank}/{world_size}, the shared "
                f"jax runtime says {jax.process_index()}/"
                f"{jax.process_count()}. A shrunken quorum cannot ride a "
                "multi-controller runtime — restart the cohort (launcher/"
                "k8s Job) or fall back to the host plane."
            )
        # one device per process carries the cross-group payload; the
        # group's inner mesh (if any) keeps using all local devices
        devs = np.empty(world_size, dtype=object)
        for d in jax.devices():
            if d.process_index < world_size and devs[d.process_index] is None:
                devs[d.process_index] = d
        if any(d is None for d in devs):
            raise RuntimeError("some process contributes no devices")
        self._mesh = Mesh(devs, ("ft",))
        self._rank = rank
        self._world = world_size
        self._jit_cache.clear()
        # p2p side-channel: every cohort member reaches configure (the
        # Manager reconfigures all members on a quorum change), so the
        # full-mesh TCP dial inside is a safe per-epoch barrier. Plain
        # sockets only (native_plane=False): bulk traffic rides ICI; this
        # channel exists for heals and ragged ops. A store is required
        # for its rendezvous — standalone use with store_addr="" keeps
        # the symmetric device collectives and loses only p2p.
        if store_addr:
            from torchft_tpu.collectives import CollectivesTcp

            if self._p2p is None:
                self._p2p = CollectivesTcp(
                    timeout=self._timeout, native_plane=False
                )
            self._p2p.configure(store_addr, rank, world_size)
        elif self._p2p is not None:
            self._p2p.shutdown()
            self._p2p = None

    def shutdown(self) -> None:
        if self._p2p is not None:
            self._p2p.shutdown()
            self._p2p = None
        self._mesh = None

    def size(self) -> int:
        return self._world

    def rank(self) -> int:
        return self._rank

    def plane_info(self) -> str:
        """Dashboard label: ICI psum plane (+TCP p2p side-channel)."""
        return "device-dist"

    # -- plumbing --

    def _cached_jit(self, key: Tuple, body, replicated_out: bool = False,
                    **shard_map_kwargs) -> Callable:
        """Build-or-fetch the jitted shard_map for ``body`` over 'ft'."""
        fn = self._jit_cache.get(key)
        if fn is not None:
            return fn
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        import torchft_tpu.utils.jax_compat  # noqa: F401 — polyfills older jax

        out_spec = P() if replicated_out else P("ft")
        fn = jax.jit(
            jax.shard_map(
                body,
                mesh=self._mesh,
                in_specs=P("ft"),
                out_specs=out_spec,
                **shard_map_kwargs,
            ),
            out_shardings=NamedSharding(self._mesh, out_spec),
        )
        self._jit_cache[key] = fn
        return fn

    def _stage(self, host_block: np.ndarray):
        """Place this process's ``[1, ...]`` host block as its shard of
        the 'ft'-sharded global array."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.make_array_from_process_local_data(
            NamedSharding(self._mesh, P("ft")),
            host_block,
            (self._world, *host_block.shape[1:]),
        )

    def _reduce_jit(self, shape, dtype, op: ReduceOp) -> Callable:
        import jax

        world = self._world

        def block(x):  # x: local [1, *shape] block
            if op in (ReduceOp.SUM, ReduceOp.AVG):
                r = jax.lax.psum(x, "ft")
                if op == ReduceOp.AVG:
                    r = r / world
            elif op == ReduceOp.MAX:
                r = jax.lax.pmax(x, "ft")
            else:
                r = jax.lax.pmin(x, "ft")
            return r

        return self._cached_jit((tuple(shape), str(dtype), op), block)

    def _gather_jit(self, shape, dtype) -> Callable:
        import jax

        return self._cached_jit(
            (tuple(shape), str(dtype), "allgather"),
            lambda x: jax.lax.all_gather(x, "ft", axis=0, tiled=True),
            replicated_out=True,
            # all_gather(tiled) IS replicated over 'ft'; the VMA
            # checker just can't infer it through the tiled form
            check_vma=False,
        )

    @staticmethod
    def _check_avg_dtype(op: ReduceOp, dtype: np.dtype) -> None:
        """AVG on integer inputs would silently truncate on the host-copy
        assignment here, while the host TCP plane's in-place np.divide
        raises a casting error — keep the planes' failure semantics
        identical (round-4 advisor low)."""
        if op == ReduceOp.AVG and not np.issubdtype(dtype, np.inexact):
            raise TypeError(
                f"ReduceOp.AVG on dtype {np.dtype(dtype)} would truncate; "
                "cast to a float dtype first (matches the host plane's "
                "np.divide casting error)"
            )

    def _allreduce_one(self, arr: np.ndarray, op: ReduceOp) -> None:
        self._check_avg_dtype(op, arr.dtype)
        garr = self._stage(np.ascontiguousarray(arr)[None, ...])
        out = self._reduce_jit(arr.shape, arr.dtype, op)(garr)
        arr[...] = np.asarray(out.addressable_shards[0].data)[0]

    # -- collectives --

    def allreduce(self, arrays: List[np.ndarray], op: ReduceOp = ReduceOp.SUM) -> Work:
        try:
            if self._world > 1:
                for arr in arrays:
                    self._allreduce_one(arr, op)
            elif op == ReduceOp.AVG:
                pass  # world 1: average of one is identity
            return Work.completed(arrays)
        except Exception as e:  # noqa: BLE001 — surface through the future
            return Work.failed(e)

    def allgather(self, arr: np.ndarray) -> Work:
        try:
            if self._world == 1:
                return Work.completed([arr.copy()])
            garr = self._stage(np.ascontiguousarray(arr)[None, ...])
            gathered = self._gather_jit(arr.shape, arr.dtype)(garr)
            local = np.asarray(gathered.addressable_shards[0].data)
            return Work.completed(
                [local[i].copy() for i in range(self._world)]
            )
        except Exception as e:  # noqa: BLE001 — surface through the future
            return Work.failed(e)

    def broadcast(self, arr: np.ndarray, root: int = 0) -> Work:
        out = self.allgather(arr)

        def pick(f: Future):
            arr[...] = f.value()[root]
            return arr

        return Work(out.get_future().then(pick))

    @staticmethod
    def _uniform(arrays: List[np.ndarray]) -> bool:
        first = arrays[0]
        return all(
            a.shape == first.shape and a.dtype == first.dtype
            for a in arrays[1:]
        )

    def _rs_jit(self, shape, dtype) -> Callable:
        import jax

        # global [world, world, *shape], dim 0 sharded on 'ft' (the
        # contributing rank), dim 1 the destination slot; psum_scatter
        # over slots leaves rank r holding sum_contributors(slot r)
        return self._cached_jit(
            (tuple(shape), str(dtype), "reduce_scatter"),
            lambda x: jax.lax.psum_scatter(
                x, "ft", scatter_dimension=1, tiled=False
            ),
        )

    def _a2a_jit(self, shape, dtype) -> Callable:
        import jax

        # local block [1, world, *shape]: split the slot dim across 'ft',
        # concatenate along the (sharded) leading dim — rank r ends with
        # [world, 1, *shape] where entry j is rank j's slot r
        return self._cached_jit(
            (tuple(shape), str(dtype), "alltoall"),
            lambda x: jax.lax.all_to_all(
                x, "ft", split_axis=1, concat_axis=0, tiled=True
            ),
        )

    def reduce_scatter(
        self, arrays: List[np.ndarray], op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        try:
            if len(arrays) != self._world:
                raise ValueError(
                    f"reduce_scatter needs {self._world} inputs, "
                    f"got {len(arrays)}"
                )
            # dtype check BEFORE the world==1 return: the host plane's
            # np.divide raises for AVG-on-int even at world 1
            self._check_avg_dtype(op, arrays[0].dtype)
            if self._world == 1:
                return Work.completed(arrays[0].copy())
            if not self._uniform(arrays):
                # ragged slots can't stack into one device array
                return self._p2p_or_raise().reduce_scatter(arrays, op)
            if op not in (ReduceOp.SUM, ReduceOp.AVG):
                # psum_scatter is sum-only; max/min scatter is a host op
                return self._p2p_or_raise().reduce_scatter(arrays, op)
            shape, dtype = arrays[0].shape, arrays[0].dtype
            garr = self._stage(np.ascontiguousarray(np.stack(arrays))[None])
            out_g = self._rs_jit(shape, dtype)(garr)
            # np.asarray of a jax shard is a READ-ONLY view; the host
            # plane returns writable arrays, so copy (alltoall below
            # and allgather do the same)
            out = np.array(np.asarray(out_g.addressable_shards[0].data)[0])
            if op == ReduceOp.AVG:
                out = out / self._world
            return Work.completed(out.astype(dtype, copy=False))
        except Exception as e:  # noqa: BLE001 — surface through the future
            return Work.failed(e)

    def alltoall(self, arrays: List[np.ndarray]) -> Work:
        try:
            if len(arrays) != self._world:
                raise ValueError(
                    f"alltoall needs {self._world} inputs, got {len(arrays)}"
                )
            if self._world == 1:
                return Work.completed([arrays[0].copy()])
            if not self._uniform(arrays):
                return self._p2p_or_raise().alltoall(arrays)
            shape, dtype = arrays[0].shape, arrays[0].dtype
            garr = self._stage(np.ascontiguousarray(np.stack(arrays))[None])
            out_g = self._a2a_jit(shape, dtype)(garr)
            local = np.asarray(out_g.addressable_shards[0].data)
            # local: [world, 1, *shape] — entry j is rank j's slot for us
            return Work.completed(
                [local[j, 0].copy() for j in range(self._world)]
            )
        except Exception as e:  # noqa: BLE001 — surface through the future
            return Work.failed(e)

    def _p2p_or_raise(self):
        if self._p2p is None:
            raise RuntimeError(
                "the p2p side-channel needs a store rendezvous: "
                "configure() with a non-empty store_addr (the Manager "
                "always does)"
            )
        return self._p2p

    def send(self, arr: np.ndarray, dst: int, tag: int = 0) -> Work:
        return self._p2p_or_raise().send(arr, dst, tag)

    def recv(self, arr: np.ndarray, src: int, tag: int = 0) -> Work:
        return self._p2p_or_raise().recv(arr, src, tag)

    def barrier(self) -> Work:
        one = np.ones(1, dtype=np.float32)
        return Work(
            self.allreduce([one], ReduceOp.SUM).get_future().then(lambda f: None)
        )
