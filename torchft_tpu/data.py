"""Fault-tolerant data sharding — the DistributedSampler analogue.

Reference: torchft/data.py:24-77. Shards the dataset over a virtual grid of
``num_replica_groups × num_replicas`` workers: this worker takes global
shard ``rank + num_replicas * replica_group`` of
``num_replicas * num_replica_groups``. Deliberately lossy on failure: if a
replica group dies, its shard simply isn't visited this epoch — for
pretraining-scale corpora that bias is negligible and it keeps recovery
stateless (same design call as the reference's docstring).

Torch-free iterable; also usable as a ``torch.utils.data`` sampler since it
just yields indices.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

__all__ = ["DistributedSampler", "step_indices"]


def step_indices(sampler: "DistributedSampler", step: int, batch: int) -> np.ndarray:
    """This group's sample indices for committed step ``step``.

    Derives the sampler's (epoch, position) purely from the committed step
    count — the one clock every replica group provably agrees on — so a
    killed/healed/disk-resumed group picks up exactly where its last
    committed step left off (no sample double-trained, none skipped) and
    groups can never desync epochs (partitions stay disjoint). Crosses
    epoch boundaries as needed; a failed commit retries the same batch
    because the step didn't advance. The reference leans on torchdata's
    StatefulDataLoader position checkpointing for this
    (train_ddp.py:57-61); deriving from the committed step is strictly
    stronger — correct even when the position snapshot is stale."""
    part_len = len(sampler)
    parts = [np.empty(0, dtype=np.int64)]
    pos = step * batch
    need = batch
    while need > 0:
        epoch, off = divmod(pos, part_len)
        chunk = sampler._partition(epoch)[off : off + need]
        parts.append(chunk)
        pos += chunk.size
        need -= chunk.size
    return np.concatenate(parts).astype(np.int64, copy=False)


class DistributedSampler:
    def __init__(
        self,
        dataset_len: int,
        replica_group: int,
        num_replica_groups: int,
        rank: int = 0,
        num_replicas: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ) -> None:
        """
        Args:
            dataset_len: number of examples (or pass a sized dataset's len)
            replica_group: which fault-tolerance replica group this is
            num_replica_groups: total replica groups in the job
            rank: local rank within the replica group
            num_replicas: local world size within the replica group
        """
        self._dataset_len = dataset_len
        self._global_rank = rank + num_replicas * replica_group
        self._global_world = num_replicas * num_replica_groups
        self._shuffle = shuffle
        self._seed = seed
        self._drop_last = drop_last
        self._epoch = 0
        self._position = 0  # resume offset within the current epoch
        # one-epoch partition cache: step_indices is called every training
        # step, and regenerating rng.permutation(dataset_len) per step is
        # O(dataset) time/memory — at odds with the pretraining-scale
        # target (round-3 advisor finding)
        self._part_cache: tuple[int, np.ndarray] | None = None

    def set_epoch(self, epoch: int) -> None:
        """Reseed shuffling per epoch (all workers must agree)."""
        self._epoch = epoch

    # dataloader-position checkpointing (the reference leans on torchdata's
    # StatefulDataLoader for this — train_ddp.py:57-61; here it's built in)
    def state_dict(self) -> dict:
        return {"epoch": self._epoch, "position": self._position}

    def load_state_dict(self, state: dict) -> None:
        self._epoch = state["epoch"]
        self._position = state["position"]

    def __len__(self) -> int:
        if self._drop_last:
            return self._dataset_len // self._global_world
        return (
            self._dataset_len + self._global_world - 1
        ) // self._global_world

    def _partition(self, epoch: int) -> np.ndarray:
        """This worker's full index partition for ``epoch`` (cached — the
        permutation is regenerated only when the epoch changes)."""
        if self._part_cache is not None and self._part_cache[0] == epoch:
            return self._part_cache[1]
        if self._shuffle:
            rng = np.random.default_rng(self._seed + epoch)
            order = rng.permutation(self._dataset_len)
        else:
            order = np.arange(self._dataset_len)
        target = len(self) * self._global_world
        if self._drop_last:
            order = order[:target]
        else:
            # pad (tiling as needed) to a grid multiple so every worker
            # sees exactly len(self) indices and replicas stay in lockstep
            order = np.resize(order, target)
        mine = np.ascontiguousarray(order[self._global_rank :: self._global_world])
        self._part_cache = (epoch, mine)
        return mine

    def __iter__(self) -> Iterator[int]:
        mine = self._partition(self._epoch)
        start = self._position
        for i, idx in enumerate(mine[start:].tolist()):
            self._position = start + i + 1
            yield idx
        self._position = 0  # epoch exhausted
