"""Commit-gated optimizer — the OptimizerWrapper analogue for optax.

The reference wraps a torch optimizer so ``zero_grad()`` starts the quorum
and ``step()`` only applies when the group votes to commit
(torchft/optim.py:48-55). Torch mutates the model in place, which is also
how a healed checkpoint reaches the optimizer mid-step; in JAX the state is
immutable pytrees, so this wrapper *owns* them — recovery (which lands via
the manager's ``load_state_dict`` callback inside ``should_commit``)
replaces the internal pytrees before the update applies::

    opt = ManagedOptimizer(manager, optax.adam(1e-3))
    opt.init(params)                      # registers state fns on the manager
    for batch in data:
        opt.begin_step()                  # zero_grad() analogue: start quorum
        loss, grads = value_and_grad_fn(opt.params, batch)
        opt.step(grads)                   # average + commit gate + update

``step`` averages gradients across replica groups through the Manager and
applies the optax update only if ``should_commit()`` — otherwise the state
is untouched and the step is discarded.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from torchft_tpu.ddp import allreduce_gradients
from torchft_tpu.manager import Manager

__all__ = ["ManagedOptimizer"]


class ManagedOptimizer:
    def __init__(self, manager: Manager, tx, register_state: bool = True) -> None:
        """``tx`` is an ``optax.GradientTransformation``. With
        ``register_state`` (default) ``init`` wires this wrapper's
        state_dict/load_state_dict into the manager so live recovery
        restores params and optimizer state automatically; pass False if the
        user snapshot covers more than the optimizer (then include
        ``opt.state_dict()`` in it)."""
        self._manager = manager
        self._tx = tx
        self._register_state = register_state
        self._apply = None
        self._params: Optional[Any] = None
        self._opt_state: Optional[Any] = None

    # -- state --

    @property
    def params(self) -> Any:
        assert self._params is not None, "call init(params) first"
        return self._params

    @property
    def opt_state(self) -> Any:
        return self._opt_state

    def init(self, params: Any) -> None:
        self._params = params
        self._opt_state = self._tx.init(params)
        if self._register_state:
            self._manager.set_state_dict_fns(self.load_state_dict, self.state_dict)

    def state_dict(self) -> Dict[str, Any]:
        return {"params": self._params, "opt_state": self._opt_state}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._params = state["params"]
        self._opt_state = state["opt_state"]

    # -- step --

    def begin_step(self, allow_heal: bool = True, shrink_only: bool = False) -> None:
        """Start the (async) quorum — call before the forward pass so the
        RPC overlaps compute (the reference hooks this into zero_grad)."""
        self._manager.start_quorum(allow_heal=allow_heal, shrink_only=shrink_only)

    def step(self, grads: Any, average: bool = True) -> Any:
        """Average ``grads`` across replica groups, then apply the update
        iff the step commits. Returns the current params (healed and/or
        updated). Pass ``average=False`` if the gradients already went
        through ``manager.allreduce``."""
        if average:
            grads = allreduce_gradients(self._manager, grads)
        committed = self._manager.should_commit()
        # should_commit may have healed: self._params now reflects the
        # recovered state; the gradient applied to it is the participants'
        # average (a healing replica contributed zeros)
        if committed:
            self._params, self._opt_state = self._apply_update(
                self._params, self._opt_state, grads
            )
        return self._params

    def _apply_update(self, params: Any, opt_state: Any, grads: Any):
        if self._apply is None:
            import jax
            import optax

            tx = self._tx

            @jax.jit
            def apply(params, opt_state, grads):
                updates, new_state = tx.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), new_state

            self._apply = apply
        return self._apply(params, opt_state, grads)
