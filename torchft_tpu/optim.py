"""Commit-gated optimizer — the OptimizerWrapper analogue for optax.

The reference wraps a torch optimizer so ``zero_grad()`` starts the quorum
and ``step()`` only applies when the group votes to commit
(torchft/optim.py:48-55). Torch mutates the model in place, which is also
how a healed checkpoint reaches the optimizer mid-step; in JAX the state is
immutable pytrees, so this wrapper *owns* them — recovery (which lands via
the manager's ``load_state_dict`` callback inside ``should_commit``)
replaces the internal pytrees before the update applies::

    opt = ManagedOptimizer(manager, optax.adam(1e-3))
    opt.init(params)                      # registers state fns on the manager
    for batch in data:
        opt.begin_step()                  # zero_grad() analogue: start quorum
        loss, grads = value_and_grad_fn(opt.params, batch)
        opt.step(grads)                   # average + commit gate + update

``step`` averages gradients across replica groups through the Manager and
applies the optax update only if ``should_commit()`` — otherwise the state
is untouched and the step is discarded.

Pipelined commit (``Manager(commit_pipeline=True)``,
docs/commit_pipeline.md): ``step`` applies the update speculatively,
issues the vote asynchronously, and the vote from step *k* resolves inside
step *k+1*'s ``step()`` — so the value_and_grad between ``begin_step`` and
``step`` overlaps the vote RTT. On a veto the pre-update pytrees are
restored; pass ``grad_fn`` (``params -> grads``) so the in-flight batch
can be replayed on the restored state — without it, a rollback also drops
the in-flight batch (the vetoed batch is dropped either way, exactly as
in sync mode).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from torchft_tpu.ddp import allreduce_gradients
from torchft_tpu.manager import Manager
from torchft_tpu.wire_codec import ErrorFeedback, ErrorFeedbackBinding

__all__ = ["ManagedOptimizer"]


class SpeculativeCommitMixin:
    """Shared pipelined-commit snapshot plumbing (used by both
    :class:`ManagedOptimizer` and
    :class:`~torchft_tpu.parallel.ft.FTTrainer`).

    The owner keeps its live pytrees in ``_params`` / ``_opt_state`` and
    its manager in ``_manager``; this mixin owns the rollback snapshot,
    the resolution callback, and the *sticky* replay flag — sticky so a
    vote resolved out-of-band (e.g. a caller who pre-averages via
    ``manager.allreduce`` must resolve first, because the manager refuses
    collectives while a vote is pending) still gets its rollback handled
    at the next ``step``."""

    _snapshot: Optional[Tuple[Any, Any]] = None
    _replay_needed = False
    _efb: Optional[ErrorFeedbackBinding] = None  # wire-plane error feedback
    rollbacks = 0  # speculative steps undone by a veto

    def _on_vote_resolved(self, committed: bool) -> None:
        """Runs on the main thread inside ``resolve_pending_commit``,
        before the speculation fence lifts — so the quorum thread can
        never observe a half-rolled-back (state, step) pair."""
        if not committed and self._snapshot is not None:
            self._params, self._opt_state = self._snapshot
            self.rollbacks += 1
            self._replay_needed = True
        self._snapshot = None
        # error-feedback residuals share the commit lineage: a vetoed
        # step's staged residual must never compensate the next step
        ef = self._efb.instance if self._efb is not None else None
        if ef is not None:
            if committed:
                ef.commit()
            else:
                ef.rollback()

    def _consume_replay(self) -> bool:
        """True once per rollback: the current in-flight gradients were
        computed on the rolled-back state and must be replayed/dropped."""
        if self._replay_needed:
            self._replay_needed = False
            return True
        return False

    def finish(self) -> Optional[bool]:
        """Resolve any outstanding speculative commit — call after the
        last ``step`` of a pipelined run (idempotent; returns the final
        vote, or None when nothing was outstanding)."""
        if self._manager.pending_commit() is None:
            return None
        return self._manager.resolve_pending_commit(rearm=False)


class ManagedOptimizer(SpeculativeCommitMixin):
    def __init__(
        self,
        manager: Manager,
        tx,
        register_state: bool = True,
        error_feedback: "Optional[ErrorFeedback | bool]" = None,
    ) -> None:
        """``tx`` is an ``optax.GradientTransformation``. With
        ``register_state`` (default) ``init`` wires this wrapper's
        state_dict/load_state_dict into the manager so live recovery
        restores params and optimizer state automatically; pass False if the
        user snapshot covers more than the optimizer (then include
        ``opt.state_dict()`` in it).

        ``error_feedback``: residual compensation for a lossy wire codec
        (docs/wire_plane.md). Default (None) AUTO-enables when the
        manager's data plane reports a lossy codec — the convergence-
        preserving configuration — unless ``TORCHFT_WIRE_EF=0``; pass
        ``False`` to force off or a prebuilt
        :class:`~torchft_tpu.wire_codec.ErrorFeedback` to share one."""
        self._manager = manager
        self._tx = tx
        self._register_state = register_state
        self._apply = None
        self._params: Optional[Any] = None
        self._opt_state: Optional[Any] = None
        # pipelined commit (SpeculativeCommitMixin state)
        self._snapshot = None
        self._replay_needed = False
        self.rollbacks = 0
        # wire-plane error feedback (accumulators ride state_dict through
        # heal/checkpoint; pending residuals follow the commit lineage)
        # auto/lazy/CMA-gate semantics live in the shared binding
        # (wire_codec.ErrorFeedbackBinding) — LocalSGD resolves the same
        # way, so the two wrappers cannot drift
        self._efb = ErrorFeedbackBinding(manager, error_feedback)

    @property
    def error_feedback(self) -> Optional[ErrorFeedback]:
        return self._efb.instance if self._efb is not None else None

    # -- state --

    @property
    def params(self) -> Any:
        assert self._params is not None, "call init(params) first"
        return self._params

    @property
    def opt_state(self) -> Any:
        return self._opt_state

    def init(self, params: Any) -> None:
        self._params = params
        self._opt_state = self._tx.init(params)
        if self._register_state:
            self._manager.set_state_dict_fns(self.load_state_dict, self.state_dict)

    def state_dict(self) -> Dict[str, Any]:
        snap = self._snapshot
        if snap is not None:
            # mid-speculation a peer must heal from COMMITTED state
            out = {"params": snap[0], "opt_state": snap[1]}
        else:
            out = {"params": self._params, "opt_state": self._opt_state}
        ef = self.error_feedback
        if ef is not None:
            # committed residuals only (state_dict() on ErrorFeedback
            # excludes pending) — a heal/checkpoint restart must resume
            # the compensation stream, not restart it from zero
            out["ef"] = ef.state_dict()
        return out

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._params = state["params"]
        self._opt_state = state["opt_state"]
        # a heal supersedes any speculative lineage — including a pending
        # replay: gradients of the NEXT step are taken on this healed
        # state, so they are valid, not vetoed-lineage leftovers
        self._snapshot = None
        self._replay_needed = False
        ef = self.error_feedback
        if ef is None and "ef" in state and self._efb is not None:
            # lazy auto mode (e.g. proxied backend): the heal may land
            # before the first live() — adopt the state's accumulators,
            # don't drop them
            ef = self._efb.ensure_for_state(state["ef"])
        if ef is not None:
            if "ef" in state:
                ef.load_state_dict(state["ef"])
            else:
                # healed from a peer without EF state: start clean rather
                # than compensate with residuals of a dead lineage
                ef.load_state_dict({"codec": None, "acc": {}})

    # -- step --

    def begin_step(self, allow_heal: bool = True, shrink_only: bool = False) -> None:
        """Start the (async) quorum — call before the forward pass so the
        RPC overlaps compute (the reference hooks this into zero_grad). In
        pipelined mode the previous vote stays in flight here too: it
        resolves inside the next ``step()``, so the caller's
        value_and_grad is the compute that hides the vote RTT."""
        self._manager.start_quorum(allow_heal=allow_heal, shrink_only=shrink_only)

    def step(
        self,
        grads: Any,
        average: bool = True,
        grad_fn: Optional[Callable[[Any], Any]] = None,
    ) -> Any:
        """Average ``grads`` across replica groups, then apply the update
        iff the step commits. Returns the current params (healed and/or
        updated). Pass ``average=False`` if the gradients already went
        through ``manager.allreduce``. ``grad_fn`` (``params -> grads``,
        pipelined mode only) recomputes the gradients after a rollback so
        the in-flight batch is replayed instead of dropped."""
        m = self._manager
        if m.pending_commit() is not None:
            # resolve the previous step's vote before this step's
            # collectives/commit (at most one speculative step outstanding)
            m.resolve_pending_commit()
        ef = self._efb.live()
        if self._consume_replay():
            # a rollback happened — here or out-of-band (an average=False
            # caller resolves before its own manager.allreduce): ``grads``
            # were computed on the rolled-back params
            if grad_fn is None:
                # cannot replay without the loss fn: drop this batch
                # too (documented pipelined-mode caveat)
                return self._params
            # fresh grads always go through the managed average — any
            # pre-averaging the caller did belongs to the vetoed lineage
            grads = allreduce_gradients(
                m, grad_fn(self._params), error_feedback=ef
            )
        elif average:
            grads = allreduce_gradients(m, grads, error_feedback=ef)
        if m.speculation_allowed():
            # publish the snapshot before the speculative apply so a
            # concurrent checkpoint serve never sees mid-update trees
            self._snapshot = (self._params, self._opt_state)
            self._params, self._opt_state = self._apply_update(
                self._params, self._opt_state, grads
            )
            # the staged EF residual stays PENDING with the vote; it is
            # promoted/discarded in _on_vote_resolved with the lineage
            m.should_commit_async(on_resolved=self._on_vote_resolved)
            return self._params
        committed = m.should_commit()
        # should_commit may have healed: self._params now reflects the
        # recovered state; the gradient applied to it is the participants'
        # average (a healing replica contributed zeros)
        ef_inst = self.error_feedback
        if ef_inst is not None:
            # heal inside should_commit restored EF state already (via
            # load_state_dict); commit/rollback is then a no-op on the
            # cleared pending set
            if committed:
                ef_inst.commit()
            else:
                ef_inst.rollback()
        if committed:
            self._params, self._opt_state = self._apply_update(
                self._params, self._opt_state, grads
            )
        return self._params

    def _apply_update(self, params: Any, opt_state: Any, grads: Any):
        # non-donating on purpose: the input pytrees double as the live
        # recovery snapshot and, in pipelined mode, as the rollback
        # snapshot — they must stay alive across the update
        if self._apply is None:
            import jax
            import optax

            tx = self._tx

            def apply(params, opt_state, grads):
                updates, new_state = tx.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), new_state

            self._apply = jax.jit(apply)
        return self._apply(params, opt_state, grads)
