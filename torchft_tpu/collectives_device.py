"""Device-path cross-replica-group collectives — the ICI data plane.

The reference's data plane between replica groups is NCCL over RDMA
(/root/reference/torchft/process_group.py:431-447): gradients never touch
the host. ``CollectivesTcp`` (the Gloo analogue) covers groups in separate
processes, but every byte it moves pays device→host→TCP→host→device. On
TPU the analogous fast path is XLA collectives over ICI: when replica
groups share one JAX runtime — one controller process driving a slice,
e.g. 4 groups × 8 chips on a v5e-32 — cross-group averaging can stay in
HBM end to end. ``CollectivesDevice`` is that backend (survey §7 item 3b).

Design:

* **arrays stay on device.** ``allreduce`` stacks each leaf across the
  participating groups into one global ``jax.Array`` over a mesh with a
  leading elastic ``'ft'`` axis (built from the groups' own inner meshes,
  which must be congruent), then runs a single jitted ``shard_map`` psum —
  XLA emits the ICI collectives. Results are handed back re-assembled on
  each group's original devices with its original sharding.
* **reconfiguration is cheap by construction.** Membership changes change
  only the tiny 'ft'-axis reduction kernel (re-jitted per (mesh, specs,
  world), cached); the model's compiled train step never recompiles —
  the same split the host backend guarantees, without the host.
* **the rendezvous is the same epoch namespace** the TCP backend uses
  (``{store}/torchft/{quorum_id}/{rank}`` — manager.py configure path),
  resolved through an in-process registry instead of sockets. Ops match
  across groups by an SPMD sequence number exactly like the TCP backend's
  frame tags; a kind mismatch at the same sequence raises the same
  "collective desync" error.

A group whose peer dies mid-op is protected by deadlines: every returned
``Work`` future fails with ``TimeoutError`` after the configured timeout,
and ``configure``/``shutdown`` fail all pending ops of the abandoned epoch
(the socket-shutdown analogue), so the Manager's latch → flush-reconfigure
path works identically over this backend.
"""

from __future__ import annotations

import threading
from collections import deque
from datetime import timedelta
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from torchft_tpu.collectives import Collectives, ReduceOp, Work
from torchft_tpu.futures import Future, future_timeout

__all__ = ["CollectivesDevice"]


def _epoch_key(store_addr: str) -> str:
    # `{store}/torchft/{quorum_id}/{rank}` → drop the rank: all members of
    # one epoch share the prefix (manager.py reconfigure path)
    return store_addr.rsplit("/", 1)[0]


class _Op:
    def __init__(self, kind: str, world: int, meta: Tuple) -> None:
        self.kind = kind
        self.world = world
        self.meta = meta
        self.inputs: Dict[int, Any] = {}
        self.futures: Dict[int, Future] = {}


class _Epoch:
    """One quorum epoch's in-process rendezvous state."""

    def __init__(self, key: str, world: int) -> None:
        self.key = key
        self.world = world
        self.lock = threading.Lock()
        self.joined: set = set()
        self.left: set = set()
        self.dead: Optional[Exception] = None
        self.ops: Dict[int, _Op] = {}  # seq tag → op
        self.sends: Dict[Tuple[int, int, int], deque] = {}
        self.recvs: Dict[Tuple[int, int, int], deque] = {}

    def fail_pending(self, exc: Exception) -> List[Future]:
        """Called under self.lock — detach every waiter and return the
        doomed futures for the CALLER to resolve AFTER releasing the
        lock. Resolving them in here ran arbitrary continuation callbacks
        (timeout-chain copies, flight-recorder completions, user ``then``
        chains) inside the epoch lock, so a continuation that re-entered
        the collectives deadlocked [found by the analysis gate:
        callback-under-lock]."""
        self.dead = exc
        doomed: List[Future] = []
        for op in self.ops.values():
            doomed.extend(op.futures.values())
        self.ops.clear()
        for waiters in self.recvs.values():
            doomed.extend(fut for fut, _arr in waiters)
        self.recvs.clear()
        self.sends.clear()
        return doomed


_REGISTRY: Dict[str, _Epoch] = {}
_REGISTRY_LOCK = threading.Lock()

# sentinel distinguishing "no buffered send matched" from a buffered None
_NOTHING = object()


def _devices_and_spec(arr) -> Tuple[np.ndarray, Tuple[str, ...], Any]:
    """Normalize an array's sharding to (device_matrix, axis_names, spec)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec, SingleDeviceSharding

    s = arr.sharding
    if isinstance(s, NamedSharding):
        return s.mesh.devices, tuple(s.mesh.axis_names), s.spec
    if isinstance(s, SingleDeviceSharding):
        devs = np.empty((), dtype=object)
        devs[()] = list(arr.devices())[0]
        return devs, (), PartitionSpec()
    raise TypeError(
        f"CollectivesDevice requires NamedSharding or SingleDeviceSharding "
        f"arrays, got {type(s).__name__}"
    )


def _congruent(ranks_arrays: Dict[int, Any], i: int) -> None:
    """All groups' i-th arrays must agree on shape/dtype/mesh-shape/spec."""
    base = None
    for rank in sorted(ranks_arrays):
        arr = ranks_arrays[rank][i]
        devs, names, spec = _devices_and_spec(arr)
        sig = (arr.shape, str(arr.dtype), devs.shape, names, spec)
        if base is None:
            base = sig
        elif sig != base:
            raise RuntimeError(
                f"collective desync: group meshes/shardings not congruent "
                f"for array {i}: {sig} vs {base}"
            )


_PSUM_CACHE: Dict[Tuple, Callable] = {}
_PSUM_CACHE_LOCK = threading.Lock()


def _reduction_fn(mesh, specs: Tuple, op: ReduceOp, world: int) -> Callable:
    """Jitted shard_map reduction over the 'ft' axis; cached per
    (mesh, specs, op, world) so steady-state steps never recompile."""
    import jax

    import torchft_tpu.utils.jax_compat  # noqa: F401 — polyfills older jax

    key = (mesh, specs, op, world)
    with _PSUM_CACHE_LOCK:
        fn = _PSUM_CACHE.get(key)
    if fn is not None:
        return fn

    red = {
        ReduceOp.SUM: jax.lax.psum,
        ReduceOp.AVG: jax.lax.psum,
        ReduceOp.MAX: jax.lax.pmax,
        ReduceOp.MIN: jax.lax.pmin,
    }[op]

    def block_fn(*blocks):
        outs = tuple(red(b, "ft") for b in blocks)
        if op == ReduceOp.AVG:
            outs = tuple((o / world).astype(o.dtype) for o in outs)
        return outs

    fn = jax.jit(
        jax.shard_map(block_fn, mesh=mesh, in_specs=specs, out_specs=specs)
    )
    with _PSUM_CACHE_LOCK:
        _PSUM_CACHE[key] = fn
    return fn


class CollectivesDevice(Collectives):
    """XLA-collective data plane for replica groups sharing one JAX runtime.

    Ops take and return ``jax.Array``s (``device_arrays = True``); numpy
    inputs are accepted and placed on the default device. All groups must
    issue the same ops in the same order (SPMD), as with every backend.
    """

    device_arrays = True

    def __init__(self, timeout: timedelta = timedelta(seconds=60)) -> None:
        self._timeout = timeout
        self._rank = -1
        self._world = 0
        self._epoch: Optional[_Epoch] = None
        self._op_seq = 0

    # -- lifecycle --

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        self._leave()
        key = _epoch_key(store_addr)
        with _REGISTRY_LOCK:
            ep = _REGISTRY.get(key)
            if ep is None:
                ep = _Epoch(key, world_size)
                _REGISTRY[key] = ep
        with ep.lock:
            if ep.dead is not None:
                raise RuntimeError(f"epoch {key} already failed: {ep.dead}")
            if ep.world != world_size:
                raise RuntimeError(
                    f"epoch {key}: world_size mismatch "
                    f"({world_size} vs {ep.world})"
                )
            ep.joined.add(rank)
        self._rank = rank
        self._world = world_size
        self._epoch = ep
        self._op_seq = 0
        # rendezvous barrier: surface missing members at configure() time,
        # like the TCP backend's eager mesh dial
        import time

        deadline = time.monotonic() + self._timeout.total_seconds()
        while True:
            with ep.lock:
                if ep.dead is not None:
                    raise RuntimeError(f"epoch {key} failed: {ep.dead}")
                missing = set(range(world_size)) - ep.joined
            if not missing:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(f"groups never joined epoch: {sorted(missing)}")
            time.sleep(0.005)

    def _leave(self) -> None:
        ep, self._epoch = self._epoch, None
        if ep is None:
            return
        exc = RuntimeError("collectives reconfigured before op completed")
        with ep.lock:
            ep.left.add(self._rank)
            # a departing member strands every in-flight op of the epoch —
            # detach the waiters now (the socket-shutdown analogue)
            doomed = ep.fail_pending(exc)
            # delete once every member that ever joined has left — members
            # that never joined (peer crashed before configure) must not
            # pin the epoch in the registry forever
            all_gone = ep.left >= ep.joined
        # resolve outside the lock: continuations run inline on this
        # thread and may re-enter the collectives
        for fut in doomed:
            fut.set_exception(exc)
        if all_gone:
            with _REGISTRY_LOCK:
                if _REGISTRY.get(ep.key) is ep:
                    del _REGISTRY[ep.key]

    def shutdown(self) -> None:
        self._leave()

    def size(self) -> int:
        return self._world

    def rank(self) -> int:
        return self._rank

    def plane_info(self) -> str:
        """Dashboard label: in-process device mesh ('ft' psum over ICI)."""
        return "device"

    def wire_codec(self) -> str:
        """The ICI psum moves exact device bytes — no wire codec applies,
        so error feedback is a no-op on this plane (docs/wire_plane.md)."""
        return "f32"

    # -- rendezvous plumbing --

    def _next_tag(self) -> int:
        self._op_seq += 1
        return self._op_seq

    def _rendezvous(self, kind: str, payload: Any, meta: Tuple = ()) -> Work:
        """Deposit this group's input for the next SPMD op slot; the last
        group to arrive computes and resolves everyone's future."""
        from torchft_tpu import telemetry
        from torchft_tpu.faultinject.core import fault_point

        fault_point(
            "collective.issue", match=f"device.{kind}", rank=self._rank
        )
        ep = self._epoch
        assert ep is not None, "configure() must be called first"
        if kind != "allreduce":  # allreduce accounts bytes+latency itself
            telemetry.COLLECTIVE_OPS.labels(op=kind, plane="device").inc()
        tag = self._next_tag()
        nbytes = 0
        try:
            leaves = payload if isinstance(payload, list) else [payload]
            nbytes = sum(int(getattr(a, "nbytes", 0) or 0) for a in leaves)
        except TypeError:
            pass
        fid = telemetry.FLIGHT.record_issue(
            kind, "device", nbytes, tag=tag, rank=self._rank
        )
        fut: Future = Future()
        run_op: Optional[_Op] = None
        dead: Optional[Exception] = None
        desync: Optional[RuntimeError] = None
        doomed: List[Future] = []
        with ep.lock:
            if ep.dead is not None:
                dead = ep.dead
            else:
                op = ep.ops.get(tag)
                if op is None:
                    op = _Op(kind, ep.world, meta)
                    ep.ops[tag] = op
                if op.kind != kind or op.meta != meta:
                    desync = RuntimeError(
                        f"collective desync: op {tag} is {op.kind}{op.meta}, "
                        f"this group issued {kind}{meta}"
                    )
                    # a desynced epoch can never make progress — fail
                    # everyone instead of stranding the other groups'
                    # waiters (futures resolved below, outside the lock)
                    doomed = ep.fail_pending(desync)
                else:
                    op.inputs[self._rank] = payload
                    op.futures[self._rank] = fut
                    if len(op.inputs) == op.world:
                        del ep.ops[tag]
                        run_op = op
        if dead is not None:
            fut.set_exception(dead)
            telemetry.FLIGHT.record_complete(fid, error=dead)
            return Work(future_timeout(fut, self._timeout))
        if desync is not None:
            for f in doomed:
                f.set_exception(desync)
            telemetry.FLIGHT.record_complete(fid, error=desync)
            raise desync
        if run_op is not None:
            self._compute(run_op)
        out = future_timeout(fut, self._timeout)

        def complete(f: Future) -> Any:
            telemetry.FLIGHT.record_complete(fid, error=f.exception())
            value = f.value()  # re-raises the op's failure, if any
            # completion-side injection (parity with the host plane's
            # site in CollectivesTcp._submit): `corrupt` silently
            # perturbs THIS group's finished output — the divergence-
            # sentinel adversary on the device plane
            inj = fault_point(
                "collective.complete", match=f"device.{kind}",
                rank=self._rank, wire=True,
            )
            if inj is not None:
                if inj.action == "corrupt":
                    value = _corrupt_device_result(value, inj.frac)
                elif inj.action in ("drop", "torn"):
                    # no wire semantics here: degrade to error — never a
                    # silent no-op (delay/kill already applied inline)
                    raise inj.make_exception()
            return value

        return Work(out.then(complete))

    def _compute(self, op: _Op) -> None:
        try:
            results = _COMPUTE[op.kind](op.inputs, op.meta)
        except BaseException as e:  # noqa: BLE001 — propagate via futures
            for fut in op.futures.values():
                fut.set_exception(e)
            return
        for rank, fut in op.futures.items():
            fut.set_result(results[rank])

    # -- collectives --

    def allreduce(self, arrays: List[Any], op: ReduceOp = ReduceOp.SUM) -> Work:
        import time

        from torchft_tpu import telemetry

        arrays = [_as_device(a) for a in arrays]
        nbytes = sum(int(a.nbytes) for a in arrays)
        if self._world == 1:
            # sum/avg/max/min of one input is itself; no timer registration.
            # Count the op + bytes but record NO latency observation — a
            # hard-coded 0.0 for the no-op path would drown the histogram's
            # real cross-group latencies
            telemetry.COLLECTIVE_OPS.labels(op="allreduce", plane="device").inc()
            telemetry.ALLREDUCE_BYTES.labels(plane="device").inc(nbytes)
            return Work(Future.completed(arrays))
        telemetry.COLLECTIVE_OPS.labels(op="allreduce", plane="device").inc()
        t0 = time.perf_counter()
        work = self._rendezvous("allreduce", arrays, (op,))

        def observe(f: Future) -> None:
            # dispatch latency of the cross-group rendezvous + psum launch
            # (device work is async; completion is fenced by the caller)
            if f.exception() is None:
                telemetry.record_collective(
                    "allreduce", nbytes, time.perf_counter() - t0, "device",
                    count_op=False,
                )

        work.get_future().then(observe)
        return work

    def allgather(self, arr: Any) -> Work:
        return self._rendezvous("allgather", _as_device(arr))

    def broadcast(self, arr: Any, root: int = 0) -> Work:
        return self._rendezvous("broadcast", _as_device(arr), (root,))

    def reduce_scatter(self, arrays: List[Any], op: ReduceOp = ReduceOp.SUM) -> Work:
        if len(arrays) != self._world:
            raise ValueError(
                f"reduce_scatter needs {self._world} inputs, got {len(arrays)}"
            )
        return self._rendezvous("reduce_scatter", [_as_device(a) for a in arrays], (op,))

    def alltoall(self, arrays: List[Any]) -> Work:
        if len(arrays) != self._world:
            raise ValueError(f"alltoall needs {self._world} inputs, got {len(arrays)}")
        return self._rendezvous("alltoall", [_as_device(a) for a in arrays])

    def barrier(self) -> Work:
        if self._world == 1:
            return Work.completed(None)
        return self._rendezvous("barrier", None)

    def send(self, arr: Any, dst: int, tag: int = 0) -> Work:
        ep = self._epoch
        assert ep is not None, "configure() must be called first"
        key = (self._rank, dst, tag)
        arr = _as_device(arr)
        matched: Optional[Future] = None
        with ep.lock:
            if ep.dead is not None:
                dead = ep.dead
            else:
                dead = None
                waiters = ep.recvs.get(key)
                if waiters:
                    matched, _target = waiters.popleft()
                else:
                    ep.sends.setdefault(key, deque()).append(arr)
        if dead is not None:
            return Work(Future.failed(dead))
        if matched is not None:
            # resolve outside the lock: the receiver's `place` continuation
            # (and any user `then`) runs inline on this thread
            matched.set_result(arr)
        return Work.completed(None)  # buffered send, like TCP's sendall

    def recv(self, arr: Any, src: int, tag: int = 0) -> Work:
        ep = self._epoch
        assert ep is not None, "configure() must be called first"
        key = (src, self._rank, tag)
        fut: Future = Future()
        got = _NOTHING
        with ep.lock:
            if ep.dead is not None:
                dead = ep.dead
            else:
                dead = None
                buffered = ep.sends.get(key)
                if buffered:
                    got = buffered.popleft()
                else:
                    ep.recvs.setdefault(key, deque()).append((fut, arr))
        if dead is not None:
            fut.set_exception(dead)
            return Work(future_timeout(fut, self._timeout))
        if got is not _NOTHING:
            fut.set_result(got)  # outside the lock — continuations inline

        def place(f: Future) -> Any:
            # received payload keeps its device placement; in-place numpy
            # semantics only apply when the caller handed us numpy
            got = f.value()
            if isinstance(arr, np.ndarray):
                arr[...] = np.asarray(got).reshape(arr.shape)
                return arr
            return got

        return Work(future_timeout(fut, self._timeout).then(place))


def _as_device(arr: Any):
    import jax
    import jax.numpy as jnp

    if isinstance(arr, jax.Array):
        return arr
    return jnp.asarray(arr)


def _corrupt_device_result(value: Any, frac: float) -> Any:
    """``corrupt(frac)`` injection semantics on the device plane: +1 on
    the leading ``frac`` of the first output's elements, THIS group only
    (see collectives._corrupt_buffers — same adversary, immutable-array
    edition: the perturbed copy replaces the result)."""
    import jax.numpy as jnp

    arrays = value if isinstance(value, (list, tuple)) else [value]
    out = list(arrays)
    for i, arr in enumerate(out):
        size = int(getattr(arr, "size", 0) or 0)
        if not size:
            continue
        host = np.array(arr)
        n = max(1, int(size * frac))
        host.reshape(-1)[:n] += host.dtype.type(1)
        out[i] = jnp.asarray(host)
        break
    if isinstance(value, (list, tuple)):
        return type(value)(out)
    return out[0]


# ---------------------------------------------------------------------------
# op implementations (run once per rendezvous, on the last-arriving thread;
# data never leaves the devices — transfers are D2D)
# ---------------------------------------------------------------------------


def _stack_over_ft(per_rank: Dict[int, Any], idx: int, big_mesh=None):
    """Build (global_array, big_mesh, global_spec, per-rank shardings) for
    the idx-th array of each rank, stacked on a leading 'ft' mesh axis.
    Pass a previously-built ``big_mesh`` to reuse it across leaves (every
    leaf of one op spans the same devices)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    _congruent(per_rank, idx)
    ranks = sorted(per_rank)
    arrs = [per_rank[r][idx] for r in ranks]
    _devs0, names0, spec0 = _devices_and_spec(arrs[0])
    if big_mesh is None:
        big_devs = np.stack([_devices_and_spec(a)[0] for a in arrs])
        big_mesh = Mesh(big_devs, ("ft", *names0))
    elif big_mesh.axis_names != ("ft", *names0):
        raise RuntimeError(
            "collective desync: arrays within one allreduce span "
            "different meshes"
        )
    gspec = PartitionSpec("ft", *spec0)
    shards = []
    for a in arrs:
        for s in a.addressable_shards:
            shards.append(jnp.expand_dims(s.data, 0))
    garr = jax.make_array_from_single_device_arrays(
        (len(ranks), *arrs[0].shape), NamedSharding(big_mesh, gspec), shards
    )
    return garr, big_mesh, gspec, [a.sharding for a in arrs]


def _unstack_over_ft(out, shardings, per_rank_devices) -> List[Any]:
    """Split a reduced global array back into per-rank arrays on their
    original devices/shardings (a squeeze per shard — metadata-cheap)."""
    import jax
    import jax.numpy as jnp

    by_dev = {s.device: s.data for s in out.addressable_shards}
    results = []
    for sharding, devices in zip(shardings, per_rank_devices):
        datas = [jnp.squeeze(by_dev[d], axis=0) for d in devices]
        results.append(
            jax.make_array_from_single_device_arrays(
                out.shape[1:], sharding, datas
            )
        )
    return results


def _compute_allreduce(inputs: Dict[int, List[Any]], meta: Tuple) -> Dict[int, Any]:
    (op,) = meta
    ranks = sorted(inputs)
    world = len(ranks)
    n_arrays = {len(v) for v in inputs.values()}
    if len(n_arrays) != 1:
        raise RuntimeError(f"collective desync: array counts differ: {n_arrays}")
    (n,) = n_arrays

    garrs, specs, all_shardings, all_devices = [], [], [], []
    big_mesh = None
    for i in range(n):
        g, big_mesh, spec, shardings = _stack_over_ft(inputs, i, big_mesh)
        garrs.append(g)
        specs.append(spec)
        all_shardings.append(shardings)
        all_devices.append(
            [[s.device for s in inputs[r][i].addressable_shards] for r in ranks]
        )

    fn = _reduction_fn(big_mesh, tuple(specs), op, world)
    outs = fn(*garrs)
    per_rank: Dict[int, List[Any]] = {r: [] for r in ranks}
    for i, out in enumerate(outs):
        rank_arrays = _unstack_over_ft(out, all_shardings[i], all_devices[i])
        for r, a in zip(ranks, rank_arrays):
            per_rank[r].append(a)
    return per_rank


def _compute_allgather(inputs: Dict[int, Any], meta: Tuple) -> Dict[int, Any]:
    import jax

    ranks = sorted(inputs)
    return {
        r: [
            jax.device_put(inputs[j], inputs[r].sharding)
            for j in ranks
        ]
        for r in ranks
    }


def _compute_broadcast(inputs: Dict[int, Any], meta: Tuple) -> Dict[int, Any]:
    import jax

    (root,) = meta
    src = inputs[root]
    return {
        r: (src if r == root else jax.device_put(src, inputs[r].sharding))
        for r in sorted(inputs)
    }


def _compute_reduce_scatter(
    inputs: Dict[int, List[Any]], meta: Tuple
) -> Dict[int, Any]:
    import jax
    import jax.numpy as jnp

    (op,) = meta
    ranks = sorted(inputs)
    world = len(ranks)
    out: Dict[int, Any] = {}
    for r in ranks:
        target_sharding = inputs[r][r].sharding
        parts = [jax.device_put(inputs[j][r], target_sharding) for j in ranks]
        if op in (ReduceOp.SUM, ReduceOp.AVG):
            acc = parts[0]
            for p in parts[1:]:
                acc = acc + p
            if op == ReduceOp.AVG:
                acc = (acc / world).astype(acc.dtype)
        elif op == ReduceOp.MAX:
            acc = parts[0]
            for p in parts[1:]:
                acc = jnp.maximum(acc, p)
        else:
            acc = parts[0]
            for p in parts[1:]:
                acc = jnp.minimum(acc, p)
        out[r] = acc
    return out


def _compute_alltoall(inputs: Dict[int, List[Any]], meta: Tuple) -> Dict[int, Any]:
    import jax

    ranks = sorted(inputs)
    return {
        r: [
            jax.device_put(inputs[j][r], inputs[r][r].sharding)
            for j in ranks
        ]
        for r in ranks
    }


def _compute_barrier(inputs: Dict[int, Any], meta: Tuple) -> Dict[int, Any]:
    return {r: None for r in inputs}


_COMPUTE: Dict[str, Callable[[Dict[int, Any], Tuple], Dict[int, Any]]] = {
    "allreduce": _compute_allreduce,
    "allgather": _compute_allgather,
    "broadcast": _compute_broadcast,
    "reduce_scatter": _compute_reduce_scatter,
    "alltoall": _compute_alltoall,
    "barrier": _compute_barrier,
}
