"""Low-level coordination API: Lighthouse / Manager servers and clients.

Public surface mirrors the reference's pyo3 module ``torchft._torchft``
(type stubs at /root/reference/torchft/_torchft.pyi:1-61, re-exported by
torchft/coordination.py:17-23) — same classes, same methods, same timeout
semantics (CANCELLED / DEADLINE_EXCEEDED become ``TimeoutError``). The
servers themselves run in the C++ core (``native/coord.cc``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from datetime import timedelta
from typing import Any, Dict, List, Optional

from torchft_tpu import _native

__all__ = [
    "LighthouseServer",
    "ManagerServer",
    "ManagerClient",
    "LighthouseClient",
    "QuorumResult",
]


def _ms(t: timedelta) -> int:
    return max(1, int(t.total_seconds() * 1000))


@dataclass
class QuorumResult:
    """Per-rank quorum outcome (ManagerQuorumResponse analogue,
    proto/torchft.proto:79-93 / src/lib.rs:240-273)."""

    quorum_id: int = 0
    replica_rank: int = 0
    replica_world_size: int = 1
    recover_src_manager_address: str = ""
    recover_src_rank: Optional[int] = None
    recover_dst_ranks: List[int] = field(default_factory=list)
    store_address: str = ""
    max_step: int = 0
    max_rank: Optional[int] = None
    max_world_size: int = 1
    heal: bool = False
    # any local rank of this group heals → the group contributes zeros on
    # every rank plane (participation must be plane-consistent)
    group_heal: bool = False
    # quorum members' replica_ids in replica_rank order — lets the data
    # plane map a failed peer's ring rank to a replica_id for evict reports
    participant_ids: List[str] = field(default_factory=list)
    # striped multi-source heal (docs/heal_plane.md): manager addresses of
    # the whole max-step cohort (single bootstrap source at max_step == 0)
    recover_src_addresses: List[str] = field(default_factory=list)
    # someone heals this round — every up-to-date member stages a
    # checkpoint so all of them can serve stripes
    heal_pending: bool = False
    # telemetry-delta ack (ISSUE 16): lighthouse's last-applied delta
    # version per encoder incarnation, {inc_hex: {"ver": int, "resync":
    # bool}}. The manager feeds it to its DeltaEncoder so steady-state
    # piggybacks stay O(changed fields); None when the lighthouse has
    # not acked anything yet (or telemetry is off)
    telemetry_ack: Optional[Dict[str, Any]] = None

    @staticmethod
    def _from_wire(d: Dict[str, Any]) -> "QuorumResult":
        return QuorumResult(
            quorum_id=d.get("quorum_id", 0),
            replica_rank=d.get("replica_rank", 0),
            replica_world_size=d.get("replica_world_size", 1),
            recover_src_manager_address=d.get("recover_src_manager_address", ""),
            recover_src_rank=d.get("recover_src_rank"),
            recover_dst_ranks=list(d.get("recover_dst_ranks", [])),
            store_address=d.get("store_address", ""),
            max_step=d.get("max_step", 0),
            max_rank=d.get("max_rank"),
            max_world_size=d.get("max_world_size", 1),
            heal=d.get("heal", False),
            group_heal=d.get("group_heal", d.get("heal", False)),
            participant_ids=[
                s if isinstance(s, str) else s.decode()
                for s in d.get("participant_ids", [])
            ],
            recover_src_addresses=[
                s if isinstance(s, str) else s.decode()
                for s in d.get("recover_src_addresses", [])
            ],
            heal_pending=d.get("heal_pending", False),
            telemetry_ack=d.get("tack") or None,
        )


class LighthouseServer:
    """Global quorum coordinator across replica groups.

    C++ server (native/coord.cc Lighthouse) re-implementing
    src/lighthouse.rs: heartbeat-based health, fast quorum, split-brain
    guard, shrink-only membership, join-timeout straggler wait, and an HTTP
    dashboard on the same port. Defaults match the Python binding defaults
    (src/lib.rs:339-341): join=100ms, tick=100ms, heartbeat timeout=5s.
    """

    def __init__(
        self,
        bind: str,
        min_replicas: int,
        join_timeout_ms: Optional[int] = None,
        quorum_tick_ms: Optional[int] = None,
        heartbeat_timeout_ms: Optional[int] = None,
        evict_probe_ms: Optional[int] = None,
    ) -> None:
        self._handle, self._address = _native.lighthouse_create(
            bind,
            min_replicas,
            join_timeout_ms if join_timeout_ms is not None else 100,
            quorum_tick_ms if quorum_tick_ms is not None else 100,
            heartbeat_timeout_ms if heartbeat_timeout_ms is not None else 5000,
            evict_probe_ms if evict_probe_ms is not None else 100,
        )

    def address(self) -> str:
        return self._address

    def shutdown(self) -> None:
        if self._handle:
            _native.lighthouse_shutdown(self._handle)
            self._handle = 0

    def __del__(self) -> None:
        try:
            self.shutdown()
        except Exception:
            pass


class ManagerServer:
    """Per-replica-group coordinator (src/manager.rs analogue): aggregates the
    group's local ranks, proxies quorum to the lighthouse, computes per-rank
    recovery assignments, and arbitrates the commit vote."""

    def __init__(
        self,
        replica_id: str,
        lighthouse_addr: str,
        hostname: str,
        bind: str,
        store_addr: str,
        world_size: int,
        heartbeat_interval: timedelta = timedelta(milliseconds=100),
        connect_timeout: timedelta = timedelta(seconds=60),
    ) -> None:
        self._handle, self._address = _native.manager_create(
            replica_id,
            lighthouse_addr,
            hostname,
            bind,
            store_addr,
            world_size,
            _ms(heartbeat_interval),
            _ms(connect_timeout),
        )

    def address(self) -> str:
        return self._address

    def shutdown(self) -> None:
        if self._handle:
            _native.manager_shutdown(self._handle)
            self._handle = 0

    def __del__(self) -> None:
        try:
            self.shutdown()
        except Exception:
            pass


class ManagerClient:
    """Client for a ManagerServer (src/lib.rs:115-238 analogue). Timeouts
    travel in-band and are enforced server-side (grpc-timeout parity)."""

    # divergence flag of the most recent should_commit reply (class-level
    # default so spec'd test doubles expose the attribute too)
    last_divergence = False

    def __init__(self, addr: str, connect_timeout: timedelta) -> None:
        self._client = _native.NativeClient(addr, _ms(connect_timeout))

    def _quorum(
        self,
        rank: int,
        step: int,
        checkpoint_metadata: str,
        shrink_only: bool,
        timeout: timedelta,
        commit_failures: int = 0,
        plane: str = "",
        telemetry_payload: Optional[Dict[str, Any]] = None,
    ) -> QuorumResult:
        """``commit_failures > 0`` requests a data-plane flush: the
        lighthouse bumps quorum_id even without membership change, forcing
        every group to re-rendezvous its collectives (extension beyond the
        reference, which needs a process restart for this). ``plane`` is
        this group's data-plane transport label, surfaced on the
        lighthouse dashboard/metrics. ``telemetry_payload`` piggybacks a
        compact per-replica telemetry summary (counters digest + recent
        span batch) on this existing RPC; the manager server forwards it
        to the lighthouse, which aggregates per replica for
        ``GET /cluster.json`` and the merged ``GET /trace`` timeline —
        zero extra control-plane round trips."""
        import time

        from torchft_tpu import telemetry

        req: Dict[str, Any] = {
            "rank": rank,
            "step": step,
            "checkpoint_metadata": checkpoint_metadata,
            "shrink_only": shrink_only,
            "commit_failures": commit_failures,
            "plane": plane,
            # trace context rides the RPC metadata. The C++ server does
            # not consume it today (it keeps no spans) — it is there for
            # wire-level debugging (a packet capture names the caller's
            # span) and for future server-side correlation; the live
            # cross-replica span linking is the checkpoint transport's
            # X-TFT-Trace header plus the shared trace_id coordinates.
            "trace": telemetry.TRACER.inject(),
        }
        if telemetry_payload:
            req["telemetry"] = telemetry_payload
        t0 = time.perf_counter()
        with telemetry.TRACER.span("quorum_rpc", rank=rank, step=step):
            resp = self._client.call("mgr.quorum", req, _ms(timeout))
        # the RPC long-polls until the lighthouse forms the quorum, so
        # this duration IS quorum-formation latency as this rank saw it
        telemetry.QUORUM_LATENCY.observe(time.perf_counter() - t0)
        telemetry.QUORUMS_TOTAL.inc()
        # reply-side injection: a delay here stretches the window between
        # the quorum landing and the plane reconfigure; an error makes
        # this rank treat a DELIVERED quorum as failed (retried next step)
        from torchft_tpu.faultinject.core import fault_point

        fault_point("quorum.reply", match="", rank=rank, step=step)
        return QuorumResult._from_wire(resp)

    def _checkpoint_metadata(self, rank: int, timeout: timedelta) -> str:
        resp = self._client.call(
            "mgr.checkpoint_metadata", {"rank": rank}, _ms(timeout)
        )
        return resp["checkpoint_metadata"]

    def should_commit(
        self,
        rank: int,
        step: int,
        should_commit: bool,
        timeout: timedelta,
        digest: Optional[str] = None,
        epoch: int = -1,
        fence: bool = False,
    ) -> bool:
        """``digest`` piggybacks the divergence sentinel's post-reduce
        state digest on this existing vote RPC (zero extra round trips);
        the manager server folds the group's rank digests and reports
        them to the lighthouse's (epoch, step) cohort compare. With
        ``fence`` the lighthouse arbitrates BEFORE the decision
        publishes — a digest mismatch vetoes the commit. The reply's
        divergence flag is latched on :attr:`last_divergence` (the
        Manager reads it after the call; a tuple return would break the
        bool contract every existing caller relies on)."""
        from torchft_tpu import telemetry
        from torchft_tpu.faultinject.core import fault_point

        # vote-RPC injection: `delay` is the synthetic commit-barrier RTT
        # (what the pipelined mode must hide), `error` a lost vote
        fault_point(
            "commit.vote", match="rpc", rank=rank, step=step,
        )
        req: Dict[str, Any] = {
            "rank": rank,
            "step": step,
            "should_commit": should_commit,
            "trace": telemetry.TRACER.inject(),
        }
        if digest is not None:
            req["digest"] = digest
            req["epoch"] = epoch
            req["fence"] = fence
        with telemetry.TRACER.span(
            "should_commit_rpc", rank=rank, step=step, vote=should_commit
        ):
            resp = self._client.call(
                "mgr.should_commit", req, _ms(timeout)
            )
        self.last_divergence = bool(resp.get("divergence", False))
        return resp["should_commit"]

    def kill(self, msg: str = "", timeout: timedelta = timedelta(seconds=10)) -> None:
        self._client.call("mgr.kill", {"msg": msg}, _ms(timeout))

    def evict(
        self, victim: str, timeout: timedelta = timedelta(seconds=5)
    ) -> bool:
        """Report ``victim`` (a replica_id seen dead on the data plane) for
        immediate eviction. The manager forwards to the lighthouse, which
        probes the victim's manager address before expiring its heartbeat —
        a false report about a live peer is a no-op. Returns whether the
        victim was actually evicted."""
        resp = self._client.call("mgr.evict", {"victim": victim}, _ms(timeout))
        return bool(resp.get("evicted", False))

    def close(self) -> None:
        self._client.close()


class LighthouseClient:
    """Direct lighthouse client — heartbeat + quorum (LighthouseService
    analogue). The Manager server normally does this for you; exposed for
    tests and tooling."""

    def __init__(self, addr: str, connect_timeout: timedelta) -> None:
        self._client = _native.NativeClient(addr, _ms(connect_timeout))

    def heartbeat(
        self,
        replica_id: str,
        timeout: timedelta = timedelta(seconds=5),
        telemetry_payload: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Heartbeat; ``telemetry_payload`` optionally piggybacks a
        per-replica telemetry summary for the lighthouse's cluster
        aggregation (same shape the Manager sends on quorum traffic)."""
        req: Dict[str, Any] = {"replica_id": replica_id}
        if telemetry_payload:
            req["telemetry"] = telemetry_payload
        self._client.call("lh.heartbeat", req, _ms(timeout))

    def quorum(
        self,
        requester: Dict[str, Any],
        timeout: timedelta,
    ) -> Dict[str, Any]:
        resp = self._client.call("lh.quorum", {"requester": requester}, _ms(timeout))
        return resp["quorum"]

    def evict(
        self,
        reporter: str,
        victim: str,
        timeout: timedelta = timedelta(seconds=5),
    ) -> bool:
        """Direct eviction report (see :meth:`ManagerClient.evict`)."""
        resp = self._client.call(
            "lh.evict", {"reporter": reporter, "victim": victim}, _ms(timeout)
        )
        return bool(resp.get("evicted", False))

    def digest(
        self,
        replica_id: str,
        epoch: int,
        step: int,
        digest: str,
        wait: bool = False,
        cohort: int = 0,
        timeout: timedelta = timedelta(seconds=10),
    ) -> Dict[str, Any]:
        """Report one replica's commit-time state digest to the
        lighthouse's (epoch, step) cohort compare (the divergence
        sentinel's RPC — normally the manager server does this from the
        vote barrier). ``wait`` long-polls until the full cohort
        reported (``cohort`` overrides the quorum size for tooling);
        returns ``{"match", "divergence", "reports"}``."""
        req: Dict[str, Any] = {
            "replica_id": replica_id,
            "epoch": epoch,
            "step": step,
            "digest": digest,
            "wait": wait,
        }
        if cohort:
            req["cohort"] = cohort
        return self._client.call("lh.digest", req, _ms(timeout))

    def close(self) -> None:
        self._client.close()
