"""Cross-language wire/protocol drift checker.

The C++ coordination core and the Python client speak a hand-rolled TLV
codec plus a small RPC vocabulary; nothing but convention keeps the two
sides in sync, and a silent mismatch is exactly how torn-frame bugs are
born (a tag decoded as a length, an opcode answered by nobody, an env
knob parsed on one side only). This module scrapes both sides with
regexes — no clang, no compile — and errors on any constant that exists
on one side only.

Checks (rule ids):

``wire-tag-drift``
    ``native/wire.h`` ``Value::Type`` enum vs ``utils/wire.py`` ``_I64``…
    constants: same names, same values, both directions.

``status-code-drift``
    ``native/wire.h`` ``Status`` enum vs ``_native/__init__.py`` status
    constants vs the ``.pyi`` stub.

``rpc-method-drift``
    Every ``"mgr.*" / "lh.*" / "store.*"`` method the Python side calls
    must have a native dispatch arm; every native dispatch arm must have a
    caller (Python or native-internal). A dead handler is drift waiting
    to diverge.

``fi-env-drift``
    The ``TORCHFT_FI_*`` family: knobs parsed by the native plane vs
    knobs documented in ``docs/fault_injection.md`` (exact match) and
    knobs referenced from Python (must be a subset of the parsed set —
    a scenario driving an unparsed knob silently no-ops).

``wire-env-drift``
    The ``TORCHFT_WIRE_*`` family (the wire-plane knob registry): knobs
    referenced anywhere in the Python tree vs the knob table in
    ``docs/wire_plane.md``, both directions — an undocumented knob is
    invisible to operators, a documented-but-unparsed knob silently
    no-ops in deploy configs.

``obs-env-drift``
    Same contract for the step-anatomy/SLO/straggler/forensics/
    divergence/time-series/regression knob families (``TORCHFT_SLO_*`` /
    ``TORCHFT_STRAGGLER_*`` / ``TORCHFT_BLACKBOX_*`` /
    ``TORCHFT_DIVERGENCE_*`` / ``TORCHFT_TSDB_*`` /
    ``TORCHFT_REGRESSION_*``) against the knob registry in
    ``docs/observability.md``.

``heal-env-drift``
    Same contract for the heal-plane knob family (``TORCHFT_HEAL_*``)
    against the knob registry in ``docs/heal_plane.md``, both
    directions.

``fault-site-drift``
    Native evidence-record site labels (``fi::write_evidence`` /
    ``fi::kill_self`` call sites) vs ``faultinject.core.NATIVE_SITES``:
    conftest's injection-evidence check and the scenario runner consume
    these labels, so an unlisted label breaks death attribution.

``stub-drift``
    Public names in ``_native/__init__.py`` vs ``_native/__init__.pyi``:
    the typed surface must cover the real one, both directions.

``makefile-hdrs-drift``
    Every ``native/*.h`` must appear in ``native/Makefile``'s ``HDRS``
    prerequisite list (and every HDRS entry must exist): a header
    missing from HDRS means its edits do not rebuild the ``.so`` — the
    stale-library class that shipped twice (tsdb.h, profiler.h).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Set

from torchft_tpu.analysis.base import Finding, repo_root

__all__ = ["run", "scrape_cpp_enum", "scrape_py_constants"]

_NATIVE_SOURCES = ("wire.h", "rpc.h", "coord.h", "dataplane.h",
                   "faultinject.h", "stripe.h", "blob.h", "rpc.cc",
                   "coord.cc", "dataplane.cc", "blob.cc", "capi.cc",
                   "lighthouse_main.cc")

_PY_RPC_SOURCES = (
    "torchft_tpu/coordination.py",
    "torchft_tpu/store.py",
)


def _read(root: str, rel: str) -> str:
    with open(os.path.join(root, rel), encoding="utf-8") as f:
        return f.read()


def scrape_cpp_enum(text: str, enum_name: str) -> Dict[str, int]:
    """``enum [class] <name> [: type] { A = 1, B = 2, ... }`` -> dict.
    Members without explicit ``= value`` continue the running count, like
    the compiler."""
    m = re.search(
        r"enum\s+(?:class\s+)?" + re.escape(enum_name)
        + r"\s*(?::\s*[\w:]+\s*)?\{([^}]*)\}",
        text, re.S,
    )
    if not m:
        return {}
    out: Dict[str, int] = {}
    nxt = 0
    for part in m.group(1).split(","):
        part = re.sub(r"//.*", "", part).strip()
        if not part:
            continue
        mm = re.match(r"(\w+)\s*(?:=\s*(\d+))?", part)
        if not mm:
            continue
        val = int(mm.group(2)) if mm.group(2) is not None else nxt
        out[mm.group(1)] = val
        nxt = val + 1
    return out


def scrape_py_constants(text: str, pattern: str) -> Dict[str, int]:
    """Module-level ``NAME = <int>`` constants matching ``pattern``."""
    out: Dict[str, int] = {}
    for m in re.finditer(
        r"^(" + pattern + r")\s*(?::\s*\w+)?\s*=\s*(\d+)\s*$", text, re.M
    ):
        out[m.group(1)] = int(m.group(2))
    return out


def _diff_maps(
    rule: str, path: str, a_name: str, a: Dict[str, int],
    b_name: str, b: Dict[str, int], normalize=lambda s: s,
) -> List[Finding]:
    finds: List[Finding] = []
    na = {normalize(k): v for k, v in a.items()}
    nb = {normalize(k): v for k, v in b.items()}
    for k in sorted(set(na) | set(nb)):
        if k not in na:
            finds.append(Finding(
                rule, path, 0, k,
                f"defined in {b_name} (={nb[k]}) but missing from {a_name}",
            ))
        elif k not in nb:
            finds.append(Finding(
                rule, path, 0, k,
                f"defined in {a_name} (={na[k]}) but missing from {b_name}",
            ))
        elif na[k] != nb[k]:
            finds.append(Finding(
                rule, path, 0, k,
                f"value mismatch: {a_name}={na[k]} vs {b_name}={nb[k]} — "
                "the two codecs would disagree byte-for-byte",
            ))
    return finds


# ---------------------------------------------------------------------------
# individual checks (each takes file texts so fixtures can drive them)
# ---------------------------------------------------------------------------


def check_wire_tags(wire_h: str, wire_py: str) -> List[Finding]:
    cpp = scrape_cpp_enum(wire_h, "Type")
    py = scrape_py_constants(wire_py, r"_[A-Z][A-Z0-9]*")
    return _diff_maps(
        "wire-tag-drift", "torchft_tpu/utils/wire.py",
        "native/wire.h Value::Type", cpp,
        "utils/wire.py", py,
        normalize=lambda s: s.lstrip("_").upper(),
    )


def check_status_codes(wire_h: str, native_init: str, pyi: str) -> List[Finding]:
    cpp = scrape_cpp_enum(wire_h, "Status")
    py = scrape_py_constants(native_init, r"[A-Z][A-Z_]*")
    finds = _diff_maps(
        "status-code-drift", "torchft_tpu/_native/__init__.py",
        "native/wire.h Status", cpp, "_native/__init__.py", py,
    )
    stub_names = set(re.findall(r"^([A-Z][A-Z_]*)\s*:\s*int\s*$", pyi, re.M))
    for k in sorted(set(cpp) - stub_names):
        finds.append(Finding(
            "status-code-drift", "torchft_tpu/_native/__init__.pyi", 0, k,
            "status code missing from the .pyi stub",
        ))
    for k in sorted(stub_names - set(cpp)):
        finds.append(Finding(
            "status-code-drift", "torchft_tpu/_native/__init__.pyi", 0, k,
            "stub declares a status code the native enum does not define",
        ))
    return finds


_METHOD_RE = re.compile(r'"((?:mgr|lh|store)\.[a-z_]+)"')


def check_rpc_methods(
    native_texts: Dict[str, str], py_texts: Dict[str, str]
) -> List[Finding]:
    handled: Set[str] = set()
    native_calls: Set[str] = set()
    for _name, text in native_texts.items():
        for m in re.finditer(r'method\s*==\s*"((?:mgr|lh|store)\.[a-z_]+)"', text):
            handled.add(m.group(1))
        for m in re.finditer(r'call\("((?:mgr|lh|store)\.[a-z_]+)"', text):
            native_calls.add(m.group(1))
    py_calls: Set[str] = set()
    for _name, text in py_texts.items():
        py_calls.update(_METHOD_RE.findall(text))
    finds: List[Finding] = []
    for m in sorted(py_calls - handled):
        finds.append(Finding(
            "rpc-method-drift", "native/coord.cc", 0, m,
            "Python calls this RPC method but no native dispatch arm "
            "handles it — the call can only ever return INVALID_ARGUMENT",
        ))
    for m in sorted(handled - py_calls - native_calls):
        finds.append(Finding(
            "rpc-method-drift", "native/coord.cc", 0, m,
            "native dispatch arm with no caller on either side — dead "
            "protocol surface drifts silently; remove it or justify in "
            "the baseline",
        ))
    return finds


_FI_RE = re.compile(r"TORCHFT_FI_[A-Z_0-9]+")


def check_fi_env(
    native_texts: Dict[str, str], doc_text: str, py_texts: Dict[str, str]
) -> List[Finding]:
    native: Set[str] = set()
    for text in native_texts.values():
        native.update(_FI_RE.findall(text))
    doc = set(_FI_RE.findall(doc_text))
    py: Set[str] = set()
    for text in py_texts.values():
        py.update(m for m in _FI_RE.findall(text) if m != "TORCHFT_FI_")
    finds: List[Finding] = []
    for k in sorted(native - doc):
        finds.append(Finding(
            "fi-env-drift", "docs/fault_injection.md", 0, k,
            "native fault-injection knob not documented in the knob table",
        ))
    for k in sorted(doc - native):
        finds.append(Finding(
            "fi-env-drift", "docs/fault_injection.md", 0, k,
            "documented knob that no native code parses — schedules "
            "driving it silently no-op",
        ))
    for k in sorted(py - native):
        finds.append(Finding(
            "fi-env-drift", "torchft_tpu/faultinject/runner.py", 0, k,
            "Python references a TORCHFT_FI_ knob the native plane does "
            "not parse — the scenario silently no-ops",
        ))
    return finds


_WIRE_RE = re.compile(r"TORCHFT_WIRE_[A-Z0-9_]+")


def check_wire_env(
    py_texts: Dict[str, str], wire_doc_text: str
) -> List[Finding]:
    py: Set[str] = set()
    for text in py_texts.values():
        py.update(_WIRE_RE.findall(text))
    doc = set(_WIRE_RE.findall(wire_doc_text))
    finds: List[Finding] = []
    for k in sorted(py - doc):
        finds.append(Finding(
            "wire-env-drift", "docs/wire_plane.md", 0, k,
            "wire-plane knob referenced in code but missing from the "
            "docs/wire_plane.md knob registry — invisible to operators",
        ))
    for k in sorted(doc - py):
        finds.append(Finding(
            "wire-env-drift", "docs/wire_plane.md", 0, k,
            "documented wire-plane knob that no code reads — a deploy "
            "config setting it silently no-ops",
        ))
    return finds


_OBS_RE = re.compile(
    r"TORCHFT_(?:SLO|STRAGGLER|BLACKBOX|DIVERGENCE|TSDB|REGRESSION|PROF"
    r"|DIAG|TELEMETRY)_[A-Z0-9_]+"
)


def check_obs_env(
    py_texts: Dict[str, str], obs_doc_text: str
) -> List[Finding]:
    """The TORCHFT_SLO_* / TORCHFT_STRAGGLER_* / TORCHFT_BLACKBOX_* /
    TORCHFT_DIVERGENCE_* / TORCHFT_TSDB_* / TORCHFT_REGRESSION_* /
    TORCHFT_PROF_* / TORCHFT_DIAG_* knob families vs the
    docs/observability.md knob registry, both directions (the
    wire-env-drift contract for the step-anatomy, forensics, divergence,
    history and diagnosis planes). The TSDB and PROF knobs are ALSO
    parsed natively (tsdb.h / profiler.h getenv) — the Python references
    the rule checks are the builder/client's shared constants, so both
    sides stay on one registry."""
    py: Set[str] = set()
    for text in py_texts.values():
        py.update(_OBS_RE.findall(text))
    doc = set(_OBS_RE.findall(obs_doc_text))
    finds: List[Finding] = []
    for k in sorted(py - doc):
        finds.append(Finding(
            "obs-env-drift", "docs/observability.md", 0, k,
            "SLO/straggler knob referenced in code but missing from the "
            "docs/observability.md knob registry — invisible to operators",
        ))
    for k in sorted(doc - py):
        finds.append(Finding(
            "obs-env-drift", "docs/observability.md", 0, k,
            "documented SLO/straggler knob that no code reads — a deploy "
            "config setting it silently no-ops",
        ))
    return finds


_HEAL_RE = re.compile(r"TORCHFT_HEAL_[A-Z0-9_]+")


def check_heal_env(
    py_texts: Dict[str, str], heal_doc_text: str
) -> List[Finding]:
    """The TORCHFT_HEAL_* knob family vs the docs/heal_plane.md knob
    registry, both directions (the wire-env-drift contract for the
    striped/differential heal plane)."""
    py: Set[str] = set()
    for text in py_texts.values():
        py.update(_HEAL_RE.findall(text))
    doc = set(_HEAL_RE.findall(heal_doc_text))
    finds: List[Finding] = []
    for k in sorted(py - doc):
        finds.append(Finding(
            "heal-env-drift", "docs/heal_plane.md", 0, k,
            "heal-plane knob referenced in code but missing from the "
            "docs/heal_plane.md knob registry — invisible to operators",
        ))
    for k in sorted(doc - py):
        finds.append(Finding(
            "heal-env-drift", "docs/heal_plane.md", 0, k,
            "documented heal-plane knob that no code reads — a deploy "
            "config setting it silently no-ops",
        ))
    return finds


def check_fault_sites(
    native_texts: Dict[str, str], native_sites: tuple
) -> List[Finding]:
    used: Set[str] = set()
    for text in native_texts.values():
        for m in re.finditer(
            r'(?:write_evidence|kill_self)\("([a-z_.]+)"', text
        ):
            used.add(m.group(1))
    finds: List[Finding] = []
    for s in sorted(used - set(native_sites)):
        finds.append(Finding(
            "fault-site-drift", "torchft_tpu/faultinject/core.py", 0, s,
            "native evidence site label not listed in "
            "faultinject.core.NATIVE_SITES — death attribution "
            "(conftest/runner evidence checks) won't recognize it",
        ))
    for s in sorted(set(native_sites) - used):
        finds.append(Finding(
            "fault-site-drift", "torchft_tpu/faultinject/core.py", 0, s,
            "NATIVE_SITES lists a site no native code emits — stale "
            "catalog entry",
        ))
    return finds


_PY_PUBLIC_RE = re.compile(r"^(?:def|class)\s+([A-Za-z_][A-Za-z0-9_]*)", re.M)


def check_stub(native_init: str, pyi: str) -> List[Finding]:
    real = {
        n for n in _PY_PUBLIC_RE.findall(native_init) if not n.startswith("_")
    }
    stub = {
        n for n in _PY_PUBLIC_RE.findall(pyi) if not n.startswith("_")
    }
    finds: List[Finding] = []
    for n in sorted(real - stub):
        finds.append(Finding(
            "stub-drift", "torchft_tpu/_native/__init__.pyi", 0, n,
            "public binding missing from the .pyi stub — typed callers "
            "can't see it",
        ))
    for n in sorted(stub - real):
        finds.append(Finding(
            "stub-drift", "torchft_tpu/_native/__init__.pyi", 0, n,
            "stub declares a binding the loader does not define",
        ))
    return finds


def check_makefile_hdrs(
    makefile: str, header_names: List[str]
) -> List[Finding]:
    """``makefile-hdrs-drift``: every ``native/*.h`` must appear in the
    Makefile's ``HDRS`` variable — HDRS is the .so targets' prerequisite
    list, so a header missing from it means editing that header does NOT
    rebuild the libraries and a stale ``.so`` ships silently. This exact
    omission happened twice (tsdb.h in PR 11, profiler.h caught again in
    PR 12); this rule makes it un-shippable. The reverse direction —
    an HDRS entry whose file is gone — is dead weight that masks the
    next real omission, so it errors too."""
    # HDRS := a.h b.h \
    #         c.h         (continuation lines folded first)
    folded = re.sub(r"\\\s*\n", " ", makefile)
    m = re.search(r"^HDRS\s*[:+?]?=\s*(.*)$", folded, re.MULTILINE)
    listed: Set[str] = set(m.group(1).split()) if m else set()
    finds: List[Finding] = []
    if m is None:
        finds.append(Finding(
            "makefile-hdrs-drift", "native/Makefile", 0, "HDRS",
            "no HDRS variable found — the .so targets have no header "
            "prerequisites at all; every header edit ships a stale .so",
        ))
        return finds
    for name in sorted(header_names):
        if name not in listed:
            finds.append(Finding(
                "makefile-hdrs-drift", "native/Makefile", 0, name,
                f"native/{name} is not in the Makefile's HDRS — editing "
                "it will NOT rebuild libtftcore*.so and a stale library "
                "ships silently (the tsdb.h/profiler.h incident class)",
            ))
    for name in sorted(listed):
        if name not in header_names:
            finds.append(Finding(
                "makefile-hdrs-drift", "native/Makefile", 0, name,
                f"HDRS lists {name} but native/{name} does not exist — "
                "dead prerequisites mask the next real omission",
            ))
    return finds


# ---------------------------------------------------------------------------
# repo gate
# ---------------------------------------------------------------------------


def run(root: Optional[str] = None) -> List[Finding]:
    root = root or repo_root()
    native_texts = {
        name: _read(root, os.path.join("native", name))
        for name in _NATIVE_SOURCES
        if os.path.exists(os.path.join(root, "native", name))
    }
    wire_h = native_texts.get("wire.h", "")
    wire_py = _read(root, "torchft_tpu/utils/wire.py")
    native_init = _read(root, "torchft_tpu/_native/__init__.py")
    pyi = _read(root, "torchft_tpu/_native/__init__.pyi")
    doc = _read(root, "docs/fault_injection.md")
    wire_doc_path = os.path.join(root, "docs", "wire_plane.md")
    wire_doc = (
        _read(root, "docs/wire_plane.md")
        if os.path.exists(wire_doc_path)
        else ""
    )

    py_rpc = {rel: _read(root, rel) for rel in _PY_RPC_SOURCES}
    py_fi: Dict[str, str] = {}
    for base, _dirs, files in os.walk(os.path.join(root, "torchft_tpu")):
        if "__pycache__" in base:
            continue
        for fn in files:
            if fn.endswith(".py"):
                rel = os.path.relpath(os.path.join(base, fn), root)
                py_fi[rel] = _read(root, rel)

    from torchft_tpu.faultinject.core import NATIVE_SITES

    out: List[Finding] = []
    out += check_wire_tags(wire_h, wire_py)
    out += check_status_codes(wire_h, native_init, pyi)
    out += check_rpc_methods(native_texts, py_rpc)
    out += check_fi_env(native_texts, doc, py_fi)
    out += check_wire_env(py_fi, wire_doc)
    obs_doc_path = os.path.join(root, "docs", "observability.md")
    obs_doc = (
        _read(root, "docs/observability.md")
        if os.path.exists(obs_doc_path)
        else ""
    )
    out += check_obs_env(py_fi, obs_doc)
    heal_doc_path = os.path.join(root, "docs", "heal_plane.md")
    heal_doc = (
        _read(root, "docs/heal_plane.md")
        if os.path.exists(heal_doc_path)
        else ""
    )
    out += check_heal_env(py_fi, heal_doc)
    out += check_fault_sites(native_texts, NATIVE_SITES)
    out += check_stub(native_init, pyi)
    native_dir = os.path.join(root, "native")
    headers = sorted(
        fn for fn in os.listdir(native_dir) if fn.endswith(".h")
    ) if os.path.isdir(native_dir) else []
    out += check_makefile_hdrs(_read(root, "native/Makefile"), headers)
    return out
