"""Clang-free concurrency lint over the native C++ core (``native/*.{h,cc}``).

clang-tidy exits 3 on this container (g++ only), which left ~6.7k LoC of
lock-discipline-critical C++ with zero static checking — the exact gap the
PR 9 ``serve_one`` reply-under-mutex finding fell through. This module is
the PR 5 Python concurrency lint ported to a lexical C++ analyzer: no
compiler, no AST — a comment/string-stripped token scan with brace-context
tracking, which is enough for the four rules below because the codebase's
locking idiom is uniform (``std::lock_guard``/``std::unique_lock`` guards
named in-scope, mutexes declared as ``std::mutex`` members).

Rules (ids are the suppression-key prefix, like the Python lint):

``cpp-lock-order-cycle``
    A cycle in the global (cross-file) lock-order graph built from nested
    guard scopes and one level of call propagation: holding ``A`` while a
    statement (or a callee, resolved by unique short name across the
    native tree) acquires ``B`` adds the edge ``A -> B``. Lock identity
    is class-qualified (``Lighthouse::mu_`` is not ``RpcClient::mu_``);
    a mutex member name declared by several classes and acquired through
    an object expression collapses to the instance-agnostic ``*.name``
    like the Python lint.

``cpp-blocking-under-lock``
    A blocking syscall/helper (``send``/``recv``/``poll``/``connect``/
    ``accept``/``select``, the repo's ``send_all``/``recv_all``/
    ``write_all`` wire helpers, ``sleep_for``/``usleep``, thread
    ``.join()``, ``RpcClient::call``) — or a call to a same-tree function
    that blocks — while a guard is held. ``cv.wait`` on the held lock is
    exempt (it releases); documented-intentional cases (a dedicated
    per-socket send mutex) are baselined with a reason.

``cpp-cv-wait-no-loop``
    A ``condition_variable`` ``wait``/``wait_for``/``wait_until`` (or the
    repo's ``cv_wait_deadline``) **without** a predicate argument and not
    lexically inside a ``while``/``for``/``do`` loop — wakeups may be
    spurious.

``cpp-atomic-no-order-reason``
    A non-seq_cst atomic operation (any explicit ``memory_order_relaxed``
    / ``acquire`` / ``release`` / ``acq_rel`` / ``consume``, including
    ``atomic_thread_fence``) with no reason annotation. The annotation
    grammar (same shape as the Python lint's ``guarded-by``)::

        seq.store(q + 1, std::memory_order_relaxed);  // relaxed-ok: <why>
        // release-order: head publishes the slot written above
        head.store(h + 1, std::memory_order_release);

    A trailing comment or the contiguous comment block directly above the
    op counts; ``// relaxed-ok(fn): <why>`` (or ``release-order(fn):``)
    anywhere earlier in the same function annotates every remaining op in
    that function — the form the seqlock protocols use, where one
    paragraph explains a dozen ops. One finding per (function, order)
    keeps suppression keys stable across edits.

Run via ``python -m torchft_tpu.analysis`` (the single repo gate) or
directly: ``run()`` returns :class:`~torchft_tpu.analysis.base.Finding`
records under the same baseline contract as every other analyzer.
"""

from __future__ import annotations

import glob
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from torchft_tpu.analysis.base import Finding, repo_root

__all__ = ["NATIVE_GLOBS", "analyze_sources", "run"]

NATIVE_GLOBS = ("native/*.h", "native/*.cc")

# Blocking call names: syscalls + the repo's own wire helpers. `read`/
# `write` are deliberately excluded (too many innocent homonyms for a
# lexical pass); the *_all helpers cover the wire paths that matter.
_BLOCKING_FUNCS = {
    "send", "recv", "sendmsg", "recvmsg", "accept", "connect", "poll",
    "select", "epoll_wait", "usleep", "nanosleep", "send_all", "recv_all",
    "write_all", "read_full", "tcp_connect", "getaddrinfo", "sleep_for",
    "sleep_until",
}
# method names that block regardless of receiver type resolution
_BLOCKING_METHODS = {"join", "call", "sleep_for", "sleep_until"}

_WAIT_NAMES = {"wait", "wait_for", "wait_until"}

_GUARD_RE = re.compile(
    r"std::(?:lock_guard|unique_lock|scoped_lock)\s*(?:<[^>]*>)?\s+"
    r"(\w+)\s*\(([^;{]*)\)"
)
_MUTEX_DECL_RE = re.compile(
    r"(?:static\s+)?std::(?:recursive_)?mutex\s+(\w+)\s*;"
)
_CV_DECL_RE = re.compile(r"std::condition_variable(?:_any)?\s+(\w+)\s*;")
_ORDER_RE = re.compile(
    r"memory_order(?:::|_)(relaxed|acquire|release|acq_rel|consume)"
)
_ANNOT_RE = re.compile(r"//\s*(?:relaxed-ok|release-order):\s*\S")
_ANNOT_FN_RE = re.compile(r"//\s*(?:relaxed-ok|release-order)\(fn\):\s*\S")
_CALL_RE = re.compile(r"([A-Za-z_]\w*)\s*\(")
_LAMBDA_TAIL_RE = re.compile(
    r"\[[^\[\]]*\]\s*(?:\([^()]*\))?\s*"
    r"(?:mutable|noexcept|constexpr|->\s*[\w:<>,&*\s]+)*\s*$"
)
_CLASS_RE = re.compile(r"\b(?:class|struct)\s+(\w+)[^;{()]*$")
_NAMESPACE_RE = re.compile(r"\bnamespace\s+(\w*)\s*$")
_FUNC_NAME_RE = re.compile(r"([A-Za-z_][\w]*(?:::~?[A-Za-z_]\w*)*)\s*\(")
_CONTROL_KWS = {"if", "while", "for", "switch", "catch", "return",
                "sizeof", "new", "delete", "throw", "do", "else",
                "defined", "assert", "static_assert"}


def _strip(source: str) -> str:
    """Replace comments and string/char literals with spaces, preserving
    newlines (so positions map back to true line numbers)."""
    out: List[str] = []
    i, n = 0, len(source)
    mode = "code"  # code | line_comment | block_comment | str | chr
    while i < n:
        c = source[i]
        nxt = source[i + 1] if i + 1 < n else ""
        if mode == "code":
            if c == "/" and nxt == "/":
                mode = "line_comment"
                out.append("  ")
                i += 2
                continue
            if c == "/" and nxt == "*":
                mode = "block_comment"
                out.append("  ")
                i += 2
                continue
            if c == '"':
                mode = "str"
                out.append(" ")
                i += 1
                continue
            if c == "'":
                mode = "chr"
                out.append(" ")
                i += 1
                continue
            out.append(c)
        elif mode == "line_comment":
            if c == "\n":
                mode = "code"
                out.append("\n")
            else:
                out.append(" ")
        elif mode == "block_comment":
            if c == "*" and nxt == "/":
                mode = "code"
                out.append("  ")
                i += 2
                continue
            out.append("\n" if c == "\n" else " ")
        else:  # str / chr
            q = '"' if mode == "str" else "'"
            if c == "\\":
                out.append("  ")
                i += 2
                continue
            if c == q:
                mode = "code"
            out.append("\n" if c == "\n" else " ")
        i += 1
    return "".join(out)


def _top_level_args(argtext: str) -> List[str]:
    """Split a call's argument text on top-level commas."""
    args: List[str] = []
    depth = 0
    cur: List[str] = []
    for c in argtext:
        if c in "([{":
            depth += 1
        elif c in ")]}":
            depth = max(0, depth - 1)
        if c == "," and depth == 0:
            args.append("".join(cur).strip())
            cur = []
        else:
            cur.append(c)
    tail = "".join(cur).strip()
    if tail:
        args.append(tail)
    return args


def _balanced_args(text: str, open_paren: int) -> str:
    """Argument text of the call whose ``(`` sits at ``open_paren``."""
    depth = 0
    for j in range(open_paren, len(text)):
        if text[j] == "(":
            depth += 1
        elif text[j] == ")":
            depth -= 1
            if depth == 0:
                return text[open_paren + 1:j]
    return text[open_paren + 1:]


class _Scope:
    __slots__ = ("kind", "name", "held", "loop")

    def __init__(self, kind: str, name: str = "", loop: bool = False) -> None:
        self.kind = kind      # class | namespace | func | lambda | block
        self.name = name
        self.held: List[str] = []  # locks acquired IN this scope
        self.loop = loop           # block opened by while/for/do


class _Func:
    __slots__ = ("qual", "path", "start", "end", "cls", "acquires",
                 "blocks", "calls")

    def __init__(self, qual: str, path: str, start: int, cls: str) -> None:
        self.qual = qual
        self.path = path
        self.start = start
        self.end = start
        self.cls = cls                        # owning class ('' for free)
        self.acquires: List[Tuple[str, int]] = []
        self.blocks: Optional[str] = None     # first blocking label
        # (callee short name, line, locks held at the call)
        self.calls: List[Tuple[str, int, Tuple[str, ...]]] = []


class _Analyzer:
    """All native files analyzed together (cross-file propagation needs
    the global function/mutex index)."""

    def __init__(self) -> None:
        self.findings: List[Finding] = []
        self.funcs: Dict[str, List[_Func]] = {}      # short name -> defs
        self.mutex_owners: Dict[str, Set[str]] = {}  # name -> owner set
        self.cv_names: Set[str] = set()
        # lock-order edge -> first (path, line, holder qualname)
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    # ------------------------------------------------------------------
    # pass 0: declarations (mutexes + condition variables, with owners)
    # ------------------------------------------------------------------

    def scan_decls(self, path: str, code: str) -> None:
        stack: List[Tuple[str, str]] = []  # (kind, name) per '{'
        seg_start = 0
        for i, c in enumerate(code):
            if c not in ";{}":
                continue
            seg = code[seg_start:i]
            if c == "{":
                m = _CLASS_RE.search(seg)
                mn = _NAMESPACE_RE.search(seg)
                if m:
                    stack.append(("class", m.group(1)))
                elif mn:
                    stack.append(("namespace", mn.group(1)))
                else:
                    stack.append(("other", ""))
            elif c == "}":
                if stack:
                    stack.pop()
            else:  # ';' — a declaration statement
                owner = next(
                    (n for k, n in reversed(stack) if k == "class"), ""
                )
                dm = _MUTEX_DECL_RE.search(seg + ";")
                if dm:
                    self.mutex_owners.setdefault(dm.group(1), set()).add(
                        owner or f"<{os.path.basename(path)}>"
                    )
                dc = _CV_DECL_RE.search(seg + ";")
                if dc:
                    self.cv_names.add(dc.group(1))
            seg_start = i + 1

    # ------------------------------------------------------------------
    # lock identity
    # ------------------------------------------------------------------

    def lock_id(self, expr: str, cls: str) -> Optional[str]:
        """Resolve a guard's mutex expression to a stable lock id, or
        None when the expression doesn't name a declared mutex."""
        expr = expr.strip().lstrip("*&").strip()
        leaf = re.split(r"\.|->|::", expr)[-1].strip().strip("()& ")
        if not leaf or leaf not in self.mutex_owners:
            return None
        owners = self.mutex_owners[leaf]
        plain = re.fullmatch(r"\w+", expr) is not None
        if plain and cls and cls in owners:
            return f"{cls}::{leaf}"
        if len(owners) == 1:
            return f"{next(iter(owners))}::{leaf}"
        return f"*.{leaf}"

    # ------------------------------------------------------------------
    # pass 1: per-file walk
    # ------------------------------------------------------------------

    def analyze_file(self, path: str, raw: str, code: str) -> None:
        raw_lines = raw.splitlines()
        stack: List[_Scope] = []
        cur_func: Optional[_Func] = None
        func_depth = 0
        guard_locks: Dict[str, str] = {}  # guard var -> lock id
        line = 1
        seg_start = 0

        def enclosing_class() -> str:
            for s in reversed(stack):
                if s.kind == "class":
                    return s.name
            return ""

        def held_now() -> List[str]:
            """Locks visible at this point: everything acquired in scopes
            inside the current function/lambda frame."""
            held: List[str] = []
            for s in reversed(stack):
                held = s.held + held
                if s.kind in ("func", "lambda"):
                    break
            return held

        def in_loop() -> bool:
            for s in reversed(stack):
                if s.kind in ("func", "lambda"):
                    return False
                if s.loop:
                    return True
            return False

        def acquire(lid: str, at_line: int, scope: _Scope) -> None:
            assert cur_func is not None
            for h in held_now():
                if h != lid:
                    self.edges.setdefault(
                        (h, lid), (path, at_line, cur_func.qual)
                    )
            cur_func.acquires.append((lid, at_line))
            scope.held.append(lid)

        def release(lid: str) -> None:
            for s in reversed(stack):
                if lid in s.held:
                    s.held.remove(lid)
                    return
                if s.kind in ("func", "lambda"):
                    return

        def handle_stmt(seg: str, seg_line: int) -> None:
            if cur_func is None or not stack:
                return
            scope = stack[-1]
            # guard declarations
            for m in _GUARD_RE.finditer(seg):
                var, args = m.group(1), m.group(2)
                for a in _top_level_args(args):
                    if a in ("std::defer_lock", "std::try_to_lock",
                             "std::adopt_lock"):
                        continue
                    lid = self.lock_id(a, cur_func.cls)
                    if lid is not None:
                        acquire(lid, seg_line, scope)
                        guard_locks[var] = lid
                        break  # first resolvable arg is the mutex
            # manual lock()/unlock() on guard vars or mutexes
            for m in re.finditer(
                r"([\w.\->()]+?)\s*\.\s*(lock|unlock)\s*\(\s*\)", seg
            ):
                target, op = m.group(1), m.group(2)
                lid = guard_locks.get(target) or self.lock_id(
                    target, cur_func.cls
                )
                if lid is None:
                    continue
                if op == "lock":
                    acquire(lid, seg_line, scope)
                else:
                    release(lid)
            handle_calls(seg, seg_line)

        def handle_calls(seg: str, seg_line: int) -> None:
            assert cur_func is not None
            held = held_now()
            pos = 0
            while True:
                m = _CALL_RE.search(seg, pos)
                if m is None:
                    break
                name = m.group(1)
                start = m.start(1)
                pos = m.end()
                if name in _CONTROL_KWS or name in (
                    "lock_guard", "unique_lock", "scoped_lock",
                    "lock", "unlock",
                ):
                    continue
                prefix = seg[:start].rstrip()
                is_method = prefix.endswith(".") or prefix.endswith("->")
                recv = ""
                if is_method:
                    rm = re.search(r"([\w\].()\->]+)(?:\.|->)$", prefix)
                    recv = rm.group(1) if rm else ""
                args = _top_level_args(_balanced_args(seg, m.end() - 1))

                # cv waits: exempt from blocking (they release the lock)
                # but subject to the predicate-loop rule
                recv_leaf = re.split(r"\.|->", recv)[-1] if recv else ""
                wait_like = (
                    (is_method and name in _WAIT_NAMES
                     and recv_leaf in self.cv_names)
                    or name == "cv_wait_deadline"
                )
                if wait_like:
                    has_pred = (
                        (name in _WAIT_NAMES and len(args) >= 2)
                        or (name == "cv_wait_deadline" and len(args) >= 4)
                    )
                    if not has_pred and not in_loop():
                        self.findings.append(Finding(
                            "cpp-cv-wait-no-loop", path, seg_line,
                            f"{cur_func.qual}:{recv_leaf or name}",
                            "condition-variable wait without a predicate "
                            "and outside a while/for loop — wakeups may "
                            "be spurious",
                        ))
                    continue

                if name in _BLOCKING_FUNCS or (
                    is_method and name in _BLOCKING_METHODS
                ):
                    label = f"{recv + '.' if recv else ''}{name}"
                    if cur_func.blocks is None:
                        cur_func.blocks = label
                    if held:
                        self.findings.append(Finding(
                            "cpp-blocking-under-lock", path, seg_line,
                            f"{cur_func.qual}:{label}",
                            f"blocking call {label}() while holding "
                            f"{'+'.join(held)} — every thread contending "
                            "that lock waits out the slow path too",
                        ))
                    continue
                cur_func.calls.append((name, seg_line, tuple(held)))

        def classify_open(seg: str) -> _Scope:
            if cur_func is not None:
                if _LAMBDA_TAIL_RE.search(seg):
                    # lambda body: executes later, possibly on another
                    # thread — locks held at the definition site do not
                    # surround it (matches the Python lint's nested-def
                    # semantics)
                    return _Scope("lambda")
                loop = bool(re.search(r"\b(while|for)\s*\(", seg)) or \
                    seg.strip().endswith("do") or seg.strip() == "do"
                return _Scope("block", loop=loop)
            m = _CLASS_RE.search(seg)
            if m:
                return _Scope("class", m.group(1))
            mn = _NAMESPACE_RE.search(seg)
            if mn:
                return _Scope("namespace", mn.group(1))
            for fm in _FUNC_NAME_RE.finditer(seg):
                name = fm.group(1)
                if name.split("::")[-1] in _CONTROL_KWS:
                    continue
                return _Scope("func", name)
            return _Scope("block")

        i, n = 0, len(code)
        paren = 0           # paren depth within the current brace scope
        paren_stack: List[int] = []  # saved depth per enclosing '{'
        while i < n:
            c = code[i]
            if c == "\n":
                line += 1
                i += 1
                continue
            if c == "(":
                paren += 1
                i += 1
                continue
            if c == ")":
                paren = max(0, paren - 1)
                i += 1
                continue
            if c not in ";{}":
                i += 1
                continue
            if c == ";" and paren > 0:
                # a ';' inside a paren group (for(;;) headers) is not a
                # statement boundary
                i += 1
                continue
            seg = code[seg_start:i]
            seg_line = line - seg.count("\n")
            if c == ";":
                handle_stmt(seg, seg_line)
            elif c == "{":
                paren_stack.append(paren)
                paren = 0
                scope = classify_open(seg)
                if scope.kind == "func" and cur_func is None:
                    qual = scope.name
                    cls = enclosing_class()
                    if "::" in qual:
                        cls = qual.split("::")[-2]
                    elif cls:
                        # in-class definition (header style): qualify so
                        # findings read Class::method like .cc methods
                        qual = f"{cls}::{qual}"
                    f = _Func(qual, path, seg_line, cls)
                    self.funcs.setdefault(qual.split("::")[-1], []).append(f)
                    cur_func = f
                    func_depth = len(stack)
                    guard_locks = {}
                elif cur_func is not None:
                    # text before an inner block still executes in order
                    # (e.g. `if (client.call(...)) {` / `while (recv(...))`)
                    handle_stmt(seg, seg_line)
                stack.append(scope)
            else:  # '}'
                paren = paren_stack.pop() if paren_stack else 0
                if stack:
                    stack.pop()
                    if cur_func is not None and len(stack) == func_depth:
                        cur_func.end = line
                        cur_func = None
                        guard_locks = {}
            seg_start = i + 1
            i += 1

        self._atomic_rule(path, raw_lines)

    # ------------------------------------------------------------------
    # pass 2: atomics annotation rule (raw lines — comments matter here)
    # ------------------------------------------------------------------

    def _atomic_rule(self, path: str, raw_lines: List[str]) -> None:
        spans: List[Tuple[int, int, str]] = []
        for defs in self.funcs.values():
            for f in defs:
                if f.path == path:
                    spans.append((f.start, f.end, f.qual))
        spans.sort()

        def func_at(lineno: int) -> Tuple[int, int, str]:
            best = (0, 10 ** 9, "<file>")
            for s, e, q in spans:
                if s <= lineno <= e and (e - s) < (best[1] - best[0]):
                    best = (s, e, q)
            return best

        fn_marker: Dict[Tuple[str, int], int] = {}  # (qual, start) -> line
        for idx, text in enumerate(raw_lines, start=1):
            if _ANNOT_FN_RE.search(text):
                s, _e, q = func_at(idx)
                fn_marker[(q, s)] = min(fn_marker.get((q, s), idx), idx)

        missing: Dict[Tuple[str, str], Tuple[int, int]] = {}
        for idx, text in enumerate(raw_lines, start=1):
            orders = set(_ORDER_RE.findall(text))
            if not orders:
                continue
            if _ANNOT_RE.search(text) or _ANNOT_FN_RE.search(text):
                continue
            j = idx - 2  # contiguous comment block directly above
            annotated = False
            while j >= 0 and raw_lines[j].strip().startswith("//"):
                if _ANNOT_RE.search(raw_lines[j]) or _ANNOT_FN_RE.search(
                    raw_lines[j]
                ):
                    annotated = True
                    break
                j -= 1
            if annotated:
                continue
            s, _e, q = func_at(idx)
            if (q, s) in fn_marker and idx >= fn_marker[(q, s)]:
                continue
            for order in orders:
                first, count = missing.get((q, order), (idx, 0))
                missing[(q, order)] = (first, count + 1)
        for (q, order), (first, count) in sorted(missing.items()):
            self.findings.append(Finding(
                "cpp-atomic-no-order-reason", path, first,
                f"{q}:{order}",
                f"{count} {order}-ordered atomic op(s) in {q} with no "
                "'// relaxed-ok:'/'// release-order:' reason annotation "
                "(same line, the comment block above, or a '(fn):' scope "
                "marker earlier in the function)",
            ))

    # ------------------------------------------------------------------
    # pass 3: cross-file propagation + cycle detection
    # ------------------------------------------------------------------

    def propagate_and_report(self) -> None:
        blocking: Dict[str, str] = {}
        acquires: Dict[str, List[Tuple[str, int]]] = {}
        for short, defs in self.funcs.items():
            if len(defs) != 1:
                continue  # ambiguous short name — skip, conservative
            f = defs[0]
            if f.blocks:
                blocking[short] = f.blocks
            if f.acquires:
                acquires[short] = f.acquires
        for defs in self.funcs.values():
            for f in defs:
                for callee, cline, held in f.calls:
                    if not held or callee == f.qual.split("::")[-1]:
                        continue
                    if callee in blocking:
                        self.findings.append(Finding(
                            "cpp-blocking-under-lock", f.path, cline,
                            f"{f.qual}:{callee}()",
                            f"call to {callee}() (which blocks on "
                            f"{blocking[callee]}) while holding "
                            f"{'+'.join(held)}",
                        ))
                    for lid, _al in acquires.get(callee, ()):
                        for h in held:
                            if h != lid:
                                self.edges.setdefault(
                                    (h, lid), (f.path, cline, f.qual)
                                )
        self._cycle_rule()

    def _cycle_rule(self) -> None:
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
        color: Dict[str, int] = {}
        path_stack: List[str] = []
        cycles: List[List[str]] = []

        def dfs(node: str) -> None:
            color[node] = 1
            path_stack.append(node)
            for m in sorted(adj.get(node, ())):
                if color.get(m, 0) == 1 and m in path_stack:
                    cycles.append(path_stack[path_stack.index(m):] + [m])
                elif color.get(m, 0) == 0:
                    dfs(m)
            path_stack.pop()
            color[node] = 2

        for node in sorted(adj):
            if color.get(node, 0) == 0:
                dfs(node)
        seen: Set[frozenset] = set()
        for cyc in cycles:
            key = frozenset(cyc)
            if key in seen:
                continue
            seen.add(key)
            pairs = [p for p in zip(cyc, cyc[1:]) if p in self.edges]
            if not pairs:
                continue
            where = "; ".join(
                f"{a}->{b} at {self.edges[(a, b)][0]}:"
                f"{self.edges[(a, b)][1]} in {self.edges[(a, b)][2]}"
                for a, b in pairs
            )
            path0, line0, _q = self.edges[pairs[0]]
            self.findings.append(Finding(
                "cpp-lock-order-cycle", path0, line0, "->".join(cyc),
                f"lock-order inversion: {' -> '.join(cyc)} ({where}) — "
                "two threads taking these locks in opposing order "
                "deadlock",
            ))


def analyze_sources(sources: List[Tuple[str, str]]) -> List[Finding]:
    """Analyze a set of (repo-relative path, source text) C++ files as
    one tree (cross-file propagation included)."""
    an = _Analyzer()
    stripped = [(p, s, _strip(s)) for p, s in sources]
    for p, _raw, code in stripped:
        an.scan_decls(p, code)
    for p, raw, code in stripped:
        an.analyze_file(p, raw, code)
    an.propagate_and_report()
    seen: Set[Tuple] = set()
    out: List[Finding] = []
    for f in sorted(an.findings,
                    key=lambda f: (f.path, f.line, f.rule, f.symbol)):
        k = (f.rule, f.path, f.symbol)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def run(root: Optional[str] = None) -> List[Finding]:
    """Analyze the native tree (the repo gate)."""
    root = root or repo_root()
    sources: List[Tuple[str, str]] = []
    for pattern in NATIVE_GLOBS:
        for path in sorted(glob.glob(os.path.join(root, pattern))):
            rel = os.path.relpath(path, root)
            with open(path, encoding="utf-8") as f:
                sources.append((rel, f.read()))
    return analyze_sources(sources)
