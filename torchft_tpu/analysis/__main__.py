"""CLI: ``python -m torchft_tpu.analysis``.

Exit codes: 0 = clean (all findings baselined, no stale suppressions),
1 = active findings and/or stale baseline entries, 2 = analyzer crash.

``--json`` emits a machine-readable report; ``--update-baseline`` writes
every currently-active finding into the baseline (each entry still needs
a human to replace the placeholder reason before review)."""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from torchft_tpu.analysis import Baseline, DEFAULT_BASELINE, run_all
from torchft_tpu.analysis.base import Finding


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="torchft_tpu.analysis",
        description="project static-analysis gate (concurrency lint, "
        "wire drift, doc drift)",
    )
    ap.add_argument("--root", default=None, help="repo root (default: auto)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline/suppression file")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write active findings into the baseline with "
                    "placeholder reasons (then go justify them)")
    ap.add_argument("--no-cache", action="store_true",
                    help="bypass the incremental analysis cache "
                    "(.analysis_cache/) and re-scan everything")
    args = ap.parse_args(argv)

    try:
        cache = None
        if not args.no_cache:
            from torchft_tpu.analysis.cache import AnalysisCache

            cache = AnalysisCache(args.root)
        per_analyzer = run_all(args.root, cache=cache)
        baseline = Baseline.load(args.baseline)
    except Exception as e:  # noqa: BLE001 — analyzer crash is exit 2
        print(f"analysis failed: {type(e).__name__}: {e}", file=sys.stderr)
        return 2

    all_findings: List[Finding] = [
        f for finds in per_analyzer.values() for f in finds
    ]
    active, suppressed, stale = baseline.apply(all_findings)

    if args.update_baseline and active:
        seen = {e["key"] for e in baseline.suppressions}
        for f in active:
            if f.key not in seen:
                seen.add(f.key)
                baseline.suppressions.append({
                    "key": f.key,
                    "reason": "TODO: justify or fix",
                })
        baseline.save(args.baseline)
        print(f"baseline updated: +{len(active)} entries "
              f"({args.baseline}) — now justify each reason")
        return 1

    if args.as_json:
        print(json.dumps({
            "analyzers": {
                name: [f.to_dict() for f in finds]
                for name, finds in per_analyzer.items()
            },
            "active": [f.to_dict() for f in active],
            "suppressed": [f.to_dict() for f in suppressed],
            "stale_suppressions": stale,
            "ok": not active and not stale,
        }, indent=2))
    else:
        for name, finds in per_analyzer.items():
            n_active = sum(1 for f in finds if f in active)
            print(f"-- {name}: {len(finds)} finding(s), "
                  f"{n_active} active, "
                  f"{len(finds) - n_active} baselined")
        for f in active:
            print(f"ACTIVE   {f.render()}")
        for e in stale:
            print(f"STALE    baseline entry matches nothing: {e['key']} "
                  f"(reason was: {e['reason']}) — remove it")
        if not active and not stale:
            cached = (
                f" [cache: {len(cache.hits)} hit(s), "
                f"{len(cache.misses)} miss(es)]"
                if cache is not None else ""
            )
            print(f"clean: {len(suppressed)} baselined finding(s), "
                  f"0 active, 0 stale{cached}")

    return 1 if (active or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
