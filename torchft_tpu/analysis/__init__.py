"""torchft_tpu static-analysis suite — one gate for the invariants that
the test tier can't see.

Run it as ``python -m torchft_tpu.analysis`` (single exit code, human or
``--json`` output, checked-in baseline at ``analysis/baseline.json``).
Four analyzers:

* :mod:`~torchft_tpu.analysis.concurrency` — AST concurrency lint over
  the FT runtime modules (lock-order cycles, blocking/callback calls
  under locks, guarded-by annotations for cross-thread state,
  ``Condition.wait`` predicate loops, thread hygiene);
* :mod:`~torchft_tpu.analysis.wiredrift` — C++ ↔ Python protocol drift
  (wire tags, status codes, RPC opcodes, ``TORCHFT_FI_*`` knobs, fault
  site labels, ``.pyi`` stub coverage, Makefile HDRS coverage);
* :mod:`~torchft_tpu.analysis.docdrift` — the bidirectional doc/registry
  catalogs (metrics, events, fault sites);
* :mod:`~torchft_tpu.analysis.nativelint` — the clang-free lexical
  concurrency lint over ``native/*.{h,cc}`` (lock-order graph,
  blocking-syscall-under-lock, cv predicate loops, non-seq_cst atomic
  annotations).

The FT-protocol verification plane (executable spec + bounded model
checker + trace conformance) lives in
:mod:`~torchft_tpu.analysis.protocol` with its own CLI
(``python -m torchft_tpu.analysis.protocol``, premerge gate [5]).

See ``docs/static_analysis.md`` for the rule catalog and the baseline
workflow.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from torchft_tpu.analysis.base import (
    Baseline,
    DEFAULT_BASELINE,
    Finding,
    repo_root,
)

__all__ = [
    "Baseline",
    "DEFAULT_BASELINE",
    "Finding",
    "repo_root",
    "run_all",
]


def run_all(root: Optional[str] = None) -> Dict[str, List[Finding]]:
    """Run every analyzer; returns findings per analyzer (pre-baseline)."""
    from torchft_tpu.analysis import (
        concurrency,
        docdrift,
        nativelint,
        wiredrift,
    )

    return {
        "concurrency": concurrency.run(root),
        "wiredrift": wiredrift.run(root),
        "docdrift": docdrift.run(root),
        "nativelint": nativelint.run(root),
    }
