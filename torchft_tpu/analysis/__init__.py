"""torchft_tpu static-analysis suite — one gate for the invariants that
the test tier can't see.

Run it as ``python -m torchft_tpu.analysis`` (single exit code, human or
``--json`` output, checked-in baseline at ``analysis/baseline.json``).
Four analyzers:

* :mod:`~torchft_tpu.analysis.concurrency` — AST concurrency lint over
  the FT runtime modules (lock-order cycles, blocking/callback calls
  under locks, guarded-by annotations for cross-thread state,
  ``Condition.wait`` predicate loops, thread hygiene);
* :mod:`~torchft_tpu.analysis.wiredrift` — C++ ↔ Python protocol drift
  (wire tags, status codes, RPC opcodes, ``TORCHFT_FI_*`` knobs, fault
  site labels, ``.pyi`` stub coverage, Makefile HDRS coverage);
* :mod:`~torchft_tpu.analysis.docdrift` — the bidirectional doc/registry
  catalogs (metrics, events, fault sites, premerge gate ids);
* :mod:`~torchft_tpu.analysis.nativelint` — the clang-free lexical
  concurrency lint over ``native/*.{h,cc}`` (lock-order graph,
  blocking-syscall-under-lock, cv predicate loops, non-seq_cst atomic
  annotations).

The FT-protocol verification plane (executable spec + bounded model
checker + trace conformance) lives in
:mod:`~torchft_tpu.analysis.protocol` with its own CLI
(``python -m torchft_tpu.analysis.protocol``, premerge gate [6]).

See ``docs/static_analysis.md`` for the rule catalog and the baseline
workflow.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from torchft_tpu.analysis.base import (
    Baseline,
    DEFAULT_BASELINE,
    Finding,
    repo_root,
)

__all__ = [
    "Baseline",
    "DEFAULT_BASELINE",
    "Finding",
    "repo_root",
    "run_all",
]


def run_all(
    root: Optional[str] = None, cache: Optional[object] = None
) -> Dict[str, List[Finding]]:
    """Run every analyzer; returns findings per analyzer (pre-baseline).

    ``cache`` — an :class:`~torchft_tpu.analysis.cache.AnalysisCache`:
    analyzers whose input fingerprint matches replay their stored
    findings instead of re-scanning (the CLI passes one unless
    ``--no-cache``; programmatic callers default to uncached)."""
    from torchft_tpu.analysis import (
        concurrency,
        docdrift,
        nativelint,
        wiredrift,
    )

    runners = {
        "concurrency": concurrency.run,
        "wiredrift": wiredrift.run,
        "docdrift": docdrift.run,
        "nativelint": nativelint.run,
    }
    out: Dict[str, List[Finding]] = {}
    for name, runner in runners.items():
        cached = cache.get(name) if cache is not None else None
        if cached is not None:
            out[name] = cached
            continue
        out[name] = runner(root)
        if cache is not None:
            cache.put(name, out[name])
    return out
