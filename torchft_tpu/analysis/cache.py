"""Incremental analysis cache: memoize per-analyzer findings by input
content hash (ISSUE 20 satellite).

The analysis gate runs on every premerge invocation, and most of those
runs see an unchanged tree — re-walking the runtime modules, the native
sources and the doc catalogs to re-derive the identical findings is pure
waste. This cache keys each analyzer's result on a fingerprint of the
files that analyzer actually reads (plus the analyzer suite's own
sources, so editing a RULE invalidates exactly like editing a scanned
file), stores findings under ``.analysis_cache/`` at the repo root, and
replays them when the fingerprint matches.

Correctness is the whole game for a cache in front of a gate, so the
input sets are deliberately conservative — over-invalidation costs one
re-run; under-invalidation silently greenlights a regression:

* ``concurrency`` — its declared ``RUNTIME_MODULES``;
* ``wiredrift`` — every ``torchft_tpu/**/*.py`` (it walks the package
  for ``TORCHFT_*`` env uses), every ``native/*`` source + the Makefile,
  and every ``docs/*.md``;
* ``docdrift`` — every ``torchft_tpu/**/*.py`` (the metric registry and
  event catalog are built by importing the package) + ``docs/*.md`` +
  ``scripts/premerge.sh`` (the premerge-gate-drift rule parses it);
* ``nativelint`` — its declared ``NATIVE_GLOBS``.

Every set additionally includes ``torchft_tpu/analysis/*.py``. The
cache never touches exit-code semantics: it stores the PRE-baseline
findings, and the baseline is applied to them exactly as to a fresh run.
``--no-cache`` on the CLI bypasses it entirely.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
from typing import Dict, List, Optional

from torchft_tpu.analysis.base import Finding, repo_root

__all__ = ["ANALYZER_INPUTS", "AnalysisCache", "fingerprint"]

CACHE_DIRNAME = ".analysis_cache"

# the analyzer suite itself: a rule edit must invalidate every analyzer
_SUITE = ("torchft_tpu/analysis/*.py",)

ANALYZER_INPUTS: Dict[str, tuple] = {
    "concurrency": ("torchft_tpu/*.py", "torchft_tpu/telemetry/*.py",
                    "torchft_tpu/checkpointing/*.py",
                    "torchft_tpu/faultinject/*.py") + _SUITE,
    "wiredrift": ("torchft_tpu/**/*.py", "native/*", "docs/*.md") + _SUITE,
    "docdrift": ("torchft_tpu/**/*.py", "docs/*.md",
                 "scripts/premerge.sh") + _SUITE,
    "nativelint": ("native/*.h", "native/*.cc") + _SUITE,
}


def fingerprint(root: str, patterns: tuple) -> str:
    """Content hash over every file matching ``patterns`` under
    ``root``: (relative path, size, blake2 of bytes) per file, so both
    an edit and an add/remove change the digest."""
    h = hashlib.blake2b(digest_size=16)
    seen = set()
    for pattern in patterns:
        for path in sorted(
            glob.glob(os.path.join(root, pattern), recursive=True)
        ):
            if not os.path.isfile(path) or path in seen:
                continue
            if "__pycache__" in path:
                continue
            seen.add(path)
            rel = os.path.relpath(path, root)
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            h.update(rel.encode())
            h.update(str(len(data)).encode())
            h.update(hashlib.blake2b(data, digest_size=16).digest())
    return h.hexdigest()


class AnalysisCache:
    """Per-analyzer findings memo under ``<root>/.analysis_cache/``."""

    def __init__(self, root: Optional[str] = None) -> None:
        self.root = root or repo_root()
        self.dir = os.path.join(self.root, CACHE_DIRNAME)
        self.hits: List[str] = []
        self.misses: List[str] = []

    def _path(self, analyzer: str) -> str:
        return os.path.join(self.dir, f"{analyzer}.json")

    def get(self, analyzer: str) -> Optional[List[Finding]]:
        """Cached findings when the input fingerprint matches; else
        None. An analyzer without a declared input set never caches."""
        patterns = ANALYZER_INPUTS.get(analyzer)
        if patterns is None:
            return None
        try:
            with open(self._path(analyzer), encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        if doc.get("fingerprint") != fingerprint(self.root, patterns):
            return None
        try:
            finds = [
                Finding(e["rule"], e["path"], int(e["line"]),
                        e["symbol"], e["message"])
                for e in doc.get("findings", [])
            ]
        except (KeyError, TypeError, ValueError):
            return None
        self.hits.append(analyzer)
        return finds

    def put(self, analyzer: str, findings: List[Finding]) -> None:
        patterns = ANALYZER_INPUTS.get(analyzer)
        if patterns is None:
            return
        self.misses.append(analyzer)
        os.makedirs(self.dir, exist_ok=True)
        doc = {
            "fingerprint": fingerprint(self.root, patterns),
            "findings": [f.to_dict() for f in findings],
        }
        tmp = self._path(analyzer) + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, self._path(analyzer))
