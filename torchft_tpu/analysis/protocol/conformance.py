"""Trace-conformance checker: replay real FT evidence against the spec.

The event trail (``telemetry/events.py``) and the crash-durable black
boxes (PR 10) record every protocol lifecycle event a replica took. This
module replays those records against the spec's *event-level* transition
rules and flags any sequence the protocol cannot legally produce — which
turns every faultmatrix scenario (and every postmortem) into a
conformance proof: the scenarios already exercise the interleavings; now
an illegal transition in any of them fails the run.

One trail file / black box = one replica's history (workers write
per-process sinks), so the rules are per-replica; the cross-replica
invariants (unique commit lineage, quorum agreement) are the model
checker's jurisdiction — a trail can't see what it never observed.

Rules (rule ids appear in findings and docs/static_analysis.md):

``epoch-regression``
    ``quorum_ready.quorum_id`` decreased. The lighthouse's epoch counter
    only ever increments (``coord.cc``), and a replica observing a lower
    epoch after a higher one re-entered a dead epoch's plane.

``step-regression``
    A ``commit`` at a step at or below an already-committed step: a
    committed step is final — recommitting it forks the lineage.

``healing-commit``
    A ``commit`` while a heal is in flight (``heal_begin`` seen, no
    ``heal_end``/``heal_failed`` yet): the staged state must land (the
    commit barrier applies it) before the vote — a commit mid-transfer
    means the barrier voted on a half-healed replica.

``heal-failed-commit``
    A ``commit`` after ``heal_failed`` with no intervening
    ``quorum_ready``: a failed heal latches the error, and the step MUST
    abort at the barrier; only the next quorum may commit again.

``rollback-of-commit``
    A ``commit_rollback`` at a step that already committed: rollback is
    the veto path of a *speculative* vote — a committed step can never
    be rolled back (the PR 6 lineage consistency).

``diverged-commit``
    With the fence armed (``divergence_detected`` carries ``fence``),
    a ``commit`` at the step the sentinel latched on: the fence's
    whole contract is vetoing that commit (PR 10).

Sources may be *truncated* (black-box rings evict old records; trails
rotate), so the checker seeds its state leniently from the first record
it sees and never flags what truncation hides.
"""

from __future__ import annotations

import glob
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional

from torchft_tpu.telemetry.events import LIFECYCLE_EVENTS

__all__ = [
    "ConformanceFinding",
    "ConformanceReport",
    "check_records",
    "check_trail_file",
    "check_tree",
]


@dataclass
class ConformanceFinding:
    rule: str
    source: str       # trail path / box id
    index: int        # record index within the source
    event: str
    step: int
    epoch: int
    detail: str

    def render(self) -> str:
        return (
            f"{self.source}#{self.index}: [{self.rule}] {self.event} "
            f"(step={self.step}, epoch={self.epoch}): {self.detail}"
        )


@dataclass
class ConformanceReport:
    sources: int = 0
    records: int = 0
    lifecycle_records: int = 0
    findings: List[ConformanceFinding] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def render(self) -> str:
        lines = [
            f"conformance: {self.sources} source(s), "
            f"{self.lifecycle_records}/{self.records} lifecycle "
            f"record(s), {len(self.findings)} illegal transition(s)"
        ]
        lines += [f"  {f.render()}" for f in self.findings]
        return "\n".join(lines)


def _normalize(rec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Map a trail record ({"event": ...}) or a black-box mirror record
    ({"k": ...}) onto one shape; None for non-lifecycle records."""
    kind = rec.get("event", rec.get("k"))
    if kind not in LIFECYCLE_EVENTS:
        return None
    step = rec.get("step", rec.get("st", -1))
    try:
        step = int(step)
    except (TypeError, ValueError):
        step = -1
    epoch = rec.get("quorum_id", rec.get("ep", -1))
    try:
        epoch = int(epoch)
    except (TypeError, ValueError):
        epoch = -1
    return {"kind": kind, "step": step, "epoch": epoch, "rec": rec}


def check_records(
    records: Iterable[Dict[str, Any]], source: str = "<records>"
) -> ConformanceReport:
    """Replay one replica's records (trail or box order = emit order)
    against the event-level spec. Returns the report for this source."""
    rep = ConformanceReport(sources=1)
    max_epoch = -1          # highest quorum_ready epoch seen
    committed_steps: set = set()
    max_committed = -1
    heal_inflight: Optional[int] = None   # heal_begin's step
    heal_failed_latched = False
    fence_steps: set = set()  # steps where divergence latched w/ fence

    for idx, raw in enumerate(records):
        rep.records += 1
        norm = _normalize(raw)
        if norm is None:
            continue
        rep.lifecycle_records += 1
        kind, step, epoch = norm["kind"], norm["step"], norm["epoch"]

        def flag(rule: str, detail: str) -> None:
            rep.findings.append(ConformanceFinding(
                rule=rule, source=source, index=idx, event=kind,
                step=step, epoch=epoch, detail=detail,
            ))

        if kind == "quorum_start":
            # a quorum_start back at step 0 after real progress means a
            # NEW process appended to this sink (SIGKILL + respawn — the
            # faultmatrix's bread and butter): per-process trackers
            # reset, because the respawned replica legitimately re-heals
            # and re-commits steps its predecessor's discarded state
            # already saw. The epoch tracker survives: the lighthouse
            # epoch is global and must stay monotone across respawns.
            if step == 0 and (committed_steps or heal_inflight is not None
                              or heal_failed_latched):
                committed_steps = set()
                max_committed = -1
                heal_inflight = None
                heal_failed_latched = False
                fence_steps = set()
        elif kind == "quorum_ready":
            if epoch >= 0:
                if max_epoch >= 0 and epoch < max_epoch:
                    flag(
                        "epoch-regression",
                        f"quorum_id {epoch} after having observed "
                        f"{max_epoch} — the lighthouse epoch only "
                        "increments; this replica re-entered a dead "
                        "epoch's plane",
                    )
                max_epoch = max(max_epoch, epoch)
            heal_failed_latched = False
            # a new round re-averages the vetoed step from the committed
            # state and re-compares digests: the fence latch belonged to
            # the ABORTED attempt, and the retry's commit (identical
            # digests this time) is the legal outcome — observed live in
            # the corrupt_divergence fence leg (veto -> re-quorum ->
            # clean retry of the same step)
            fence_steps = set()
        elif kind == "heal_begin":
            heal_inflight = step
        elif kind in ("heal_end", "heal_failed"):
            heal_inflight = None
            if kind == "heal_failed":
                heal_failed_latched = True
        elif kind == "divergence_detected":
            if bool(raw.get("fence")):
                fence_steps.add(step)
        elif kind == "commit":
            if heal_inflight is not None:
                flag(
                    "healing-commit",
                    f"commit at step {step} while a heal begun at step "
                    f"{heal_inflight} is still in flight (no heal_end/"
                    "heal_failed) — the barrier voted on a half-healed "
                    "replica",
                )
            if heal_failed_latched:
                flag(
                    "heal-failed-commit",
                    f"commit at step {step} after heal_failed with no "
                    "intervening quorum_ready — a failed heal latches "
                    "the error and the step must abort",
                )
            if step >= 0:
                if step in committed_steps:
                    flag(
                        "step-regression",
                        f"step {step} committed twice — a committed "
                        "step is final; recommitting forks the lineage",
                    )
                elif max_committed >= 0 and step < max_committed:
                    flag(
                        "step-regression",
                        f"commit at step {step} after step "
                        f"{max_committed} already committed — committed "
                        "steps are monotone",
                    )
                if step in fence_steps:
                    flag(
                        "diverged-commit",
                        f"commit at step {step} where the divergence "
                        "sentinel latched with the fence armed — the "
                        "fence must veto this commit",
                    )
                committed_steps.add(step)
                max_committed = max(max_committed, step)
        elif kind == "commit_rollback":
            if step >= 0 and step in committed_steps:
                flag(
                    "rollback-of-commit",
                    f"commit_rollback at step {step}, which already "
                    "committed — only a speculative (un-committed) vote "
                    "can roll back (PR 6 lineage consistency)",
                )
    return rep


def _merge(into: ConformanceReport, one: ConformanceReport) -> None:
    into.sources += one.sources
    into.records += one.records
    into.lifecycle_records += one.lifecycle_records
    into.findings.extend(one.findings)


def check_trail_file(path: str) -> ConformanceReport:
    """Replay one JSONL trail file (torn tails skipped, like every other
    trail reader)."""
    records: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    except OSError:
        return ConformanceReport()
    return check_records(records, source=os.path.relpath(path))


def check_tree(root: str) -> ConformanceReport:
    """Replay every trail file and black box under ``root``: the
    ``postmortem --conformance`` / faultmatrix-runner entry point.

    Trails and boxes duplicate each other (the box mirrors every trail
    emit), but conformance is per-source order-sensitive, so both are
    replayed independently — a finding in either is real."""
    rep = ConformanceReport()
    for path in sorted(
        glob.glob(os.path.join(root, "**", "*.jsonl"), recursive=True)
    ):
        _merge(rep, check_trail_file(path))
    # black boxes: python rings carry the mirrored trail records
    try:
        from torchft_tpu.telemetry.blackbox import (
            read_blackbox,
            read_native_blackbox,
        )

        for path in sorted(
            glob.glob(os.path.join(root, "**", "*.bb"), recursive=True)
        ):
            try:
                if path.endswith("_native.bb"):
                    records, _meta = read_native_blackbox(path)
                else:
                    records, _meta = read_blackbox(path)
            except OSError:
                continue
            _merge(
                rep,
                check_records(records, source=os.path.relpath(path)),
            )
    except Exception:  # noqa: BLE001 — boxes are optional evidence
        pass
    return rep
