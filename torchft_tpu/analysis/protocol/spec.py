"""The FT protocol as an executable state machine.

The model is the per-step lifecycle exactly as the implementation ships
it (``manager.py`` / ``coord.cc`` semantics), abstracted to the decisions
that carry the correctness argument:

* **Replicas** (one per replica group — the Manager's unit of commit)
  hold a committed *lineage* — the ordered tuple of per-step commit
  tokens — plus an error-feedback *residual* version that must track the
  committed step (PR 6's rollback consistency). A replica is JOINING
  (pre-first-quorum), HEALTHY, HEALING (behind the round's max step,
  pulling state from a source), SPECULATING (pipelined commit: the
  optimizer update applied, the vote still in flight — PR 3), or DEAD.
* **The lighthouse** forms rounds: replicas join, a round *forms* when
  the join barrier is satisfied (every live replica — the quorum), and
  each formed round bumps the epoch (quorum_id). Members compute, vote,
  and **resolve independently**: the commit vote is arbitrated per
  replica group (``mgr.should_commit``), not fleet-wide — the only
  fleet-global wait is the divergence fence's cohort digest compare
  (PR 10), which blocks resolution until every member's digest (or
  abstention) is in and vetoes every member's commit on a mismatch.
* **Crashes** are a first-class action: while the crash budget lasts,
  any live replica can die *between any two transitions* — the
  model-checker scheduler interleaves the crash action at every
  transition point, which is the SIGKILL-anywhere semantics the
  faultinject runner implements dynamically. Dead replicas respawn from
  their last committed state (the checkpoint), rejoin behind, and heal.

``SpecConfig`` flags deliberately allow *broken* variants — the fences
off, the join barrier off (split brain), residual rollback off — so the
checker can demonstrate that each protection is load-bearing: turning
one off must produce an invariant violation (the seeded-fixture tests
assert exactly that), and the shipped configuration must produce none.

Invariants (``check_state`` / ``check_terminal``):

* ``I1 unique-commit``   — at most one committed lineage token per step,
  fleet-wide (a split brain or silently diverged commit violates this);
* ``I2 epoch-monotonic`` — a replica's observed quorum epoch never
  decreases;
* ``I3 healer-fence``    — a healer never observes (copies) speculative
  state: heal sources must not be SPECULATING (PR 3's fence);
* ``I4 residual-rollback`` — every replica's error-feedback residual
  version equals the step its state actually encodes (committed step, or
  the provisional step while SPECULATING) — a vetoed speculative update
  must roll the residual back with the weights (PR 6);
* ``I5 diverged-commit`` — a *detected* divergence (two member states
  disagreeing) never commits while the divergence fence is armed
  (PR 10);
* ``L  liveness``        — in every terminal state with at least
  ``min_replicas`` live replicas, some step committed.

**The HA layer (ISSUE 20).** With ``n_lighthouses >= 2`` the model grows
a Raft-replicated lighthouse tier: each lighthouse replica is FOLLOWER /
CANDIDATE / LEADER / DEAD with a term, a single persistent vote per
term, and a durable log of quorum *decisions* (one ``(term, rid)`` entry
appended by the leader that forms round ``rid``). Leaders commit a log
prefix once a majority of lighthouses replicated it; managers fail over
via the peer list (``form`` goes through *any* live leader — including a
stale minority-partitioned one, which is exactly the hazard the
majority-commit fence neutralizes). ``membership_deltas`` adds the
sublinear-control-traffic membership protocol: the lighthouse keeps a
versioned membership log, replicas apply deltas in order (a gap forces a
full-snapshot resync), and rounds stamp the membership version their
quorum was computed against. ``n_subaggs`` adds the two-level quorum
tree: sub-aggregator nodes front the joins of the groups they own; a
sub-aggregator crash loses its buffered joins (the members re-join
through a re-homed aggregator) but never touches a formed round.

HA invariants:

* ``H1 one-leader-per-term``  — no two live leaders share a term
  (election safety; ``raft_single_vote=False`` plants the double-vote
  bug that breaks it);
* ``H2 committed-survives``   — every decision ever majority-committed
  is present in every live leader's log (leader-death durability;
  ``stale_leader_fence=False`` lets a minority leader commit locally
  and breaks it);
* ``H3 stale-view-commit``    — no commit vote rides a membership view
  older than the round's (``stale_view_fence=False`` breaks it);
* ``H4 delta-chain``          — a replica's incrementally-applied view
  always equals the full snapshot at its version
  (``ordered_deltas=False`` applies deltas out of order and breaks it);
* ``H5 epoch-unique``         — formed rounds carry globally unique
  epochs: a sub-aggregator crash/re-home never splits a group's epoch.

Election *liveness* is deliberately out of scope: Raft terminates
elections with randomized timeouts, which a bounded nondeterministic
model cannot honor — terminal states with no live leader are exempt from
``L`` (the checker proves election safety, not election progress).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import FrozenSet, List, NamedTuple, Optional, Tuple

__all__ = [
    "JOINING", "HEALTHY", "HEALING", "SPECULATING", "DEAD",
    "FOLLOWER", "CANDIDATE", "LEADER",
    "SpecConfig", "Replica", "Round", "State", "Invariant",
    "Lighthouse", "Subagg",
    "init_state", "enabled_actions", "check_state", "check_terminal",
    "is_terminal",
]

# replica status values (shared vocabulary with the conformance checker
# and docs/static_analysis.md's state catalog)
JOINING = "JOINING"
HEALTHY = "HEALTHY"
HEALING = "HEALING"
SPECULATING = "SPECULATING"
DEAD = "DEAD"

# lighthouse replica (Raft) status values — DEAD is shared
FOLLOWER = "FOLLOWER"
CANDIDATE = "CANDIDATE"
LEADER = "LEADER"


@dataclass(frozen=True)
class SpecConfig:
    """One bounded configuration of the model.

    The shipped protocol is ``fence_speculation=True``,
    ``fence_divergence=True`` (sentinel armed), ``join_barrier=True``,
    ``rollback_residual=True``. Every flag exists so the checker can
    prove the protection matters by turning it off.
    """

    n_replicas: int = 2
    min_replicas: int = 1
    max_rounds: int = 3          # formed quorum rounds (bounded steps)
    crash_budget: int = 1        # SIGKILL-anywhere injections
    respawn_budget: int = 1
    corrupt_budget: int = 0      # silently-diverging computes
    speculation: bool = False    # pipelined commit (PR 3 semantics)
    join_barrier: bool = True    # False = split-brain-capable lighthouse
    fence_speculation: bool = True   # PR 3: heal waits out speculation
    fence_divergence: bool = True    # PR 10: mismatched digests veto
    rollback_residual: bool = True   # PR 6: veto rolls residual back

    # --- the HA layer (ISSUE 20) — all off/neutral by default, so the
    # single-lighthouse configurations above explore the exact PR 15
    # state space
    n_lighthouses: int = 1       # >= 2 arms the Raft lighthouse tier
    lh_crash_budget: int = 0     # lighthouse SIGKILLs (durable log kept)
    lh_respawn_budget: int = 0
    max_terms: int = 1           # term ids 1..max_terms bound elections
    partition_budget: int = 0    # isolate-the-leader network splits
    raft_single_vote: bool = True    # False = double-vote split brain
    stale_leader_fence: bool = True  # False = minority leader commits
    membership_deltas: bool = False  # versioned membership delta stream
    ordered_deltas: bool = True      # False = deltas applied out of order
    stale_view_fence: bool = True    # False = commit on a stale view
    n_subaggs: int = 0           # two-level quorum tree fan-in nodes
    subagg_crash_budget: int = 0


class Replica(NamedTuple):
    status: str
    step: int                 # committed step
    lineage: Tuple[str, ...]  # committed tokens; len == step
    residual: int             # error-feedback accumulator version
    joined: bool              # in the lighthouse's open (unformed) round
    round: int                # formed-round id this replica is in, or -1
    voted: bool               # voted in `round`
    abstain: bool             # vote was an abstention (failed heal)
    worked: bool              # computed this round's reduction
    diverged: bool            # this round's compute silently corrupted
    healer: bool              # assigned to heal in `round`
    healed: bool              # heal transfer landed
    spec_round: int           # round id of the in-flight speculative vote
    spec_token: str           # provisional token (speculation)
    epoch: int                # last quorum epoch observed
    mview: int = 0            # membership version this replica applied
    view: FrozenSet[int] = frozenset()  # its membership view at mview


class Round(NamedTuple):
    rid: int
    epoch: int
    step: int                            # the step this round attempts
    members: FrozenSet[int]
    # votes recorded at cast time: (member, token) — token "" = abstain
    votes: Tuple[Tuple[int, str], ...]
    resolved: FrozenSet[int]             # members whose vote resolved
    # members whose collective contribution completed (work done). This
    # is ROUND state, not replica state: it must survive the member's
    # later crash — a peer that died AFTER contributing does not fail
    # the survivors' allreduce, and their commits are per-group.
    done: FrozenSet[int]
    mver: int = 0   # membership version the quorum was computed against


class Lighthouse(NamedTuple):
    """One lighthouse replica of the Raft tier (``n_lighthouses >= 2``).

    ``term``/``voted_for``/``log`` are *durable* (they survive a crash —
    Raft's persistent state); ``votes`` (the ballots a candidate
    gathered) is volatile. ``log`` holds quorum-decision entries
    ``(term, rid)``; ``commit_len`` is the majority-replicated prefix
    this node, as leader, has committed. ``cell`` is the partition cell
    (0 = the majority side)."""

    status: str
    term: int
    voted_for: int                       # -1 = no vote cast this term
    votes: FrozenSet[int]
    log: Tuple[Tuple[int, int], ...]
    commit_len: int
    cell: int


class Subagg(NamedTuple):
    """A sub-aggregator of the two-level quorum tree: fronts the joins
    of the replica groups it ``owns``. Its only protocol state is the
    buffered joins — a crash loses those (the members re-join through a
    re-homed aggregator) and nothing else."""

    status: str                          # HEALTHY / DEAD
    owns: FrozenSet[int]


class State(NamedTuple):
    replicas: Tuple[Replica, ...]
    rounds: Tuple[Round, ...]       # formed rounds, in formation order
    open_round: FrozenSet[int]      # joined-but-unformed replica ids
    epoch: int
    rounds_formed: int
    crash_budget: int
    respawn_budget: int
    corrupt_budget: int
    # committed tokens per step, fleet-wide: ((step, (tokens...)), ...)
    commits: Tuple[Tuple[int, Tuple[str, ...]], ...]
    divergence_latched: bool
    # --- HA layer (constant () / 0 in single-lighthouse configs, so
    # the PR 15 state space is unchanged byte for byte)
    lighthouses: Tuple[Lighthouse, ...] = ()
    # every decision entry ever majority-committed, fleet-global ledger
    # (the H2 durability oracle — the model's ghost variable):
    # (commit_term, entry_term, rid) — commit_term scopes the Raft
    # Leader Completeness claim (a STALE lower-term leader legally
    # lacks entries committed after its term; it can't commit anything)
    ha_committed: Tuple[Tuple[int, int, int], ...] = ()
    lh_crash_budget: int = 0
    lh_respawn_budget: int = 0
    partition_budget: int = 0
    mversion: int = 0                       # membership log head version
    # membership deltas: (version, replica, alive) — version is 1-based
    mlog: Tuple[Tuple[int, int, bool], ...] = ()
    subaggs: Tuple[Subagg, ...] = ()
    subagg_budget: int = 0


class Invariant(NamedTuple):
    """One violated invariant, with human detail."""

    name: str
    detail: str


def init_state(cfg: SpecConfig) -> State:
    full_view = (
        frozenset(range(cfg.n_replicas))
        if cfg.membership_deltas else frozenset()
    )
    lighthouses: Tuple[Lighthouse, ...] = ()
    if cfg.n_lighthouses >= 2:
        # boot with lighthouse 0 already elected at term 1 (every peer
        # voted for it) — the interesting space is what happens AFTER
        # the steady state, not the bootstrap election
        lighthouses = tuple(
            Lighthouse(
                status=(LEADER if i == 0 else FOLLOWER), term=1,
                voted_for=0, votes=frozenset(), log=(), commit_len=0,
                cell=0,
            )
            for i in range(cfg.n_lighthouses)
        )
    subaggs: Tuple[Subagg, ...] = ()
    if cfg.n_subaggs > 0:
        subaggs = tuple(
            Subagg(status=HEALTHY, owns=frozenset(
                i for i in range(cfg.n_replicas)
                if i % cfg.n_subaggs == s
            ))
            for s in range(cfg.n_subaggs)
        )
    return State(
        replicas=tuple(
            Replica(
                status=JOINING, step=0, lineage=(), residual=0,
                joined=False, round=-1, voted=False, abstain=False,
                worked=False, diverged=False, healer=False, healed=False,
                spec_round=-1, spec_token="", epoch=-1,
                mview=0, view=full_view,
            )
            for _ in range(cfg.n_replicas)
        ),
        rounds=(), open_round=frozenset(), epoch=0, rounds_formed=0,
        crash_budget=cfg.crash_budget,
        respawn_budget=cfg.respawn_budget,
        corrupt_budget=cfg.corrupt_budget,
        commits=(), divergence_latched=False,
        lighthouses=lighthouses,
        lh_crash_budget=cfg.lh_crash_budget,
        lh_respawn_budget=cfg.lh_respawn_budget,
        partition_budget=cfg.partition_budget,
        subaggs=subaggs,
        subagg_budget=cfg.subagg_crash_budget,
    )


def _token(step: int, diverged: bool, epoch: int) -> str:
    """A commit token: the identity of the state a replica commits at a
    step. Epoch-tagged, because one round produces ONE agreed state —
    two rounds each committing the same step (a split brain) are two
    lineages even when both computes were clean. Within a round the tag
    is constant, so the divergence compare keys on the clean/corrupt
    prefix alone."""
    return f"{'x' if diverged else 'c'}{step}@e{epoch}"


def _commit_record(
    commits: Tuple[Tuple[int, Tuple[str, ...]], ...], step: int, token: str
) -> Tuple[Tuple[int, Tuple[str, ...]], ...]:
    out: List[Tuple[int, Tuple[str, ...]]] = []
    seen = False
    for s, toks in commits:
        if s == step:
            seen = True
            if token not in toks:
                toks = tuple(sorted(toks + (token,)))
        out.append((s, toks))
    if not seen:
        out.append((step, (token,)))
    return tuple(sorted(out))


def _replace(state: State, idx: int, rep: Replica, **kw) -> State:
    reps = state.replicas[:idx] + (rep,) + state.replicas[idx + 1:]
    return state._replace(replicas=reps, **kw)


def _set_round(state: State, rnd: Round) -> State:
    return state._replace(rounds=tuple(
        rnd if rd.rid == rnd.rid else rd for rd in state.rounds
    ))


def _live(state: State) -> List[int]:
    return [i for i, r in enumerate(state.replicas) if r.status != DEAD]


def _provisional_step(r: Replica) -> int:
    """The step a replica's in-flight state encodes: committed step,
    plus one while a speculative update is applied."""
    return r.step + (1 if r.spec_round >= 0 else 0)


def _attached(state: State, rnd: Round, j: int) -> bool:
    r = state.replicas[j]
    return r.round == rnd.rid or r.spec_round == rnd.rid


# --- HA helpers ------------------------------------------------------------


def _lh_majority(cfg: SpecConfig) -> int:
    return cfg.n_lighthouses // 2 + 1


def _lh_live(state: State) -> List[int]:
    return [
        i for i, lh in enumerate(state.lighthouses) if lh.status != DEAD
    ]


def _live_leaders(state: State) -> List[int]:
    return [
        i for i, lh in enumerate(state.lighthouses)
        if lh.status == LEADER
    ]


def _set_lh(state: State, idx: int, lh: Lighthouse, **kw) -> State:
    lhs = state.lighthouses[:idx] + (lh,) + state.lighthouses[idx + 1:]
    return state._replace(lighthouses=lhs, **kw)


def _log_up_to_date(
    a: Tuple[Tuple[int, int], ...], b: Tuple[Tuple[int, int], ...]
) -> bool:
    """Raft §5.4.1: is log ``a`` at least as up-to-date as ``b``?
    (compare last entry's term, then length)"""
    la = a[-1][0] if a else 0
    lb = b[-1][0] if b else 0
    return la > lb or (la == lb and len(a) >= len(b))


def _mem_snapshot(
    mlog: Tuple[Tuple[int, int, bool], ...], version: int, n: int
) -> FrozenSet[int]:
    """The full membership snapshot at ``version``: the initial full
    set with every delta up to and including ``version`` applied in
    order — the reference the delta chain must be equivalent to."""
    view = set(range(n))
    for ver, rep, alive in mlog:
        if ver > version:
            break
        if alive:
            view.add(rep)
        else:
            view.discard(rep)
    return frozenset(view)


def _mem_bump(state: State, cfg: SpecConfig, rep: int,
              alive: bool) -> dict:
    """State-field updates for one membership change (crash/respawn of
    replica ``rep``): bump the version, append the delta."""
    if not cfg.membership_deltas:
        return {}
    v = state.mversion + 1
    return {"mversion": v, "mlog": state.mlog + ((v, rep, alive),)}


def _home(state: State, i: int) -> Optional[int]:
    """The sub-aggregator owning replica ``i`` (None = no tree)."""
    for s, sub in enumerate(state.subaggs):
        if i in sub.owns:
            return s
    return None


def enabled_actions(
    state: State, cfg: SpecConfig
) -> List[Tuple[str, State]]:
    """Every transition enabled in ``state``: the scheduler's menu. The
    crash action appears here like any other, so the DFS interleaves a
    crash at every transition point — exhaustive SIGKILL-anywhere."""
    out: List[Tuple[str, State]] = []
    live = _live(state)

    # -- crash: any live replica, at any point, while the budget lasts
    if state.crash_budget > 0:
        for i in live:
            r = state.replicas[i]
            # SIGKILL loses everything in memory: the speculative
            # update, round membership, the un-committed residual
            # advance. The committed lineage survives (the checkpoint).
            dead = r._replace(
                status=DEAD, joined=False, round=-1, voted=False,
                abstain=False, worked=False, diverged=False,
                healer=False, healed=False, spec_round=-1,
                spec_token="", residual=r.step,
            )
            ns = _replace(
                state, i, dead,
                open_round=state.open_round - {i},
                crash_budget=state.crash_budget - 1,
                # a death is a membership change: the lighthouse bumps
                # the membership version and appends the delta
                **_mem_bump(state, cfg, i, alive=False),
            )
            out.append((f"crash({i})", ns))

    # -- respawn: a dead replica returns, state = its last commit
    if state.respawn_budget > 0:
        for i, r in enumerate(state.replicas):
            if r.status != DEAD:
                continue
            bump = _mem_bump(state, cfg, i, alive=True)
            rep = r._replace(status=JOINING)
            if cfg.membership_deltas:
                # a (re)join hands the replica the FULL membership
                # snapshot (the sublinear protocol's bootstrap path) —
                # deltas only flow to already-synced members
                v = bump["mversion"]
                rep = rep._replace(
                    mview=v,
                    view=_mem_snapshot(
                        bump["mlog"], v, cfg.n_replicas
                    ),
                )
            ns = _replace(
                state, i, rep,
                respawn_budget=state.respawn_budget - 1,
                **bump,
            )
            out.append((f"respawn({i})", ns))

    # -- join: a free live replica enters the lighthouse's open round
    if state.rounds_formed < cfg.max_rounds:
        for i in live:
            r = state.replicas[i]
            if r.joined or r.round >= 0:
                continue
            if state.subaggs:
                # two-level tree: the join goes through the replica's
                # sub-aggregator; a dead home blocks it until re-home
                home = _home(state, i)
                if home is None or state.subaggs[home].status == DEAD:
                    continue
            # pipelined: a replica may join the next round while its
            # previous vote is still in flight — that IS the pipeline
            ns = _replace(
                state, i, r._replace(joined=True),
                open_round=state.open_round | {i},
            )
            out.append((f"join({i})", ns))

    # -- form: the open round becomes a quorum
    if state.open_round and state.rounds_formed < cfg.max_rounds:
        joined = state.open_round
        barrier_ok = (
            joined == frozenset(live)
            if cfg.join_barrier
            else len(joined) >= cfg.min_replicas
        )
        if barrier_ok:
            rid = state.rounds_formed
            epoch = state.epoch + 1
            # the round attempts the max provisional step of its
            # members (the physical step the fleet's trainers are on);
            # members behind it heal first
            max_step = max(
                _provisional_step(state.replicas[i]) for i in joined
            )
            reps = list(state.replicas)
            for i in joined:
                r = reps[i]
                behind = _provisional_step(r) < max_step
                reps[i] = r._replace(
                    joined=False, round=rid, voted=False, abstain=False,
                    worked=False, healer=behind, healed=False,
                    epoch=epoch,
                    status=(HEALING if behind else (
                        r.status if r.status == SPECULATING else HEALTHY
                    )),
                )
            ns = state._replace(
                replicas=tuple(reps),
                rounds=state.rounds + (
                    Round(rid=rid, epoch=epoch, step=max_step,
                          members=joined, votes=(),
                          resolved=frozenset(), done=frozenset(),
                          mver=state.mversion),
                ),
                open_round=frozenset(),
                epoch=epoch,
                rounds_formed=rid + 1,
            )
            if not state.lighthouses:
                out.append((f"form(r{rid},step={max_step})", ns))
            else:
                # HA tier: the round is a quorum DECISION — it must go
                # through a leader, which appends the (term, rid) entry
                # to its durable log. Managers fail over via the peer
                # list, so ANY live leader serves — including a stale
                # minority-partitioned one (its appended entry can never
                # majority-commit while the fence holds; with the fence
                # off that is exactly the H2 counterexample).
                for li in _live_leaders(state):
                    lh = ns.lighthouses[li]
                    ns2 = _set_lh(
                        ns, li,
                        lh._replace(log=lh.log + ((lh.term, rid),)),
                    )
                    out.append(
                        (f"form(r{rid},step={max_step},lh={li})", ns2)
                    )

    # per-round member actions
    for rnd in state.rounds:
        for i in sorted(rnd.members):
            if i in rnd.resolved:
                continue
            r = state.replicas[i]
            if r.status == DEAD:
                continue

            # -- heal: copy state from an up-to-date round member that
            # has not voted yet (the serve happens at quorum time,
            # before the source's compute/vote — a voted source's
            # staged window is closed). The source serves its CURRENT
            # committed state (manager.py: "the received state dict is
            # authoritative ... never rewind below the state the bytes
            # actually encode").
            if r.round == rnd.rid and r.healer and not r.healed:
                sourced = False
                for j in sorted(rnd.members):
                    src = state.replicas[j]
                    if (
                        j == i or src.status == DEAD or src.healer
                        or not _attached(state, rnd, j)
                        or (src.round == rnd.rid and src.voted)
                    ):
                        continue
                    speculative = src.spec_round >= 0
                    if cfg.fence_speculation and speculative:
                        # PR 3 fence: the heal WAITS until the source's
                        # vote resolves — the action is disabled, not
                        # taken (resolve of that vote re-enables it)
                        continue
                    sourced = True
                    lineage = src.lineage
                    step = src.step
                    if speculative:
                        # fence off: the staged state illegally carries
                        # the un-voted provisional update
                        lineage = lineage + (src.spec_token,)
                        step += 1
                    healed = r._replace(
                        step=step, lineage=lineage, residual=step,
                        healed=True, status=HEALING,
                    )
                    label = f"heal({i}<-{j})" + (
                        "!spec" if speculative else ""
                    )
                    out.append((label, _replace(state, i, healed)))
                # -- heal_fail: transfers can fail (torn stream, source
                # shutdown) and a fenced-out heal eventually times out:
                # the healer latches the error and its barrier vote
                # abstains — its own step aborts, nobody else's does
                if not sourced and not r.voted:
                    ns = _replace(
                        state, i,
                        r._replace(voted=True, abstain=True),
                    )
                    ns = _set_round(
                        ns, rnd._replace(votes=rnd.votes + ((i, ""),))
                    )
                    out.append((f"heal_fail({i})", ns))

            # -- work: compute this round's reduction. A replica with a
            # still-unresolved speculative vote resolves it before
            # issuing the next step's ops (resolve_pending_commit
            # precedes collectives), so work is gated on spec_round < 0.
            ready = (not r.healer) or r.healed
            if (
                r.round == rnd.rid and ready and not r.worked
                and not r.voted and r.spec_round < 0
            ):
                with_done = _set_round(
                    state, rnd._replace(done=rnd.done | {i})
                )
                ns = _replace(with_done, i, r._replace(worked=True))
                out.append((f"work({i})", ns))
                if state.corrupt_budget > 0 and not r.healer:
                    ns2 = _replace(
                        with_done, i,
                        r._replace(worked=True, diverged=True),
                        corrupt_budget=state.corrupt_budget - 1,
                    )
                    out.append((f"work_corrupt({i})", ns2))

            # -- vote: cast this round's commit vote (with the state
            # digest riding it — the token). The token's step is the
            # REPLICA's committed step at vote time (the vote RPC's
            # rec.step), not the round label: a replica whose previous
            # speculation was vetoed legitimately re-attempts its
            # rolled-back step inside a round labeled one ahead
            # (manager.py start_quorum's "a veto makes that step's
            # label one ahead" comment).
            # a commit vote must ride a membership view at least as new
            # as the one the round's quorum was computed against: with
            # the fence on, a lagging replica applies its pending deltas
            # (or snapshot-resyncs) before voting — the action is
            # disabled, not taken; with the fence off the vote goes out
            # stale and H3 flags it (the !stale label)
            stale_view = (
                cfg.membership_deltas and r.mview < rnd.mver
            )
            if (
                r.round == rnd.rid and r.worked and not r.voted
                and not (stale_view and cfg.stale_view_fence)
            ):
                token = _token(
                    r.step, r.diverged and not r.healer, rnd.epoch
                )
                tag = "!stale" if stale_view else ""
                if cfg.speculation and not r.healer:
                    # pipelined: apply the update provisionally, vote,
                    # and float free to start the next step while the
                    # vote is in flight
                    spec = r._replace(
                        voted=True, status=SPECULATING,
                        spec_round=rnd.rid, spec_token=token,
                        residual=r.step + 1,  # error-feedback applied
                        round=-1,
                    )
                    ns = _replace(state, i, spec)
                    ns = _set_round(
                        ns, rnd._replace(votes=rnd.votes + ((i, token),))
                    )
                    out.append((f"vote_spec({i}){tag}", ns))
                else:
                    ns = _replace(state, i, r._replace(voted=True))
                    ns = _set_round(
                        ns, rnd._replace(votes=rnd.votes + ((i, token),))
                    )
                    out.append((f"vote({i}){tag}", ns))

            # -- resolve: this replica's vote decision lands. Commit is
            # arbitrated PER replica group; the divergence fence is the
            # only fleet-global wait (the cohort digest compare).
            cast = (
                (r.round == rnd.rid and r.voted)
                or r.spec_round == rnd.rid
            )
            if cast:
                unresolved = [
                    j for j in rnd.members if j not in rnd.resolved
                ]
                accounted = all(
                    (not _attached(state, rnd, j))
                    or state.replicas[j].status == DEAD
                    or any(v[0] == j for v in rnd.votes)
                    for j in unresolved
                )
                if cfg.fence_divergence and not accounted:
                    continue  # fence: wait for the full cohort's digests
                out.append(_resolve(state, cfg, rnd, i))

    _ha_actions(state, cfg, out)
    return out


def _ha_actions(
    state: State, cfg: SpecConfig, out: List[Tuple[str, State]]
) -> None:
    """The HA-layer transitions: Raft lighthouse tier, membership
    deltas, sub-aggregator tree. All empty in a default config."""

    # ---- Raft lighthouse tier -------------------------------------------
    for li, lh in enumerate(state.lighthouses):
        if lh.status == DEAD:
            # -- lh_respawn: durable state (term/voted_for/log) intact,
            # volatile ballots gone; returns as a follower
            if state.lh_respawn_budget > 0:
                ns = _set_lh(
                    state, li,
                    lh._replace(status=FOLLOWER, votes=frozenset()),
                    lh_respawn_budget=state.lh_respawn_budget - 1,
                )
                out.append((f"lh_respawn({li})", ns))
            continue

        # -- lh_crash: SIGKILL a lighthouse; the log is durable
        if state.lh_crash_budget > 0:
            ns = _set_lh(
                state, li,
                lh._replace(status=DEAD, votes=frozenset()),
                lh_crash_budget=state.lh_crash_budget - 1,
            )
            out.append((f"lh_crash({li})", ns))

        # -- lh_campaign: a non-leader that sees no live leader in its
        # cell at its term or above starts an election one term up
        # (bounded by max_terms — election *liveness* is randomized-
        # timeout territory, out of the model's scope)
        if lh.status != LEADER and lh.term + 1 <= cfg.max_terms:
            leader_visible = any(
                o.status == LEADER and o.cell == lh.cell
                and o.term >= lh.term
                for oi, o in enumerate(state.lighthouses)
                if oi != li and o.status != DEAD
            )
            if not leader_visible:
                ns = _set_lh(state, li, lh._replace(
                    status=CANDIDATE, term=lh.term + 1, voted_for=li,
                    votes=frozenset({li}),
                ))
                out.append((f"lh_campaign({li},t{lh.term + 1})", ns))

        # -- lh_vote: grant a ballot to a live same-cell candidate.
        # Raft's two checks: one vote per term (persistent voted_for),
        # and the candidate's log must be at least as up-to-date.
        # ``raft_single_vote=False`` plants the double-vote bug.
        if lh.status == CANDIDATE:
            for vi, v in enumerate(state.lighthouses):
                if (
                    vi == li or v.status == DEAD or v.cell != lh.cell
                    or lh.term < v.term
                ):
                    continue
                already = v.voted_for >= 0 and v.term == lh.term
                if already and v.voted_for != li and cfg.raft_single_vote:
                    continue
                if v.voted_for == li and v.term == lh.term:
                    continue  # ballot already counted
                if not _log_up_to_date(lh.log, v.log):
                    continue
                granter = v._replace(
                    status=(FOLLOWER if v.status != DEAD else v.status),
                    term=lh.term, voted_for=li, votes=frozenset(),
                )
                ns = _set_lh(state, vi, granter)
                ns = _set_lh(
                    ns, li,
                    ns.lighthouses[li]._replace(
                        votes=lh.votes | {vi}
                    ),
                )
                out.append((f"lh_vote({vi}->{li},t{lh.term})", ns))

        # -- lh_elect: a candidate with a majority of ballots wins
        if (
            lh.status == CANDIDATE
            and len(lh.votes) >= _lh_majority(cfg)
        ):
            ns = _set_lh(state, li, lh._replace(status=LEADER))
            out.append((f"lh_elect({li},t{lh.term})", ns))

        # -- lh_append: a leader replicates its log to a live same-cell
        # peer at or below its term (full-prefix adoption — the
        # AppendEntries catch-up collapsed to one step; a stale leader
        # adopting a newer leader's log is Raft's log repair and steps
        # it down)
        if lh.status == LEADER:
            for fi, f in enumerate(state.lighthouses):
                if (
                    fi == li or f.status == DEAD or f.cell != lh.cell
                    or f.term > lh.term or f.log == lh.log
                ):
                    continue
                ns = _set_lh(state, fi, f._replace(
                    status=FOLLOWER, term=lh.term, log=lh.log,
                    votes=frozenset(),
                ))
                out.append((f"lh_append({fi}<-{li})", ns))

        # -- lh_commit: the leader advances its commit index over the
        # longest prefix a majority of lighthouses hold (logs are
        # durable, so a dead node's replicated prefix still counts).
        # ``stale_leader_fence=False`` plants the bug: the leader
        # commits its whole log with no majority check — a minority-
        # partitioned stale leader then "commits" decisions the next
        # leader never saw (H2).
        if lh.status == LEADER and lh.commit_len < len(lh.log):
            if cfg.stale_leader_fence:
                new_len = lh.commit_len
                for k in range(lh.commit_len + 1, len(lh.log) + 1):
                    holders = sum(
                        1 for o in state.lighthouses
                        if o.log[:k] == lh.log[:k]
                    )
                    if holders >= _lh_majority(cfg):
                        new_len = k
                    else:
                        break
            else:
                new_len = len(lh.log)
            if new_len > lh.commit_len:
                known = {
                    (et, rid) for _ct, et, rid in state.ha_committed
                }
                committed = state.ha_committed + tuple(
                    (lh.term, e[0], e[1])
                    for e in lh.log[lh.commit_len:new_len]
                    if e not in known
                )
                ns = _set_lh(
                    state, li, lh._replace(commit_len=new_len),
                    ha_committed=committed,
                )
                out.append((f"lh_commit({li},{new_len})", ns))

    # -- lh_partition / lh_unpartition: a network split that isolates
    # the current leader (the classic stale-leader scenario); healing
    # restores one cell
    if state.partition_budget > 0 and len(state.lighthouses) >= 3:
        for li in _live_leaders(state):
            if state.lighthouses[li].cell != 0:
                continue
            ns = _set_lh(
                state, li,
                state.lighthouses[li]._replace(cell=1),
                partition_budget=state.partition_budget - 1,
            )
            out.append((f"lh_partition({li})", ns))
    if any(lh.cell != 0 for lh in state.lighthouses):
        ns = state._replace(lighthouses=tuple(
            lh._replace(cell=0) for lh in state.lighthouses
        ))
        out.append(("lh_unpartition", ns))

    # ---- membership deltas ----------------------------------------------
    if cfg.membership_deltas and state.mversion > 0:
        for i, r in enumerate(state.replicas):
            if r.status == DEAD or r.mview >= state.mversion:
                continue
            if cfg.ordered_deltas:
                versions = (r.mview + 1,)
            else:
                # the planted bug: the transport reorders/drops, and the
                # replica applies whatever delta arrives next
                versions = tuple(
                    range(r.mview + 1, state.mversion + 1)
                )
            for v in versions:
                ver, rep, alive = state.mlog[v - 1]
                view = (r.view | {rep}) if alive else (r.view - {rep})
                ns = _replace(
                    state, i, r._replace(mview=v, view=view)
                )
                out.append((f"delta({i},v{v})", ns))
            if state.mversion - r.mview >= 2:
                # gap detected (a delta was lost): the sublinear
                # protocol falls back to the full snapshot
                ns = _replace(state, i, r._replace(
                    mview=state.mversion,
                    view=_mem_snapshot(
                        state.mlog, state.mversion, cfg.n_replicas
                    ),
                ))
                out.append((f"delta_snap({i})", ns))

    # ---- sub-aggregator tree --------------------------------------------
    if state.subaggs:
        live_subs = [
            s for s, sub in enumerate(state.subaggs)
            if sub.status != DEAD
        ]
        # -- sub_crash: the aggregator dies; its buffered (un-formed)
        # joins die with it — the owned members fall out of the open
        # round and must re-join once re-homed. Formed rounds are
        # untouched: the tree only fronts joins (H5's contract).
        if state.subagg_budget > 0 and len(live_subs) > 1:
            for s in live_subs:
                sub = state.subaggs[s]
                reps = tuple(
                    r._replace(joined=False) if i in sub.owns else r
                    for i, r in enumerate(state.replicas)
                )
                ns = state._replace(
                    replicas=reps,
                    open_round=state.open_round - sub.owns,
                    subaggs=tuple(
                        x._replace(status=DEAD) if j == s else x
                        for j, x in enumerate(state.subaggs)
                    ),
                    subagg_budget=state.subagg_budget - 1,
                )
                out.append((f"sub_crash({s})", ns))
        # -- sub_rehome: a dead aggregator's groups re-home onto the
        # first live one (deterministic — the lighthouse assigns)
        for s, sub in enumerate(state.subaggs):
            if sub.status != DEAD or not sub.owns or not live_subs:
                continue
            t = live_subs[0]
            subs = list(state.subaggs)
            subs[t] = subs[t]._replace(owns=subs[t].owns | sub.owns)
            subs[s] = sub._replace(owns=frozenset())
            ns = state._replace(subaggs=tuple(subs))
            out.append((f"sub_rehome({s}->{t})", ns))


def _resolve(
    state: State, cfg: SpecConfig, rnd: Round, i: int
) -> Tuple[str, State]:
    r = state.replicas[i]
    was_spec = r.spec_round == rnd.rid

    # a member that disappeared BEFORE its collective contribution
    # landed broke the survivors' allreduce: their ops errored, the
    # error latched, their steps abort. A member that died after
    # contributing (work done), cast its vote (incl. a failed-heal
    # abstention — its ranks still rode the plane with zeros), or
    # already resolved fails nobody — commits are per-group; the dead
    # group simply respawns behind and heals.
    lost = any(
        j not in rnd.done
        and j not in rnd.resolved
        and not any(v[0] == j for v in rnd.votes)
        and (state.replicas[j].status == DEAD
             or not _attached(state, rnd, j))
        for j in rnd.members
    )
    # the divergence fence: compare the cast digests within MY (epoch,
    # step) cohort — the lighthouse keys its compare on (epoch, step),
    # so votes for a different step never enter it; abstains ("")
    # complete the cohort but never enter the comparison
    my_step = state.replicas[i].step
    tokens = {
        t for _j, t in rnd.votes
        if t and t[1:].split("@", 1)[0] == str(my_step)
    }
    diverged = len(tokens) > 1
    latched = state.divergence_latched
    my_token = r.spec_token if was_spec else next(
        (t for j, t in rnd.votes if j == i), ""
    )
    commit = bool(my_token) and not r.abstain and not lost
    if diverged and cfg.fence_divergence:
        commit = False
        latched = True

    if commit:
        new_step = r.step + 1
        lineage = r.lineage + (my_token,)
        if was_spec:
            # resolve the speculation in place: the replica may already
            # be a member of the NEXT round — leave that round's
            # bookkeeping (round/voted/worked) untouched
            rep = r._replace(
                status=(HEALTHY if r.status == SPECULATING else r.status),
                step=new_step, lineage=lineage, residual=new_step,
                spec_round=-1, spec_token="",
            )
        else:
            rep = r._replace(
                status=HEALTHY, step=new_step, lineage=lineage,
                residual=new_step, round=-1, voted=False, abstain=False,
                worked=False, diverged=False, healer=False, healed=False,
            )
        commits = _commit_record(state.commits, r.step, my_token)
    else:
        residual = r.step
        if was_spec and not cfg.rollback_residual:
            residual = r.step + 1  # the planted PR 6 bug
        if was_spec:
            rep = r._replace(
                status=(HEALTHY if r.status == SPECULATING else r.status),
                residual=residual, spec_round=-1, spec_token="",
            )
        else:
            rep = r._replace(
                status=HEALTHY, round=-1, voted=False, abstain=False,
                worked=False, diverged=False, healer=False,
                # an aborted heal is discarded with the step: the healer
                # stays behind until a committing round
                healed=False,
                residual=residual,
            )
        commits = state.commits

    ns = _replace(state, i, rep, commits=commits,
                  divergence_latched=latched)
    ns = _set_round(ns, rnd._replace(resolved=rnd.resolved | {i}))
    verdict = "commit" if commit else "abort"
    return (f"resolve({i},r{rnd.rid},{verdict})", ns)


# ---------------------------------------------------------------------------
# invariants
# ---------------------------------------------------------------------------


def check_state(
    state: State, cfg: SpecConfig, action: str = ""
) -> List[Invariant]:
    """Safety invariants, checked at every visited state."""
    out: List[Invariant] = []

    # I1: at most one committed lineage per step, fleet-wide
    for step, tokens in state.commits:
        if len(tokens) > 1:
            out.append(Invariant(
                "I1-unique-commit",
                f"step {step} committed {len(tokens)} distinct lineages "
                f"{list(tokens)} — split brain or silently diverged "
                "commit",
            ))

    # I3: a heal action that copied speculative state is labeled !spec
    if action.startswith("heal(") and action.endswith("!spec"):
        out.append(Invariant(
            "I3-healer-fence",
            f"{action}: the healer copied a SPECULATING source's state — "
            "an un-voted optimizer update leaked into a served "
            "checkpoint (PR 3 fence violated)",
        ))

    # I4: residual version == the step the replica's state encodes
    for i, r in enumerate(state.replicas):
        if r.status == DEAD:
            continue
        expect = _provisional_step(r)
        if r.residual != expect:
            out.append(Invariant(
                "I4-residual-rollback",
                f"replica {i}: error-feedback residual v{r.residual} but "
                f"state encodes step {expect} — a vetoed speculative "
                "update left the residual un-rolled-back (PR 6)",
            ))
        if len(r.lineage) != r.step:
            out.append(Invariant(
                "I4-residual-rollback",
                f"replica {i}: lineage length {len(r.lineage)} != "
                f"committed step {r.step}",
            ))

    # I5: a DETECTED divergence never commits while the fence is armed.
    # (A single-member cohort committing a corrupt state is invisible to
    # any digest compare — the sentinel's contract, like the real one's,
    # covers disagreement, which needs two states to disagree.)
    if cfg.fence_divergence:
        for step, tokens in state.commits:
            if len(tokens) > 1 and any(t.startswith("x") for t in tokens):
                out.append(Invariant(
                    "I5-diverged-commit",
                    f"step {step} committed disagreeing tokens "
                    f"{list(tokens)} with the divergence fence armed — "
                    "the cohort compare must have vetoed this",
                ))

    # I2: epochs only increment (structural in the model; the
    # conformance checker enforces it on real trails)
    for i, r in enumerate(state.replicas):
        if r.epoch > state.epoch:
            out.append(Invariant(
                "I2-epoch-monotonic",
                f"replica {i} observed epoch {r.epoch} beyond the "
                f"lighthouse's {state.epoch}",
            ))

    # ---- HA invariants (ISSUE 20) --------------------------------------

    # H1: at most one live leader per term (Raft election safety)
    if state.lighthouses:
        by_term: dict = {}
        for li, lh in enumerate(state.lighthouses):
            if lh.status == LEADER:
                by_term.setdefault(lh.term, []).append(li)
        for term, leaders in sorted(by_term.items()):
            if len(leaders) > 1:
                out.append(Invariant(
                    "H1-one-leader-per-term",
                    f"term {term} has {len(leaders)} live leaders "
                    f"{leaders} — split-brain election (a voter granted "
                    "two ballots in one term)",
                ))

        # H2: Raft Leader Completeness over quorum decisions — a
        # decision committed in term T must be present in every live
        # leader of term >= T (a STALE lower-term leader legally lacks
        # newer entries; the majority-commit fence keeps it impotent)
        for li in _live_leaders(state):
            lh = state.lighthouses[li]
            for ct, et, rid in state.ha_committed:
                if lh.term >= ct and (et, rid) not in lh.log:
                    out.append(Invariant(
                        "H2-committed-survives",
                        f"leader {li} (term {lh.term}) is missing "
                        f"decision ({et}, r{rid}) committed in term "
                        f"{ct} — a committed quorum decision was lost "
                        "across a leader change (stale-leader commit)",
                    ))

    # H3: a commit vote rode a membership view older than the round's
    # (action-labelled, like I3 — the !stale tag marks the transition)
    if action.startswith(("vote(", "vote_spec(")) \
            and action.endswith("!stale"):
        out.append(Invariant(
            "H3-stale-view-commit",
            f"{action}: the commit vote rode a membership view older "
            "than the version the round's quorum was computed against "
            "— the stale-view fence must hold the vote until the "
            "replica's deltas catch up",
        ))

    # H4: delta-chain equivalence — the incrementally-applied view must
    # equal the full snapshot at the replica's version
    if cfg.membership_deltas:
        for i, r in enumerate(state.replicas):
            if r.status == DEAD:
                continue
            want = _mem_snapshot(state.mlog, r.mview, cfg.n_replicas)
            if r.view != want:
                out.append(Invariant(
                    "H4-delta-chain",
                    f"replica {i} at membership v{r.mview} holds view "
                    f"{sorted(r.view)} but the snapshot at v{r.mview} "
                    f"is {sorted(want)} — the delta stream was applied "
                    "out of order (delta-chain equivalence broken)",
                ))

    # H5: formed rounds carry globally unique epochs — a sub-aggregator
    # crash/re-home must never split a group's epoch plane
    seen_epochs: dict = {}
    for rnd in state.rounds:
        if rnd.epoch in seen_epochs:
            out.append(Invariant(
                "H5-epoch-unique",
                f"rounds r{seen_epochs[rnd.epoch]} and r{rnd.rid} both "
                f"carry epoch {rnd.epoch} — the epoch plane split",
            ))
        else:
            seen_epochs[rnd.epoch] = rnd.rid

    return out


def is_terminal(state: State, cfg: SpecConfig) -> bool:
    return not enabled_actions(state, cfg)


def check_terminal(state: State, cfg: SpecConfig) -> List[Invariant]:
    """Liveness-ish: a terminal state with a quorum's worth of live
    replicas must have committed something."""
    live = _live(state)
    if state.lighthouses and not _live_leaders(state):
        # no live leader in a terminal state: the election deadlocked
        # inside the term bound (two candidates splitting the vote
        # forever). Raft breaks these with randomized timeouts — a
        # probabilistic liveness argument a bounded nondeterministic
        # model cannot make, so these terminals are exempt from L (the
        # checker proves election SAFETY, not election progress).
        return []
    if len(live) >= cfg.min_replicas and cfg.max_rounds > 0:
        if not state.commits:
            return [Invariant(
                "L-liveness",
                f"terminal state with {len(live)} live replicas "
                f"(min_replicas={cfg.min_replicas}) committed nothing "
                f"in {cfg.max_rounds} rounds",
            )]
    return []
