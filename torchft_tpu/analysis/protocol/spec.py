"""The FT protocol as an executable state machine.

The model is the per-step lifecycle exactly as the implementation ships
it (``manager.py`` / ``coord.cc`` semantics), abstracted to the decisions
that carry the correctness argument:

* **Replicas** (one per replica group — the Manager's unit of commit)
  hold a committed *lineage* — the ordered tuple of per-step commit
  tokens — plus an error-feedback *residual* version that must track the
  committed step (PR 6's rollback consistency). A replica is JOINING
  (pre-first-quorum), HEALTHY, HEALING (behind the round's max step,
  pulling state from a source), SPECULATING (pipelined commit: the
  optimizer update applied, the vote still in flight — PR 3), or DEAD.
* **The lighthouse** forms rounds: replicas join, a round *forms* when
  the join barrier is satisfied (every live replica — the quorum), and
  each formed round bumps the epoch (quorum_id). Members compute, vote,
  and **resolve independently**: the commit vote is arbitrated per
  replica group (``mgr.should_commit``), not fleet-wide — the only
  fleet-global wait is the divergence fence's cohort digest compare
  (PR 10), which blocks resolution until every member's digest (or
  abstention) is in and vetoes every member's commit on a mismatch.
* **Crashes** are a first-class action: while the crash budget lasts,
  any live replica can die *between any two transitions* — the
  model-checker scheduler interleaves the crash action at every
  transition point, which is the SIGKILL-anywhere semantics the
  faultinject runner implements dynamically. Dead replicas respawn from
  their last committed state (the checkpoint), rejoin behind, and heal.

``SpecConfig`` flags deliberately allow *broken* variants — the fences
off, the join barrier off (split brain), residual rollback off — so the
checker can demonstrate that each protection is load-bearing: turning
one off must produce an invariant violation (the seeded-fixture tests
assert exactly that), and the shipped configuration must produce none.

Invariants (``check_state`` / ``check_terminal``):

* ``I1 unique-commit``   — at most one committed lineage token per step,
  fleet-wide (a split brain or silently diverged commit violates this);
* ``I2 epoch-monotonic`` — a replica's observed quorum epoch never
  decreases;
* ``I3 healer-fence``    — a healer never observes (copies) speculative
  state: heal sources must not be SPECULATING (PR 3's fence);
* ``I4 residual-rollback`` — every replica's error-feedback residual
  version equals the step its state actually encodes (committed step, or
  the provisional step while SPECULATING) — a vetoed speculative update
  must roll the residual back with the weights (PR 6);
* ``I5 diverged-commit`` — a *detected* divergence (two member states
  disagreeing) never commits while the divergence fence is armed
  (PR 10);
* ``L  liveness``        — in every terminal state with at least
  ``min_replicas`` live replicas, some step committed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import FrozenSet, List, NamedTuple, Optional, Tuple

__all__ = [
    "JOINING", "HEALTHY", "HEALING", "SPECULATING", "DEAD",
    "SpecConfig", "Replica", "Round", "State", "Invariant",
    "init_state", "enabled_actions", "check_state", "check_terminal",
    "is_terminal",
]

# replica status values (shared vocabulary with the conformance checker
# and docs/static_analysis.md's state catalog)
JOINING = "JOINING"
HEALTHY = "HEALTHY"
HEALING = "HEALING"
SPECULATING = "SPECULATING"
DEAD = "DEAD"


@dataclass(frozen=True)
class SpecConfig:
    """One bounded configuration of the model.

    The shipped protocol is ``fence_speculation=True``,
    ``fence_divergence=True`` (sentinel armed), ``join_barrier=True``,
    ``rollback_residual=True``. Every flag exists so the checker can
    prove the protection matters by turning it off.
    """

    n_replicas: int = 2
    min_replicas: int = 1
    max_rounds: int = 3          # formed quorum rounds (bounded steps)
    crash_budget: int = 1        # SIGKILL-anywhere injections
    respawn_budget: int = 1
    corrupt_budget: int = 0      # silently-diverging computes
    speculation: bool = False    # pipelined commit (PR 3 semantics)
    join_barrier: bool = True    # False = split-brain-capable lighthouse
    fence_speculation: bool = True   # PR 3: heal waits out speculation
    fence_divergence: bool = True    # PR 10: mismatched digests veto
    rollback_residual: bool = True   # PR 6: veto rolls residual back


class Replica(NamedTuple):
    status: str
    step: int                 # committed step
    lineage: Tuple[str, ...]  # committed tokens; len == step
    residual: int             # error-feedback accumulator version
    joined: bool              # in the lighthouse's open (unformed) round
    round: int                # formed-round id this replica is in, or -1
    voted: bool               # voted in `round`
    abstain: bool             # vote was an abstention (failed heal)
    worked: bool              # computed this round's reduction
    diverged: bool            # this round's compute silently corrupted
    healer: bool              # assigned to heal in `round`
    healed: bool              # heal transfer landed
    spec_round: int           # round id of the in-flight speculative vote
    spec_token: str           # provisional token (speculation)
    epoch: int                # last quorum epoch observed


class Round(NamedTuple):
    rid: int
    epoch: int
    step: int                            # the step this round attempts
    members: FrozenSet[int]
    # votes recorded at cast time: (member, token) — token "" = abstain
    votes: Tuple[Tuple[int, str], ...]
    resolved: FrozenSet[int]             # members whose vote resolved
    # members whose collective contribution completed (work done). This
    # is ROUND state, not replica state: it must survive the member's
    # later crash — a peer that died AFTER contributing does not fail
    # the survivors' allreduce, and their commits are per-group.
    done: FrozenSet[int]


class State(NamedTuple):
    replicas: Tuple[Replica, ...]
    rounds: Tuple[Round, ...]       # formed rounds, in formation order
    open_round: FrozenSet[int]      # joined-but-unformed replica ids
    epoch: int
    rounds_formed: int
    crash_budget: int
    respawn_budget: int
    corrupt_budget: int
    # committed tokens per step, fleet-wide: ((step, (tokens...)), ...)
    commits: Tuple[Tuple[int, Tuple[str, ...]], ...]
    divergence_latched: bool


class Invariant(NamedTuple):
    """One violated invariant, with human detail."""

    name: str
    detail: str


def init_state(cfg: SpecConfig) -> State:
    return State(
        replicas=tuple(
            Replica(
                status=JOINING, step=0, lineage=(), residual=0,
                joined=False, round=-1, voted=False, abstain=False,
                worked=False, diverged=False, healer=False, healed=False,
                spec_round=-1, spec_token="", epoch=-1,
            )
            for _ in range(cfg.n_replicas)
        ),
        rounds=(), open_round=frozenset(), epoch=0, rounds_formed=0,
        crash_budget=cfg.crash_budget,
        respawn_budget=cfg.respawn_budget,
        corrupt_budget=cfg.corrupt_budget,
        commits=(), divergence_latched=False,
    )


def _token(step: int, diverged: bool, epoch: int) -> str:
    """A commit token: the identity of the state a replica commits at a
    step. Epoch-tagged, because one round produces ONE agreed state —
    two rounds each committing the same step (a split brain) are two
    lineages even when both computes were clean. Within a round the tag
    is constant, so the divergence compare keys on the clean/corrupt
    prefix alone."""
    return f"{'x' if diverged else 'c'}{step}@e{epoch}"


def _commit_record(
    commits: Tuple[Tuple[int, Tuple[str, ...]], ...], step: int, token: str
) -> Tuple[Tuple[int, Tuple[str, ...]], ...]:
    out: List[Tuple[int, Tuple[str, ...]]] = []
    seen = False
    for s, toks in commits:
        if s == step:
            seen = True
            if token not in toks:
                toks = tuple(sorted(toks + (token,)))
        out.append((s, toks))
    if not seen:
        out.append((step, (token,)))
    return tuple(sorted(out))


def _replace(state: State, idx: int, rep: Replica, **kw) -> State:
    reps = state.replicas[:idx] + (rep,) + state.replicas[idx + 1:]
    return state._replace(replicas=reps, **kw)


def _set_round(state: State, rnd: Round) -> State:
    return state._replace(rounds=tuple(
        rnd if rd.rid == rnd.rid else rd for rd in state.rounds
    ))


def _live(state: State) -> List[int]:
    return [i for i, r in enumerate(state.replicas) if r.status != DEAD]


def _provisional_step(r: Replica) -> int:
    """The step a replica's in-flight state encodes: committed step,
    plus one while a speculative update is applied."""
    return r.step + (1 if r.spec_round >= 0 else 0)


def _attached(state: State, rnd: Round, j: int) -> bool:
    r = state.replicas[j]
    return r.round == rnd.rid or r.spec_round == rnd.rid


def enabled_actions(
    state: State, cfg: SpecConfig
) -> List[Tuple[str, State]]:
    """Every transition enabled in ``state``: the scheduler's menu. The
    crash action appears here like any other, so the DFS interleaves a
    crash at every transition point — exhaustive SIGKILL-anywhere."""
    out: List[Tuple[str, State]] = []
    live = _live(state)

    # -- crash: any live replica, at any point, while the budget lasts
    if state.crash_budget > 0:
        for i in live:
            r = state.replicas[i]
            # SIGKILL loses everything in memory: the speculative
            # update, round membership, the un-committed residual
            # advance. The committed lineage survives (the checkpoint).
            dead = r._replace(
                status=DEAD, joined=False, round=-1, voted=False,
                abstain=False, worked=False, diverged=False,
                healer=False, healed=False, spec_round=-1,
                spec_token="", residual=r.step,
            )
            ns = _replace(
                state, i, dead,
                open_round=state.open_round - {i},
                crash_budget=state.crash_budget - 1,
            )
            out.append((f"crash({i})", ns))

    # -- respawn: a dead replica returns, state = its last commit
    if state.respawn_budget > 0:
        for i, r in enumerate(state.replicas):
            if r.status != DEAD:
                continue
            ns = _replace(
                state, i, r._replace(status=JOINING),
                respawn_budget=state.respawn_budget - 1,
            )
            out.append((f"respawn({i})", ns))

    # -- join: a free live replica enters the lighthouse's open round
    if state.rounds_formed < cfg.max_rounds:
        for i in live:
            r = state.replicas[i]
            if r.joined or r.round >= 0:
                continue
            # pipelined: a replica may join the next round while its
            # previous vote is still in flight — that IS the pipeline
            ns = _replace(
                state, i, r._replace(joined=True),
                open_round=state.open_round | {i},
            )
            out.append((f"join({i})", ns))

    # -- form: the open round becomes a quorum
    if state.open_round and state.rounds_formed < cfg.max_rounds:
        joined = state.open_round
        barrier_ok = (
            joined == frozenset(live)
            if cfg.join_barrier
            else len(joined) >= cfg.min_replicas
        )
        if barrier_ok:
            rid = state.rounds_formed
            epoch = state.epoch + 1
            # the round attempts the max provisional step of its
            # members (the physical step the fleet's trainers are on);
            # members behind it heal first
            max_step = max(
                _provisional_step(state.replicas[i]) for i in joined
            )
            reps = list(state.replicas)
            for i in joined:
                r = reps[i]
                behind = _provisional_step(r) < max_step
                reps[i] = r._replace(
                    joined=False, round=rid, voted=False, abstain=False,
                    worked=False, healer=behind, healed=False,
                    epoch=epoch,
                    status=(HEALING if behind else (
                        r.status if r.status == SPECULATING else HEALTHY
                    )),
                )
            ns = state._replace(
                replicas=tuple(reps),
                rounds=state.rounds + (
                    Round(rid=rid, epoch=epoch, step=max_step,
                          members=joined, votes=(),
                          resolved=frozenset(), done=frozenset()),
                ),
                open_round=frozenset(),
                epoch=epoch,
                rounds_formed=rid + 1,
            )
            out.append((f"form(r{rid},step={max_step})", ns))

    # per-round member actions
    for rnd in state.rounds:
        for i in sorted(rnd.members):
            if i in rnd.resolved:
                continue
            r = state.replicas[i]
            if r.status == DEAD:
                continue

            # -- heal: copy state from an up-to-date round member that
            # has not voted yet (the serve happens at quorum time,
            # before the source's compute/vote — a voted source's
            # staged window is closed). The source serves its CURRENT
            # committed state (manager.py: "the received state dict is
            # authoritative ... never rewind below the state the bytes
            # actually encode").
            if r.round == rnd.rid and r.healer and not r.healed:
                sourced = False
                for j in sorted(rnd.members):
                    src = state.replicas[j]
                    if (
                        j == i or src.status == DEAD or src.healer
                        or not _attached(state, rnd, j)
                        or (src.round == rnd.rid and src.voted)
                    ):
                        continue
                    speculative = src.spec_round >= 0
                    if cfg.fence_speculation and speculative:
                        # PR 3 fence: the heal WAITS until the source's
                        # vote resolves — the action is disabled, not
                        # taken (resolve of that vote re-enables it)
                        continue
                    sourced = True
                    lineage = src.lineage
                    step = src.step
                    if speculative:
                        # fence off: the staged state illegally carries
                        # the un-voted provisional update
                        lineage = lineage + (src.spec_token,)
                        step += 1
                    healed = r._replace(
                        step=step, lineage=lineage, residual=step,
                        healed=True, status=HEALING,
                    )
                    label = f"heal({i}<-{j})" + (
                        "!spec" if speculative else ""
                    )
                    out.append((label, _replace(state, i, healed)))
                # -- heal_fail: transfers can fail (torn stream, source
                # shutdown) and a fenced-out heal eventually times out:
                # the healer latches the error and its barrier vote
                # abstains — its own step aborts, nobody else's does
                if not sourced and not r.voted:
                    ns = _replace(
                        state, i,
                        r._replace(voted=True, abstain=True),
                    )
                    ns = _set_round(
                        ns, rnd._replace(votes=rnd.votes + ((i, ""),))
                    )
                    out.append((f"heal_fail({i})", ns))

            # -- work: compute this round's reduction. A replica with a
            # still-unresolved speculative vote resolves it before
            # issuing the next step's ops (resolve_pending_commit
            # precedes collectives), so work is gated on spec_round < 0.
            ready = (not r.healer) or r.healed
            if (
                r.round == rnd.rid and ready and not r.worked
                and not r.voted and r.spec_round < 0
            ):
                with_done = _set_round(
                    state, rnd._replace(done=rnd.done | {i})
                )
                ns = _replace(with_done, i, r._replace(worked=True))
                out.append((f"work({i})", ns))
                if state.corrupt_budget > 0 and not r.healer:
                    ns2 = _replace(
                        with_done, i,
                        r._replace(worked=True, diverged=True),
                        corrupt_budget=state.corrupt_budget - 1,
                    )
                    out.append((f"work_corrupt({i})", ns2))

            # -- vote: cast this round's commit vote (with the state
            # digest riding it — the token). The token's step is the
            # REPLICA's committed step at vote time (the vote RPC's
            # rec.step), not the round label: a replica whose previous
            # speculation was vetoed legitimately re-attempts its
            # rolled-back step inside a round labeled one ahead
            # (manager.py start_quorum's "a veto makes that step's
            # label one ahead" comment).
            if r.round == rnd.rid and r.worked and not r.voted:
                token = _token(
                    r.step, r.diverged and not r.healer, rnd.epoch
                )
                if cfg.speculation and not r.healer:
                    # pipelined: apply the update provisionally, vote,
                    # and float free to start the next step while the
                    # vote is in flight
                    spec = r._replace(
                        voted=True, status=SPECULATING,
                        spec_round=rnd.rid, spec_token=token,
                        residual=r.step + 1,  # error-feedback applied
                        round=-1,
                    )
                    ns = _replace(state, i, spec)
                    ns = _set_round(
                        ns, rnd._replace(votes=rnd.votes + ((i, token),))
                    )
                    out.append((f"vote_spec({i})", ns))
                else:
                    ns = _replace(state, i, r._replace(voted=True))
                    ns = _set_round(
                        ns, rnd._replace(votes=rnd.votes + ((i, token),))
                    )
                    out.append((f"vote({i})", ns))

            # -- resolve: this replica's vote decision lands. Commit is
            # arbitrated PER replica group; the divergence fence is the
            # only fleet-global wait (the cohort digest compare).
            cast = (
                (r.round == rnd.rid and r.voted)
                or r.spec_round == rnd.rid
            )
            if cast:
                unresolved = [
                    j for j in rnd.members if j not in rnd.resolved
                ]
                accounted = all(
                    (not _attached(state, rnd, j))
                    or state.replicas[j].status == DEAD
                    or any(v[0] == j for v in rnd.votes)
                    for j in unresolved
                )
                if cfg.fence_divergence and not accounted:
                    continue  # fence: wait for the full cohort's digests
                out.append(_resolve(state, cfg, rnd, i))

    return out


def _resolve(
    state: State, cfg: SpecConfig, rnd: Round, i: int
) -> Tuple[str, State]:
    r = state.replicas[i]
    was_spec = r.spec_round == rnd.rid

    # a member that disappeared BEFORE its collective contribution
    # landed broke the survivors' allreduce: their ops errored, the
    # error latched, their steps abort. A member that died after
    # contributing (work done), cast its vote (incl. a failed-heal
    # abstention — its ranks still rode the plane with zeros), or
    # already resolved fails nobody — commits are per-group; the dead
    # group simply respawns behind and heals.
    lost = any(
        j not in rnd.done
        and j not in rnd.resolved
        and not any(v[0] == j for v in rnd.votes)
        and (state.replicas[j].status == DEAD
             or not _attached(state, rnd, j))
        for j in rnd.members
    )
    # the divergence fence: compare the cast digests within MY (epoch,
    # step) cohort — the lighthouse keys its compare on (epoch, step),
    # so votes for a different step never enter it; abstains ("")
    # complete the cohort but never enter the comparison
    my_step = state.replicas[i].step
    tokens = {
        t for _j, t in rnd.votes
        if t and t[1:].split("@", 1)[0] == str(my_step)
    }
    diverged = len(tokens) > 1
    latched = state.divergence_latched
    my_token = r.spec_token if was_spec else next(
        (t for j, t in rnd.votes if j == i), ""
    )
    commit = bool(my_token) and not r.abstain and not lost
    if diverged and cfg.fence_divergence:
        commit = False
        latched = True

    if commit:
        new_step = r.step + 1
        lineage = r.lineage + (my_token,)
        if was_spec:
            # resolve the speculation in place: the replica may already
            # be a member of the NEXT round — leave that round's
            # bookkeeping (round/voted/worked) untouched
            rep = r._replace(
                status=(HEALTHY if r.status == SPECULATING else r.status),
                step=new_step, lineage=lineage, residual=new_step,
                spec_round=-1, spec_token="",
            )
        else:
            rep = r._replace(
                status=HEALTHY, step=new_step, lineage=lineage,
                residual=new_step, round=-1, voted=False, abstain=False,
                worked=False, diverged=False, healer=False, healed=False,
            )
        commits = _commit_record(state.commits, r.step, my_token)
    else:
        residual = r.step
        if was_spec and not cfg.rollback_residual:
            residual = r.step + 1  # the planted PR 6 bug
        if was_spec:
            rep = r._replace(
                status=(HEALTHY if r.status == SPECULATING else r.status),
                residual=residual, spec_round=-1, spec_token="",
            )
        else:
            rep = r._replace(
                status=HEALTHY, round=-1, voted=False, abstain=False,
                worked=False, diverged=False, healer=False,
                # an aborted heal is discarded with the step: the healer
                # stays behind until a committing round
                healed=False,
                residual=residual,
            )
        commits = state.commits

    ns = _replace(state, i, rep, commits=commits,
                  divergence_latched=latched)
    ns = _set_round(ns, rnd._replace(resolved=rnd.resolved | {i}))
    verdict = "commit" if commit else "abort"
    return (f"resolve({i},r{rnd.rid},{verdict})", ns)


# ---------------------------------------------------------------------------
# invariants
# ---------------------------------------------------------------------------


def check_state(
    state: State, cfg: SpecConfig, action: str = ""
) -> List[Invariant]:
    """Safety invariants, checked at every visited state."""
    out: List[Invariant] = []

    # I1: at most one committed lineage per step, fleet-wide
    for step, tokens in state.commits:
        if len(tokens) > 1:
            out.append(Invariant(
                "I1-unique-commit",
                f"step {step} committed {len(tokens)} distinct lineages "
                f"{list(tokens)} — split brain or silently diverged "
                "commit",
            ))

    # I3: a heal action that copied speculative state is labeled !spec
    if action.startswith("heal(") and action.endswith("!spec"):
        out.append(Invariant(
            "I3-healer-fence",
            f"{action}: the healer copied a SPECULATING source's state — "
            "an un-voted optimizer update leaked into a served "
            "checkpoint (PR 3 fence violated)",
        ))

    # I4: residual version == the step the replica's state encodes
    for i, r in enumerate(state.replicas):
        if r.status == DEAD:
            continue
        expect = _provisional_step(r)
        if r.residual != expect:
            out.append(Invariant(
                "I4-residual-rollback",
                f"replica {i}: error-feedback residual v{r.residual} but "
                f"state encodes step {expect} — a vetoed speculative "
                "update left the residual un-rolled-back (PR 6)",
            ))
        if len(r.lineage) != r.step:
            out.append(Invariant(
                "I4-residual-rollback",
                f"replica {i}: lineage length {len(r.lineage)} != "
                f"committed step {r.step}",
            ))

    # I5: a DETECTED divergence never commits while the fence is armed.
    # (A single-member cohort committing a corrupt state is invisible to
    # any digest compare — the sentinel's contract, like the real one's,
    # covers disagreement, which needs two states to disagree.)
    if cfg.fence_divergence:
        for step, tokens in state.commits:
            if len(tokens) > 1 and any(t.startswith("x") for t in tokens):
                out.append(Invariant(
                    "I5-diverged-commit",
                    f"step {step} committed disagreeing tokens "
                    f"{list(tokens)} with the divergence fence armed — "
                    "the cohort compare must have vetoed this",
                ))

    # I2: epochs only increment (structural in the model; the
    # conformance checker enforces it on real trails)
    for i, r in enumerate(state.replicas):
        if r.epoch > state.epoch:
            out.append(Invariant(
                "I2-epoch-monotonic",
                f"replica {i} observed epoch {r.epoch} beyond the "
                f"lighthouse's {state.epoch}",
            ))

    return out


def is_terminal(state: State, cfg: SpecConfig) -> bool:
    return not enabled_actions(state, cfg)


def check_terminal(state: State, cfg: SpecConfig) -> List[Invariant]:
    """Liveness-ish: a terminal state with a quorum's worth of live
    replicas must have committed something."""
    live = _live(state)
    if len(live) >= cfg.min_replicas and cfg.max_rounds > 0:
        if not state.commits:
            return [Invariant(
                "L-liveness",
                f"terminal state with {len(live)} live replicas "
                f"(min_replicas={cfg.min_replicas}) committed nothing "
                f"in {cfg.max_rounds} rounds",
            )]
    return []
