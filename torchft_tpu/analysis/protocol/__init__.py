"""Executable specification of the FT protocol + its three consumers.

The per-step quorum → vote → commit/abort/heal lifecycle (Lighthouse
quorum + Manager arbitration — ``manager.py`` / ``coordination.py`` /
``native/coord.cc``) is the one protocol the paper's value rests on, and
until now its only proofs were dynamic: faultmatrix scenarios sample
interleavings, sanitizers sample executions. This package is the
machine-checked side:

* :mod:`~torchft_tpu.analysis.protocol.spec` — the protocol as an
  explicit state machine: replica states (JOINING / HEALTHY / HEALING /
  SPECULATING / DEAD), lighthouse epoch rounds, vote folding, the
  speculation fence (PR 3), error-feedback lineage rollback (PR 6) and
  the divergence fence (PR 10), with the core invariants as checkable
  predicates;
* :mod:`~torchft_tpu.analysis.protocol.checker` — a deterministic DFS
  model checker that exhaustively explores bounded configurations with a
  crash injected at every transition point (the SIGKILL-anywhere
  semantics faultinject implements dynamically);
* :mod:`~torchft_tpu.analysis.protocol.conformance` — replays real FT
  event trails and black-box records against the spec's event-level
  transition rules, flagging any illegal transition (wired into
  ``postmortem --conformance`` and the faultmatrix runner).

CLI: ``python -m torchft_tpu.analysis.protocol`` (model-check the gate
configurations; ``--conformance DIR`` additionally replays every trail
under DIR). See ``docs/static_analysis.md`` "Protocol verification".
"""

from torchft_tpu.analysis.protocol.spec import (
    CANDIDATE,
    DEAD,
    FOLLOWER,
    HEALING,
    HEALTHY,
    JOINING,
    LEADER,
    SPECULATING,
    Invariant,
    SpecConfig,
)
from torchft_tpu.analysis.protocol.checker import (
    GATE_CONFIGS,
    HA_STATE_BUDGETS,
    CheckResult,
    check,
)
from torchft_tpu.analysis.protocol.conformance import (
    check_records,
    check_trail_file,
    check_tree,
)

__all__ = [
    "JOINING",
    "HEALTHY",
    "HEALING",
    "SPECULATING",
    "DEAD",
    "FOLLOWER",
    "CANDIDATE",
    "LEADER",
    "Invariant",
    "SpecConfig",
    "CheckResult",
    "check",
    "GATE_CONFIGS",
    "HA_STATE_BUDGETS",
    "check_records",
    "check_trail_file",
    "check_tree",
]
