"""CLI: ``python -m torchft_tpu.analysis.protocol``.

Two halves, one exit code (premerge gate [5]):

* **model check** (default) — exhaustively explore every gate
  configuration (:data:`~torchft_tpu.analysis.protocol.checker.GATE_CONFIGS`)
  with a crash injected at every transition point; any invariant
  violation prints its action trace and fails the gate.
* **conformance replay** (``--conformance DIR``, repeatable) — replay
  every event trail / black box under DIR against the spec's event-level
  transition rules; any illegal transition fails the gate.

Exit codes: 0 clean, 1 violations/illegal transitions, 2 crash.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

from torchft_tpu.analysis.protocol.checker import (
    GATE_CONFIGS,
    HA_STATE_BUDGETS,
    check,
)
from torchft_tpu.analysis.protocol.conformance import check_tree


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="torchft_tpu.analysis.protocol",
        description="FT-protocol verification gate: exhaustive bounded "
        "model check + trace-conformance replay",
    )
    ap.add_argument("--conformance", action="append", default=[],
                    metavar="DIR",
                    help="also replay every trail/black box under DIR "
                    "(repeatable)")
    ap.add_argument("--config", action="append", default=None,
                    choices=sorted(GATE_CONFIGS),
                    help="model-check only these gate configs")
    ap.add_argument("--skip-model", action="store_true",
                    help="conformance replay only")
    ap.add_argument("--no-por", action="store_true",
                    help="disable partial-order reduction (exhaustive "
                    "reference mode half 1)")
    ap.add_argument("--no-symmetry", action="store_true",
                    help="disable symmetry reduction (reference half 2)")
    ap.add_argument("--bitstate", action="store_true",
                    help="64-bit bitstate hashing: cheaper visited set, "
                    "APPROXIMATE coverage — never a gate verdict")
    ap.add_argument("--max-states", type=int, default=None,
                    help="override the per-config state budget")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    report = {"model": {}, "conformance": {}, "ok": True}
    try:
        if not args.skip_model:
            names = args.config or sorted(GATE_CONFIGS)
            for name in names:
                t0 = time.time()
                budget = args.max_states or HA_STATE_BUDGETS.get(
                    name, 2_000_000
                )
                res = check(
                    GATE_CONFIGS[name],
                    max_states=budget,
                    por=not args.no_por,
                    symmetry=not args.no_symmetry,
                    bitstate=args.bitstate,
                )
                report["model"][name] = {
                    "states": res.states,
                    "transitions": res.transitions,
                    "budget": budget,
                    "violations": [
                        {"invariant": v.invariant, "detail": v.detail,
                         "trace": v.trace}
                        for v in res.violations
                    ],
                    "truncated": res.truncated,
                    "truncated_states": res.truncated_states,
                    "truncated_transitions": res.truncated_transitions,
                    "approximate": res.approximate,
                    "seconds": round(time.time() - t0, 2),
                }
                if not args.as_json:
                    print(
                        f"model {name}: {res.states} states, "
                        f"{res.transitions} transitions, "
                        f"{len(res.violations)} violation(s) "
                        f"[{report['model'][name]['seconds']}s]"
                    )
                    if res.truncated:
                        print(
                            f"  TRUNCATED: budget {budget} hit — "
                            f"{res.truncated_states} frontier state(s) "
                            f"and {res.truncated_transitions} enabled "
                            "action(s) never explored; NOT a clean "
                            "verdict"
                        )
                    if res.approximate:
                        print(
                            "  APPROXIMATE: bitstate hashing on — a "
                            "hash collision silently prunes coverage; "
                            "exploratory only, never a gate verdict"
                        )
                    for v in res.violations:
                        print("  " + v.render())
                report["ok"] = report["ok"] and res.ok
        for root in args.conformance:
            rep = check_tree(root)
            report["conformance"][root] = {
                "sources": rep.sources,
                "records": rep.records,
                "lifecycle_records": rep.lifecycle_records,
                "findings": [f.__dict__ for f in rep.findings],
            }
            if not args.as_json:
                print(rep.render())
            report["ok"] = report["ok"] and rep.ok
    except Exception as e:  # noqa: BLE001 — checker crash is exit 2
        print(f"protocol gate failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps(report, indent=2))
    elif report["ok"]:
        print("protocol gate clean")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
