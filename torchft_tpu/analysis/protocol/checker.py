"""Bounded model checker for the FT-protocol spec, with reductions.

Plain explicit-state depth-first search with a visited set: every
interleaving of every enabled transition — including the crash action,
which :func:`~torchft_tpu.analysis.protocol.spec.enabled_actions` offers
at every transition point (SIGKILL-anywhere) — is explored. Safety
invariants are evaluated at every visited state; the liveness check at
every terminal state. A violation comes back with the full action trace
from the initial state, so a red check reads like a reproduction recipe,
not a boolean — and :mod:`~torchft_tpu.analysis.protocol.compile` lowers
that trace into a runnable faultinject schedule.

The HA lighthouse tier (ISSUE 20) multiplies the state space far past
what plain DFS can exhaust in a premerge budget, so the checker carries
three *sound* reductions and one loud approximation:

* **Partial-order reduction** (``por=True``): when a *pure-local* action
  is enabled whose effects commute with every other enabled action and
  are invisible to every invariant, the checker expands only that action
  and defers the rest (they stay enabled in the successor). Two action
  families qualify, each under the precondition that makes it safe:
  ``join`` (only with the join barrier on — ``form`` is then disabled
  until every live replica joined, and a join erased by a later crash
  collapses to the crash alone) and ``work`` (only once the crash AND
  corrupt budgets are spent — a pending ``crash(i)``/``work_corrupt(i)``
  does *not* commute with ``work(i)``: dying before vs. after the
  contribution changes the survivors' ``lost`` verdict).
* **State canonicalization**: visited-set keys are rendered through a
  normal form that (a) sorts each round's cast-vote tuple (every reader
  is order-insensitive), (b) collapses *closed* rounds — every member
  resolved or permanently detached — to their identity (no enabled
  action or invariant reads a closed round's bookkeeping), and
  (c) scrubs dead replicas' membership view (a respawn rebuilds it from
  the snapshot). The checker still explores REAL states — only the
  dedup key is canonical — so violation traces stay executable.
* **Symmetry reduction** (``symmetry=True``): interchangeable replica
  groups (and lighthouse replicas) are quotiented by taking the
  lexicographically-least rendering over index permutations. Sound
  because the transition relation is index-uniform: a permuted state's
  behaviour is the permutation of the original's.
* **Bitstate hashing** (``bitstate=True``): the visited set stores 64-bit
  digests instead of renderings. A hash collision silently *prunes* an
  unexplored subtree, so coverage becomes APPROXIMATE — the result is
  marked ``approximate`` and every front end prints it loudly. Off by
  default; for exploratory sweeps of configs beyond the gate budget.

Budgets: ``max_states`` / ``max_transitions`` cap the search; hitting a
cap sets ``truncated`` and the explicit counters ``truncated_states``
(frontier states never expanded) / ``truncated_transitions`` (enabled
actions never taken), so "the check passed" can never silently mean
"the check gave up".

The four single-lighthouse gate configurations verify with verdicts
identical to the exhaustive run at >5x fewer explored states under
POR+symmetry (asserted by tests/test_protocol.py); the HA gate configs
verify clean within the stated budgets in ``HA_STATE_BUDGETS``.
"""

from __future__ import annotations

import hashlib
import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from torchft_tpu.analysis.protocol.spec import (
    DEAD,
    Invariant,
    Round,
    SpecConfig,
    State,
    check_state,
    check_terminal,
    enabled_actions,
    init_state,
)

__all__ = [
    "CheckResult", "Violation", "check", "GATE_CONFIGS",
    "HA_STATE_BUDGETS",
]


@dataclass
class Violation:
    invariant: str
    detail: str
    trace: List[str]  # action labels from the initial state

    def render(self) -> str:
        path = " -> ".join(self.trace) if self.trace else "<initial>"
        return f"[{self.invariant}] {self.detail}\n    trace: {path}"


@dataclass
class CheckResult:
    config: SpecConfig
    states: int = 0
    transitions: int = 0
    terminals: int = 0
    violations: List[Violation] = field(default_factory=list)
    truncated: bool = False          # a state/transition budget was hit
    truncated_states: int = 0        # frontier states never expanded
    truncated_transitions: int = 0   # enabled actions never taken
    pruned_actions: int = 0          # actions deferred by POR
    approximate: bool = False        # bitstate: coverage NOT exhaustive

    @property
    def ok(self) -> bool:
        return not self.violations and not self.truncated


# ---------------------------------------------------------------------------
# canonicalization: the visited-set normal form
# ---------------------------------------------------------------------------


def _round_closed(state: State, rnd: Round) -> bool:
    """A round no enabled action and no invariant will ever read again:
    every member either resolved its vote or is permanently detached
    (crashed out / floated away — old round ids are never re-attached)."""
    for j in rnd.members:
        if j in rnd.resolved:
            continue
        r = state.replicas[j]
        if r.round == rnd.rid or r.spec_round == rnd.rid:
            return False
    return True


def _render(
    state: State,
    rperm: Tuple[int, ...],
    lperm: Tuple[int, ...],
) -> tuple:
    """One fully-ordered rendering of ``state`` under a replica-index
    permutation ``rperm`` and a lighthouse-index permutation ``lperm``
    (old index -> new index). Frozensets become sorted tuples so
    renderings are totally ordered; the identity permutation's
    rendering is itself a faithful state key."""
    rmap = rperm.__getitem__

    reps: List[tuple] = [()] * len(state.replicas)
    for i, r in enumerate(state.replicas):
        if r.status == DEAD:
            # volatile-on-respawn fields: a respawn rebuilds the
            # membership view from the snapshot, so two dead states
            # differing only there are bisimilar
            mview, view = 0, ()
        else:
            mview, view = r.mview, tuple(sorted(rmap(x) for x in r.view))
        reps[rmap(i)] = (
            r.status, r.step, r.lineage, r.residual, r.joined, r.round,
            r.voted, r.abstain, r.worked, r.diverged, r.healer,
            r.healed, r.spec_round, r.spec_token, r.epoch, mview, view,
        )

    rounds: List[tuple] = []
    for rnd in state.rounds:
        if _round_closed(state, rnd):
            rounds.append((rnd.rid, rnd.epoch, "closed"))
        else:
            rounds.append((
                rnd.rid, rnd.epoch, rnd.step,
                tuple(sorted(rmap(m) for m in rnd.members)),
                tuple(sorted((rmap(m), t) for m, t in rnd.votes)),
                tuple(sorted(rmap(m) for m in rnd.resolved)),
                tuple(sorted(rmap(m) for m in rnd.done)),
                rnd.mver,
            ))

    lmap = lperm.__getitem__
    lhs: List[tuple] = [()] * len(state.lighthouses)
    for i, lh in enumerate(state.lighthouses):
        lhs[lmap(i)] = (
            lh.status, lh.term,
            (lmap(lh.voted_for) if lh.voted_for >= 0 else -1),
            tuple(sorted(lmap(v) for v in lh.votes)),
            lh.log, lh.commit_len, lh.cell,
        )

    return (
        tuple(reps), tuple(rounds),
        tuple(sorted(rmap(i) for i in state.open_round)),
        state.epoch, state.rounds_formed,
        state.crash_budget, state.respawn_budget, state.corrupt_budget,
        state.commits, state.divergence_latched,
        tuple(lhs), state.ha_committed,
        state.lh_crash_budget, state.lh_respawn_budget,
        state.partition_budget,
        state.mversion,
        tuple((v, rmap(rep), a) for v, rep, a in state.mlog),
        tuple(
            (s.status, tuple(sorted(rmap(x) for x in s.owns)))
            for s in state.subaggs
        ),
        state.subagg_budget,
    )


def _perm_sets(
    cfg: SpecConfig, symmetry: bool
) -> Tuple[List[Tuple[int, ...]], List[Tuple[int, ...]]]:
    rid = tuple(range(cfg.n_replicas))
    lid = tuple(range(cfg.n_lighthouses if cfg.n_lighthouses >= 2 else 0))
    if not symmetry:
        return [rid], [lid]
    # factorials past 4 cost more than they merge; fall back to identity
    rperms = (
        [tuple(p) for p in itertools.permutations(rid)]
        if 2 <= cfg.n_replicas <= 4 else [rid]
    )
    lperms = (
        [tuple(p) for p in itertools.permutations(lid)]
        if 2 <= len(lid) <= 4 else [lid]
    )
    return rperms, lperms


# ---------------------------------------------------------------------------
# partial-order reduction: the ample-action selector
# ---------------------------------------------------------------------------


def _por_select(
    state: State, cfg: SpecConfig,
    actions: List[Tuple[str, State]],
) -> List[Tuple[str, State]]:
    """Return the subset of ``actions`` to expand. Picks a single safe
    pure-local action when one exists (see the module docstring for the
    commutation argument); otherwise everything."""
    # joins commute pairwise and with every non-form action; with the
    # barrier on, form is disabled until no join is enabled, and a
    # join erased by a later crash equals the crash alone
    if cfg.join_barrier:
        for a in actions:
            if a[0].startswith("join("):
                return [a]
    # work(i) commutes with everything EXCEPT crash(i) (dying before
    # vs. after contributing flips the survivors' `lost` verdict) and
    # work_corrupt(i) (the same replica's branching choice) — both
    # excluded by requiring the budgets already spent
    if state.crash_budget == 0 and state.corrupt_budget == 0:
        for a in actions:
            if a[0].startswith("work("):
                return [a]
    return actions


# ---------------------------------------------------------------------------
# the search
# ---------------------------------------------------------------------------


def check(
    cfg: SpecConfig,
    max_states: int = 2_000_000,
    max_violations: int = 16,
    *,
    por: bool = True,
    symmetry: bool = True,
    bitstate: bool = False,
    max_transitions: Optional[int] = None,
) -> CheckResult:
    """Explore ``cfg``; returns states visited + violations (each with
    its executable action trace). ``por=False, symmetry=False`` is the
    exhaustive reference mode the reductions are validated against.

    Collecting ``max_violations`` violations stops the search early
    (marked ``truncated`` — exploration was incomplete, but the verdict
    is already red); pass ``max_violations=1`` for a fast fail-on-first
    run over a known-broken config."""
    res = CheckResult(config=cfg, approximate=bitstate)
    root = init_state(cfg)
    rperms, lperms = _perm_sets(cfg, symmetry)

    def key_of(state: State):
        k = min(
            _render(state, rp, lp)
            for rp in rperms for lp in lperms
        )
        if bitstate:
            return hashlib.blake2b(
                repr(k).encode(), digest_size=8
            ).digest()
        return k

    # parent pointers for trace reconstruction (state -> (prev, action))
    parent: Dict[State, Optional[Tuple[State, str]]] = {root: None}
    stack: List[State] = [root]
    seen = {key_of(root)}

    def trace_of(state: State, extra: Optional[str] = None) -> List[str]:
        labels: List[str] = []
        cur: Optional[State] = state
        while cur is not None:
            link = parent[cur]
            if link is None:
                break
            prev, action = link
            labels.append(action)
            cur = prev
        labels.reverse()
        if extra:
            labels.append(extra)
        return labels

    def record(inv: Invariant, state: State,
               extra: Optional[str] = None) -> None:
        if len(res.violations) >= max_violations:
            return
        res.violations.append(
            Violation(inv.name, inv.detail, trace_of(state, extra))
        )

    for inv in check_state(root, cfg):
        record(inv, root)

    while stack:
        if len(res.violations) >= max_violations:
            # verdict is already red; stop burning budget on more paths
            res.truncated = True
            res.truncated_states = len(stack)
            break
        state = stack.pop()
        res.states += 1
        if res.states > max_states:
            res.truncated = True
            res.truncated_states = len(stack) + 1
            break
        actions = enabled_actions(state, cfg)
        if not actions:
            res.terminals += 1
            for inv in check_terminal(state, cfg):
                record(inv, state)
            continue
        if por:
            expand = _por_select(state, cfg, actions)
            res.pruned_actions += len(actions) - len(expand)
        else:
            expand = actions
        for label, nxt in expand:
            if (
                max_transitions is not None
                and res.transitions >= max_transitions
            ):
                res.truncated = True
                res.truncated_transitions += 1
                continue
            res.transitions += 1
            # action-labelled invariants (the heal-fence and stale-view
            # checks key on the transition itself) are evaluated on the
            # SUCCESSOR with the action attached, even when the
            # successor was already reached by a benign path
            for inv in check_state(nxt, cfg, action=label):
                # dedupe identical (invariant, detail) repeats — one
                # trace per distinct violation is plenty
                if not any(
                    v.invariant == inv.name and v.detail == inv.detail
                    for v in res.violations
                ):
                    record(inv, state, extra=label)
            k = key_of(nxt)
            if k not in seen:
                seen.add(k)
                parent[nxt] = (state, label)
                stack.append(nxt)
    return res


# The repo-gate configurations (premerge gate [6] + tier-1 wrapper):
# every one of these must come back clean. The broken variants live in
# tests/fixtures/analysis/ as seeded fixtures, not here.
GATE_CONFIGS: Dict[str, SpecConfig] = {
    # the shipped sync protocol, 2 groups, a crash anywhere + respawn
    "sync-2g": SpecConfig(
        n_replicas=2, min_replicas=1, max_rounds=3,
        crash_budget=1, respawn_budget=1,
    ),
    # pipelined commit: speculation + the PR 3 fence, crash anywhere
    "pipelined-2g": SpecConfig(
        n_replicas=2, min_replicas=1, max_rounds=3,
        crash_budget=1, respawn_budget=1, speculation=True,
    ),
    # divergence fence armed against a silently-corrupting compute
    "divergence-fenced-2g": SpecConfig(
        n_replicas=2, min_replicas=1, max_rounds=3,
        crash_budget=1, respawn_budget=1, corrupt_budget=1,
    ),
    # three groups, shipped protocol (wider interleavings, quick config)
    "sync-3g": SpecConfig(
        n_replicas=3, min_replicas=2, max_rounds=3,
        crash_budget=1, respawn_budget=1,
    ),
    # --- the HA tier (ISSUE 20). The replica-group protocol is carried
    # by the four configs above; these stress the lighthouse tier, so
    # the group side stays minimal to keep the product space honest.
    # leader SIGKILLed mid-epoch, durable-log respawn, one re-election
    "ha-leader-crash": SpecConfig(
        n_replicas=1, min_replicas=1, max_rounds=2,
        n_lighthouses=3, lh_crash_budget=1, lh_respawn_budget=1,
        max_terms=2,
    ),
    # the leader isolated by a network split; majority side re-elects;
    # the stale leader keeps serving joins but can never commit
    "ha-partition-reelect": SpecConfig(
        n_replicas=1, min_replicas=1, max_rounds=2,
        n_lighthouses=3, partition_budget=1, max_terms=2,
    ),
    # versioned membership deltas under crash+respawn churn: in-order
    # apply, loss -> gap-detect -> full-snapshot resync, stale-view
    # fence on the commit vote
    "ha-delta-resync": SpecConfig(
        n_replicas=2, min_replicas=1, max_rounds=3,
        crash_budget=1, respawn_budget=1, membership_deltas=True,
    ),
    # two-level quorum tree: a sub-aggregator crash drops its buffered
    # joins, the groups re-home and re-join — epochs never split
    "ha-subagg-crash": SpecConfig(
        n_replicas=3, min_replicas=2, max_rounds=2,
        n_subaggs=2, subagg_crash_budget=1,
    ),
}

# The stated exploration budget per HA gate config (acceptance: clean
# within these bounds — a config outgrowing its budget fails loudly via
# `truncated` instead of silently passing on partial coverage).
HA_STATE_BUDGETS: Dict[str, int] = {
    "ha-leader-crash": 600_000,
    "ha-partition-reelect": 600_000,
    "ha-delta-resync": 400_000,
    "ha-subagg-crash": 400_000,
}
