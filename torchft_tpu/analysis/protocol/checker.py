"""Exhaustive bounded model checker for the FT-protocol spec.

Plain explicit-state depth-first search with a visited set: every
interleaving of every enabled transition — including the crash action,
which :func:`~torchft_tpu.analysis.protocol.spec.enabled_actions` offers
at every transition point (SIGKILL-anywhere) — is explored exactly once.
Safety invariants are evaluated at every visited state; the liveness
check at every terminal state. A violation comes back with the full
action trace from the initial state, so a red check reads like a
reproduction recipe, not a boolean.

The bounded configurations the repo gate runs (2–3 replica groups ×
3 rounds × 1 crash) explore a few thousand to a few hundred thousand
states in well under a minute — small enough for premerge, exhaustive
enough that the PR 3/6/10 protections each flip a violation when
disabled (the seeded-fixture tests assert both directions).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from torchft_tpu.analysis.protocol.spec import (
    Invariant,
    SpecConfig,
    State,
    check_state,
    check_terminal,
    enabled_actions,
    init_state,
)

__all__ = ["CheckResult", "Violation", "check", "GATE_CONFIGS"]


@dataclass
class Violation:
    invariant: str
    detail: str
    trace: List[str]  # action labels from the initial state

    def render(self) -> str:
        path = " -> ".join(self.trace) if self.trace else "<initial>"
        return f"[{self.invariant}] {self.detail}\n    trace: {path}"


@dataclass
class CheckResult:
    config: SpecConfig
    states: int = 0
    transitions: int = 0
    terminals: int = 0
    violations: List[Violation] = field(default_factory=list)
    truncated: bool = False  # state cap hit (never in the gate configs)

    @property
    def ok(self) -> bool:
        return not self.violations and not self.truncated


def check(
    cfg: SpecConfig,
    max_states: int = 2_000_000,
    max_violations: int = 16,
) -> CheckResult:
    """Exhaustively explore ``cfg``; returns states visited + violations
    (each with its action trace)."""
    res = CheckResult(config=cfg)
    root = init_state(cfg)
    # parent pointers for trace reconstruction (state -> (prev, action))
    parent: Dict[State, Optional[Tuple[State, str]]] = {root: None}
    stack: List[State] = [root]
    seen = {root}

    def trace_of(state: State, extra: Optional[str] = None) -> List[str]:
        labels: List[str] = []
        cur: Optional[State] = state
        while cur is not None:
            link = parent[cur]
            if link is None:
                break
            prev, action = link
            labels.append(action)
            cur = prev
        labels.reverse()
        if extra:
            labels.append(extra)
        return labels

    def record(inv: Invariant, state: State,
               extra: Optional[str] = None) -> None:
        if len(res.violations) >= max_violations:
            return
        res.violations.append(
            Violation(inv.name, inv.detail, trace_of(state, extra))
        )

    for inv in check_state(root, cfg):
        record(inv, root)

    while stack:
        state = stack.pop()
        res.states += 1
        if res.states > max_states:
            res.truncated = True
            break
        actions = enabled_actions(state, cfg)
        if not actions:
            res.terminals += 1
            for inv in check_terminal(state, cfg):
                record(inv, state)
            continue
        for label, nxt in actions:
            res.transitions += 1
            # action-labelled invariants (the heal-fence check keys on
            # the transition itself) are evaluated on the SUCCESSOR with
            # the action attached, even when the successor was already
            # reached by a benign path
            for inv in check_state(nxt, cfg, action=label):
                # dedupe identical (invariant, detail) repeats — one
                # trace per distinct violation is plenty
                if not any(
                    v.invariant == inv.name and v.detail == inv.detail
                    for v in res.violations
                ):
                    record(inv, state, extra=label)
            if nxt not in seen:
                seen.add(nxt)
                parent[nxt] = (state, label)
                stack.append(nxt)
    return res


# The repo-gate configurations (premerge gate [5] + tier-1 wrapper):
# every one of these must come back clean. The broken variants live in
# tests/fixtures/analysis/ as seeded fixtures, not here.
GATE_CONFIGS: Dict[str, SpecConfig] = {
    # the shipped sync protocol, 2 groups, a crash anywhere + respawn
    "sync-2g": SpecConfig(
        n_replicas=2, min_replicas=1, max_rounds=3,
        crash_budget=1, respawn_budget=1,
    ),
    # pipelined commit: speculation + the PR 3 fence, crash anywhere
    "pipelined-2g": SpecConfig(
        n_replicas=2, min_replicas=1, max_rounds=3,
        crash_budget=1, respawn_budget=1, speculation=True,
    ),
    # divergence fence armed against a silently-corrupting compute
    "divergence-fenced-2g": SpecConfig(
        n_replicas=2, min_replicas=1, max_rounds=3,
        crash_budget=1, respawn_budget=1, corrupt_budget=1,
    ),
    # three groups, shipped protocol (wider interleavings, quick config)
    "sync-3g": SpecConfig(
        n_replicas=3, min_replicas=2, max_rounds=3,
        crash_budget=1, respawn_budget=1,
    ),
}
